"""The registrar orchestrator: registration + heartbeat + health checking.

Rebuild of the reference's default export ``register_plus``
(lib/index.js:33-182).  Ties the three subsystems together and exposes a
lifecycle event surface:

    register(znodes)           registration (or re-registration) completed
    unregister(err, znodes)    health check declared down; znodes holds what
                               was actually deleted (a shared service node
                               with sibling hosts under it stays, and is
                               not listed)
    heartbeat(znodes)          periodic znode liveness probe succeeded
    heartbeatFailure(err)      probe failed after bounded retries
    ok()                       health check recovered (was down)
    fail(err)                  health check crossed the failure threshold
    error(err)                 unexpected error from any subsystem

Loop behavior matches the reference exactly (BASELINE.md):

  * heartbeat every ``heartbeat_interval`` (default 3 s,
    lib/index.js:132), re-armed *after* each probe completes (the
    reference's self-rescheduling setTimeout chain, not a fixed-rate timer);
  * after a heartbeat failure the loop backs off to
    ``max(heartbeat_interval, 60 s)`` (lib/index.js:146);
  * a heartbeat failure does NOT deregister or exit — recovery rides on ZK
    session expiry + supervisor restart, or a health-check ``ok``
    re-registration (SURVEY.md §3.2 note).  SURVEY.md §3.2 flags re-creating
    missing ephemerals on heartbeat NO_NODE as a worthwhile but
    behavior-changing improvement: it is available here as the **opt-in**
    ``repair_heartbeat_miss`` flag (config key ``repairHeartbeatMiss``),
    default off for reference parity.  When enabled, a heartbeat that fails
    with NO_NODE re-runs the registration pipeline — unless the health
    checker has deliberately deregistered the host (``ee.down``);
  * on health ``fail`` with ``isDown`` the znodes are unregistered; on the
    next health ``ok`` the full registration pipeline runs again
    (lib/index.js:59-116).

Two opt-in recovery layers ride above the reference loops (ISSUE 3):

  * the ZK client's ``session_reborn`` event (``surviveSessionExpiry``)
    is consumed here — a fresh in-process session has no ephemerals, so
    the idempotent registration pipeline re-runs, honoring ``ee.down``
    (a health-deregistered host is never resurrected by a rebirth);
  * a level-triggered reconciler (:mod:`registrar_tpu.reconcile`, config
    ``reconcile: {intervalSeconds, repair}``) periodically diffs the
    owned znodes against the desired records and emits structured
    ``drift`` / ``driftRepaired`` / ``reconcile`` events — with
    ``repair`` on it converges through the same pipeline.

Every znode-mutating flow (heartbeat repair, rebirth re-registration,
health transitions, reconciler repair) is single-flight through one
``asyncio.Lock``, so two recovery paths can never interleave a cleanup
stage into each other's half-built registration.

Fixed here (reference warts that are unobservable in znode state):
``register_plus`` references an undefined ``cfg`` on initial-registration
failure (lib/index.js:48) — the error path here just emits ``error``; and
re-registration is guarded against overlapping ``ok`` events.
"""

from __future__ import annotations

import asyncio
import logging
import weakref
from typing import Any, List, Mapping, Optional, Sequence

from registrar_tpu import registration as register_mod
from registrar_tpu import trace
from registrar_tpu.events import EventEmitter, spawn_owned
from registrar_tpu.health import HealthCheck, create_health_check
from registrar_tpu.registration import SETTLE_DELAY_S
from registrar_tpu.retry import RetryPolicy
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import Err, ZKError

log = logging.getLogger("registrar_tpu.agent")

#: reference lib/index.js:132
DEFAULT_HEARTBEAT_INTERVAL_S = 3.0
#: reference lib/index.js:146 — floor of the post-failure re-arm delay
HEARTBEAT_FAILURE_BACKOFF_S = 60.0


class RegistrarEvents(EventEmitter):
    """Event surface returned by :func:`register_plus` (the reference's
    EventEmitter + ``.stop()``, lib/index.js:164-171)."""

    def __init__(self) -> None:
        super().__init__()
        self.znodes: list = []
        #: True while the health checker holds the host deregistered —
        #: gates heartbeat repair so it never races a deliberate
        #: deregistration.
        self.down = False
        #: bumped every time a registration pipeline run refreshes
        #: ``znodes``.  Recovery actors queued on the single-flight lock
        #: snapshot it when they DECIDE to repair and skip if it moved by
        #: the time they hold the lock: without this, the loser of the
        #: race re-runs the pipeline over the winner's fresh registration
        #: and its cleanup stage deletes the just-repaired znodes —
        #: re-minting the very drift that queued it (an unbounded
        #: repair tug-of-war between heartbeat repair and the
        #: reconciler; regression: tests/test_e2e_options.py).
        self.epoch = 0
        self._tasks: set = set()
        self._health: Optional[HealthCheck] = None
        self._stopped = False
        #: the level-triggered reconciler, when configured (test/metrics
        #: observability; None without the ``reconcile`` config block)
        self.reconciler = None
        #: bound by _run once the initial registration lands (ISSUE 5):
        #: the SIGHUP hot-reload entry point (see :meth:`reload`)
        self._reload_fn = None
        #: reload bookkeeping: None = the live registration corresponds
        #: to the current params' desired records (every successful
        #: pipeline run resets it).  After a reload delta fails
        #: mid-apply, a ``(base_map, dirty_paths)`` pair: the
        #: desired-record map of the last SUCCESSFUL application plus
        #: the set of paths the failed delta may have half-touched —
        #: the next reload re-diffs from the base and force-rewrites
        #: every dirty path, so neither a retry nor a revert can read
        #: as a hollow "noop" while ZooKeeper holds partial state.
        self._applied_desired = None

    async def reload(self, registration, admin_ip=None) -> str:
        """Hot-apply a new registration/adminIp (SIGHUP, ISSUE 5).

        Diffs the old desired records against the new and applies ONLY
        the delta through the single-flight pipeline lock — unchanged
        znodes are never touched (no delete+recreate blip for names that
        did not change).  Returns ``"applied"`` or ``"noop"``.  Raises
        when the initial registration has not completed yet, or when a
        delta operation fails — by then the agent's desired state has
        already switched to the new config, so the heartbeat/reconciler
        recovery layers converge on it.
        """
        if self._reload_fn is None:
            raise RuntimeError(
                "initial registration has not completed; cannot reload"
            )
        return await self._reload_fn(registration, admin_ip)

    def stop(self) -> None:
        """Stop the heartbeat loop and health checker.

        Does NOT delete the znodes — like the reference, a graceful library
        stop leaves cleanup to ZK session expiry (SURVEY.md §3.4)."""
        self._stopped = True
        if self._health is not None:
            self._health.stop()
        for task in list(self._tasks):
            task.cancel()
        self._tasks.clear()

    def _track(self, coro) -> "asyncio.Task":
        """Spawn ``coro`` as a task owned until done (finished tasks drop
        out, so a daemon with a flapping health check doesn't accumulate
        them forever) and cancelled by stop()."""
        return spawn_owned(coro, self._tasks)

    @property
    def stopped(self) -> bool:
        return self._stopped


def register_plus(
    zk: ZKClient,
    registration: Mapping[str, Any],
    admin_ip: Optional[str] = None,
    health_check: Optional[Mapping[str, Any]] = None,
    heartbeat_interval: float = DEFAULT_HEARTBEAT_INTERVAL_S,
    hostname: Optional[str] = None,
    settle_delay: float = SETTLE_DELAY_S,
    heartbeat_retry: Optional[RetryPolicy] = None,
    repair_heartbeat_miss: bool = False,
    register_retry: Optional[RetryPolicy] = None,
    reconcile: Optional[Mapping[str, Any]] = None,
    resume_manifest: Optional[Sequence[str]] = None,
) -> RegistrarEvents:
    """Register, then keep the registration alive; returns the event surface.

    Must be called with a running event loop (the daemon's mainline or a
    test harness).  ``health_check`` is the config's ``healthCheck`` object
    (seconds-based keys, see :mod:`registrar_tpu.config` for translation).
    ``heartbeat_retry`` overrides the per-probe retry policy (configured
    from the sample config's ``maxAttempts``, see config.py).
    ``repair_heartbeat_miss`` opts into re-registering when a heartbeat
    finds the znodes gone (module docstring; default off = reference
    behavior).  ``register_retry`` opts the registration pipeline (initial
    and every re-registration) into the transient-fault retry layer
    (:data:`registrar_tpu.registration.REGISTER_RETRY` is the shipped
    policy); default None = single attempt, reference behavior.
    ``reconcile`` starts the level-triggered reconciler (module
    docstring): ``{"interval_seconds": float, "repair": bool}`` — the
    config's ``reconcile`` object, seconds-based.  Default None = no
    reconciler, reference behavior.
    ``resume_manifest`` (ISSUE 5) marks a cross-process session resume:
    the client reattached a predecessor's live session whose ephemerals
    are expected intact, so the agent VERIFIES the registration (one
    read-back sweep against the desired records) instead of running the
    pipeline's delete+recreate — a watching resolver sees zero NO_NODE.
    Any drift (or a failed sweep) falls back to the normal pipeline.
    The value is the predecessor's owned-znode list (observability; the
    desired records, not the manifest, are the verification truth).
    """
    ee = RegistrarEvents()
    # Health-check construction fails HERE, synchronously: built inside
    # the spawned _run task, a bad healthCheck mapping raised ValueError
    # into a fire-and-forget task AFTER registration landed — the error
    # vanished in the loop's default handler and the host stayed
    # registered with no health checking at all (caught by checklib's
    # task-exception-blackhole rule).  The consumer still STARTS only
    # after registration completes, as before.
    health = create_health_check(**health_check) if health_check else None
    ee._track(_run(ee, zk, registration, admin_ip,
                   health, heartbeat_interval,
                   hostname, settle_delay,
                   heartbeat_retry,
                   repair_heartbeat_miss,
                   register_retry,
                   reconcile,
                   resume_manifest))
    return ee


async def _run(
    ee: RegistrarEvents,
    zk: ZKClient,
    registration: Mapping[str, Any],
    admin_ip: Optional[str],
    health_check: Optional[HealthCheck],
    heartbeat_interval: float,
    hostname: Optional[str],
    settle_delay: float,
    heartbeat_retry: Optional[RetryPolicy] = None,
    repair_heartbeat_miss: bool = False,
    register_retry: Optional[RetryPolicy] = None,
    reconcile: Optional[Mapping[str, Any]] = None,
    resume_manifest: Optional[Sequence[str]] = None,
) -> None:
    # Mutable so the SIGHUP hot-reload can swap the registration in
    # place: every later pipeline run (heartbeat repair, rebirth,
    # health recovery, reconciler) reads through this one holder.
    params = {"registration": dict(registration), "admin_ip": admin_ip}

    async def do_register() -> list:
        """The one registration pipeline call every path shares."""
        return await register_mod.register(
            zk, params["registration"], admin_ip=params["admin_ip"],
            hostname=hostname,
            settle_delay=settle_delay, retry_policy=register_retry,
        )

    #: single-flight guard over every znode-mutating recovery flow
    #: (heartbeat repair, rebirth re-registration, health transitions,
    #: reconciler repair) — see module docstring.
    repair_lock = asyncio.Lock()

    resumed = False
    znodes = None
    if resume_manifest is not None:
        znodes = await _adopt_resumed(zk, params, hostname, resume_manifest)
        resumed = znodes is not None
    if znodes is None:
        try:
            # Under the single-flight lock like every other pipeline run:
            # no recovery actor exists yet to contend, but the invariant
            # ("znode mutations hold the repair lock") is then true
            # without exception — and machine-checked (checklib's
            # await-in-lock-free-mutator rule).
            async with repair_lock:
                znodes = await do_register()
        except asyncio.CancelledError:
            raise
        except Exception as err:  # noqa: BLE001
            log.debug("registration failed: %r", err)
            ee.emit("error", err)
            return

    ee.znodes = znodes
    ee.epoch += 1
    ee._reload_fn = lambda reg, ip: _apply_reload(
        ee, zk, params, repair_lock, hostname, reg, ip
    )
    if ee.stopped:
        return

    ee._track(_heartbeat_loop(
        ee, zk, heartbeat_interval, heartbeat_retry,
        do_register if repair_heartbeat_miss else None,
        repair_lock,
    ))
    if health_check is not None:
        _start_health_consumer(ee, zk, do_register, health_check, repair_lock)

    # Session lifecycle supervisor consumer (ISSUE 3): a reborn session
    # holds none of the old session's ephemerals — re-run the idempotent
    # pipeline, unless health deliberately deregistered the host.  One
    # long-lived task consumes a signal (not a task per event), so
    # back-to-back expiries cannot stack duplicate pipelines.
    rebirth_signal = asyncio.Event()
    zk.on("session_reborn", lambda _sid: rebirth_signal.set())
    ee._track(_rebirth_loop(ee, zk, do_register, repair_lock, rebirth_signal))

    if reconcile:
        from registrar_tpu.reconcile import Reconciler

        ee.reconciler = Reconciler(
            zk, ee, params["registration"],
            admin_ip=params["admin_ip"], hostname=hostname,
            interval_s=reconcile.get("interval_seconds", 60.0),
            repair=bool(reconcile.get("repair", False)),
            repair_fn=lambda epoch: _reregister_guarded(
                ee, zk, do_register, repair_lock, expect_epoch=epoch
            ),
            lock=repair_lock,
        )
        ee._track(ee.reconciler.run())
    if resume_manifest is not None:
        # "reattached": verify-not-recreate adopted the predecessor's
        # live znodes (zero NO_NODE across the restart); "repaired":
        # the sweep found drift and the pipeline re-ran instead.
        ee.emit("resume", "reattached" if resumed else "repaired")
    ee.emit("register", znodes)


async def _adopt_resumed(
    zk: ZKClient,
    params: Mapping[str, Any],
    hostname: Optional[str],
    manifest: Sequence[str],
) -> Optional[List[str]]:
    """Verify-not-recreate (ISSUE 5 handoff resume).

    The client reattached the predecessor's session, so its ephemerals
    should be sitting exactly where the desired records say — running the
    pipeline would delete and recreate them, a Binder-visible NO_NODE
    window, which is the one thing a handoff exists to avoid.  One
    read-back sweep (the reconciler's own diff engine) checks every
    desired znode: clean means the registration is adopted as-is; any
    drift — or a sweep the wire won't carry — returns None and the
    caller falls back to the normal pipeline (the registration was
    already broken, so the pipeline's blip costs nothing extra).
    """
    from registrar_tpu import reconcile as reconcile_mod

    try:
        desired = reconcile_mod.desired_records(
            params["registration"], params["admin_ip"], hostname
        )
        drifts = await reconcile_mod.sweep(
            zk, desired, session_id=zk.session_id
        )
    except asyncio.CancelledError:
        raise
    except Exception as err:  # noqa: BLE001 - fall back to the pipeline
        log.warning(
            "resume verification sweep failed (%r); falling back to the "
            "registration pipeline", err,
        )
        return None
    if drifts:
        log.warning(
            "resume verification found %d drift(s) (%s); falling back to "
            "the registration pipeline",
            len(drifts), [(d.reason, d.path) for d in drifts],
        )
        return None
    adopted = [d.path for d in desired]
    extra = sorted(set(manifest) - set(adopted))
    if extra:
        # Manifest nodes the current desired records no longer cover
        # (shouldn't happen with the config-hash gate, but a manifest is
        # operator-editable): never adopt them blind — they would be
        # heartbeated and defended forever.
        log.warning(
            "resume manifest lists %s beyond the desired records; ignoring",
            extra,
        )
    log.info(
        "resumed registration verified in place (%d znodes, zero drift)",
        len(adopted),
    )
    return adopted


async def _apply_reload(
    ee: RegistrarEvents,
    zk: ZKClient,
    params: dict,
    lock: asyncio.Lock,
    hostname: Optional[str],
    new_registration: Mapping[str, Any],
    new_admin_ip: Optional[str],
) -> str:
    """Apply a SIGHUP config reload as a minimal znode delta (ISSUE 5).

    Old and new desired records are diffed path-by-path; only the
    difference touches ZooKeeper — an unchanged host ephemeral is never
    deleted or recreated, so names that didn't change never flicker in
    DNS.  The agent's desired state (``params``, the reconciler's view,
    ``ee.znodes``) switches to the new config FIRST, under the
    single-flight lock: even if a delta operation then fails (raised to
    the caller), every recovery layer is already converging on the new
    records, not fighting for the old ones.

    The diff base is what was last successfully APPLIED, not merely what
    the params hold: a delta that died mid-apply leaves
    ``ee._applied_desired`` carrying the pre-reload records plus the
    paths the failed delta may have half-touched, so a retry SIGHUP —
    or a revert back to the old config — re-computes the real remaining
    work (dirty paths are unconditionally rewritten) instead of
    comparing the new config against itself and declaring a hollow
    "noop".  The individual delta operations are idempotent (absent
    deletes, already-created creates, and missing set_data targets are
    absorbed) for exactly that replay.
    """
    from registrar_tpu import reconcile as reconcile_mod

    # desired_records validates the registration on every path through
    # here, so a bad reload fails before any state is touched.
    base = ee._applied_desired
    if base is None:
        old_desired = reconcile_mod.desired_records(
            params["registration"], params["admin_ip"], hostname
        )
        base_map, dirty = {d.path: d for d in old_desired}, frozenset()
    else:
        base_map, dirty = base
    new_desired = reconcile_mod.desired_records(
        new_registration, new_admin_ip, hostname
    )
    new_map = {d.path: d for d in new_desired}

    async with lock:
        params["registration"] = dict(new_registration)
        params["admin_ip"] = new_admin_ip
        if ee.reconciler is not None:
            ee.reconciler.registration = params["registration"]
            ee.reconciler.admin_ip = new_admin_ip
        if base_map == new_map and not dirty:
            ee._applied_desired = None  # in sync with params again
            return "noop"
        if ee.stopped:
            return "noop"
        if ee.down:
            # Desired state while health-deregistered is ABSENT; the new
            # records materialize through do_register on recovery.
            log.info(
                "config reload applied while health-down: desired state "
                "updated, znodes follow on recovery"
            )
            ee.epoch += 1
            ee._applied_desired = None
            return "applied"
        try:
            await _apply_desired_delta(zk, base_map, new_map, dirty=dirty)
        except BaseException:
            # Remember the pre-reload base AND every path this delta
            # could have touched: a later reload (retry or revert) must
            # assume those are in an unknown state and rewrite them,
            # never trust the always-"noop" new-vs-new comparison the
            # already-swapped params would produce.
            touched = {
                p
                for p in set(base_map) | set(new_map)
                if base_map.get(p) != new_map.get(p)
            }
            ee._applied_desired = (base_map, dirty | touched)
            raise
        ee._applied_desired = None
        ee.znodes = [d.path for d in new_desired]
        ee.epoch += 1
        log.info(
            "config reload applied: %d znode(s) now owned (epoch %d)",
            len(ee.znodes), ee.epoch,
        )
        ee.emit("register", ee.znodes)
    return "applied"


async def _apply_desired_delta(
    zk: ZKClient, old_map, new_map, dirty=frozenset()
) -> None:
    """Converge ZooKeeper from one desired-record map to another with the
    minimum touch set.  Every operation is idempotent so a replay after a
    mid-apply failure is safe (see :func:`_apply_reload`).

    ``dirty`` paths are in an UNKNOWN state (a previous delta died while
    touching them): they are unconditionally cleared in pass 1 — a stale
    node a failed forward delta created must not survive a revert — and
    rewritten from scratch in pass 2 when the new records want them.

    Order matters: removals, shape changes, and dirty paths are cleared
    FIRST — a node flipping ephemeral <-> persistent can only be
    converged by unlink+recreate (a put cannot change ephemerality:
    leaving a service record ephemeral means it silently dies with the
    session), and a path becoming a service record may be about to grow
    children, which an ephemeral cannot hold.
    """
    # Pass 1: clear removals, shape flips, and unknown (dirty) state.
    for path in old_map:
        if path not in new_map:
            await register_mod.unlink_tolerant(zk, path)
    for path in dirty:
        if path not in old_map or path in new_map:
            await register_mod.unlink_tolerant(zk, path)
    for path, want in new_map.items():
        have = old_map.get(path)
        if (
            have is not None
            and path not in dirty
            and have.ephemeral != want.ephemeral
        ):
            await register_mod.unlink_tolerant(zk, path)

    # Pass 2: write the new records.
    for path, want in new_map.items():
        have = None if path in dirty else old_map.get(path)
        if (
            have is not None
            and have.payload == want.payload
            and have.ephemeral == want.ephemeral
        ):
            continue  # untouched: zero NO_NODE for unchanged names
        if not want.ephemeral:
            await zk.put(path, want.payload)  # service-record upsert
        elif (
            have is not None
            and have.ephemeral
            and have.payload != want.payload
        ):
            # Payload-only change on a node we own: set in place —
            # watchers see one dataChanged, never a NO_NODE.
            try:
                await zk.set_data(path, want.payload)
            except ZKError as err:
                if err.code != Err.NO_NODE:
                    raise
                await zk.create_ephemeral_plus(path, want.payload)
        else:
            try:
                await zk.create_ephemeral_plus(path, want.payload)
            except ZKError as err:
                if err.code != Err.NODE_EXISTS:
                    raise
                # replay after a half-applied delta: already created
                await zk.set_data(path, want.payload)


#: post-rebirth re-registration retry: unbounded like the connect path
#: (a live session with NO registration is a silent DNS outage — strictly
#: worse than the exit(1)+supervisor-restart the feature replaces, so the
#: agent must never give up while the client is alive), decorrelated
#: jitter so a fleet reborn by the same ensemble event does not re-run
#: its pipelines in lockstep.
REBIRTH_REREGISTER_RETRY = RetryPolicy(
    max_attempts=float("inf"), initial_delay=0.5, max_delay=30.0,
    jitter="decorrelated",
)


async def _rebirth_loop(ee, zk, do_register, lock, signal) -> None:
    """Consume ``session_reborn`` signals: re-run the idempotent pipeline
    until it lands, with jittered backoff across transient failures.

    A single attempt is not enough: rebirths happen exactly when the
    ensemble is flaky, so the first pipeline run frequently dies on the
    same turbulence that killed the session — and nothing else would
    retry it (the heartbeat loop sees NO_NODE but only repairs with the
    opt-in ``repairHeartbeatMiss``).  The loop stops retrying when the
    registration is refreshed (by this loop or any other recovery path —
    ``_reregister_guarded`` reports both as True), when health holds the
    host down (``on_recover`` owns the eventual re-registration), or
    when the client/agent is gone.  A new expiry mid-retry just re-sets
    the signal; the running retry chain continues against the newest
    session, since ``do_register`` always uses the live client.
    """
    while not ee.stopped:
        await signal.wait()
        signal.clear()
        delays = REBIRTH_REREGISTER_RETRY.schedule()
        while not ee.stopped and not zk.closed:
            try:
                done = await _reregister_guarded(ee, zk, do_register, lock)
            except asyncio.CancelledError:
                raise
            except Exception as err:  # noqa: BLE001
                delay = next(delays)
                log.warning(
                    "post-rebirth re-registration failed (%r); "
                    "retrying in %.1fs", err, delay,
                )
                ee.emit("error", err)
                await asyncio.sleep(delay)
                continue
            if not done:  # down/stopped: on_recover owns the comeback
                log.debug("post-rebirth re-registration skipped (down)")
            break


async def _reregister_guarded(
    ee, zk, do_register, lock, expect_epoch: Optional[int] = None
) -> bool:
    """Run the registration pipeline under the single-flight lock,
    honoring a health deregistration that lands at any point.

    Returns True when the registration was refreshed (``ee.znodes``
    updated, ``register`` emitted); False when the host is down/stopped —
    including the race where health crosses its threshold while the
    pipeline (1 s settle + RPCs) is in flight, in which case the freshly
    created znodes are rolled back out rather than resurrecting a host
    health just declared dead.  Pipeline errors propagate to the caller.

    ``expect_epoch`` is the ``ee.epoch`` the caller observed when it
    decided repair was needed: if another recovery actor refreshed the
    registration while this one waited on the lock, the stale repair is
    skipped (returns True — the registration IS fresh) instead of
    running the pipeline's delete+recreate over it.
    """
    if expect_epoch is None:
        expect_epoch = ee.epoch
    if ee.down or ee.stopped:
        return False
    # The span covers the lock wait AND the pipeline run: its children
    # (register.pipeline, the zk.op spans) show where the time went, and
    # the lock-wait gap is the span's own duration minus theirs.
    with trace.tracer_for(zk).span(
        "agent.repair", expect_epoch=expect_epoch
    ) as sp:
        async with lock:
            if ee.down or ee.stopped:
                return False
            if ee.epoch != expect_epoch:
                log.debug(
                    "re-registration skipped: epoch moved %d -> %d while "
                    "waiting (another recovery path already repaired)",
                    expect_epoch, ee.epoch,
                )
                sp.set_attr("outcome", "stale-epoch")
                return True
            new_znodes = await do_register()
            if ee.down or ee.stopped:
                log.debug("re-registration rolled back (health down/stopped)")
                sp.set_attr("outcome", "rolled-back")
                try:
                    await register_mod.unregister(zk, new_znodes)
                except Exception as u_err:  # noqa: BLE001
                    ee.emit("error", u_err)
                return False
            ee.znodes = new_znodes
            ee.epoch += 1
            ee._applied_desired = None  # pipeline wrote the params' records
            log.debug("re-registered %s (epoch %d)", ee.znodes, ee.epoch)
            sp.set_attr("outcome", "registered")
            ee.emit("register", new_znodes)
            return True


#: cap on how long a coalesced sweep waits for sibling services to join
#: its flush (the window is otherwise interval/10, so fast test
#: intervals stay fast); a sweep is never delayed past this.
COALESCE_WINDOW_CAP_S = 0.05


class HeartbeatCoalescer:
    """Cork concurrent heartbeat sweeps from the register_plus services
    sharing ONE ZKClient into a single pipelined EXISTS flush (ISSUE 11).

    Each service's ``_heartbeat_loop`` still owns its cadence, failure
    backoff, and NO_NODE→confirm→repair flow; what changes is only the
    wire shape: sweeps that arrive within one window ride a single
    :meth:`ZKClient.heartbeat_many` call (one corked write, one drain,
    one shared deadline) instead of one flush per service.  Per-service
    outcomes resolve the moment the client decides them (``on_outcome``),
    so a healthy service is never held behind a failing sibling's retry
    schedule.  With a single attached service the coalescer is a pure
    pass-through to :meth:`ZKClient.heartbeat` — zero added latency, and
    tests that monkeypatch ``client.heartbeat`` still intercept the
    probe.  Sweeps are reads (EXISTS only): no single-flight lock needed.
    """

    def __init__(self, zk) -> None:
        self._zk = zk
        self._attached = 0
        #: (nodes, retry, future) staged for the open window's flush
        self._staged: list = []
        self._flush_task: Optional[asyncio.Task] = None
        self._tasks: set = set()

    def attach(self) -> None:
        self._attached += 1

    def detach(self) -> None:
        self._attached -= 1

    async def sweep(self, nodes, retry, interval: float) -> None:
        """One heartbeat sweep over ``nodes``; raises what a solo
        ``zk.heartbeat(nodes, retry=retry)`` would raise."""
        if self._attached <= 1 and not self._staged:
            # Solo service: no window, no future — the daemon's common
            # shape stays byte-identical to the uncoalesced loop.
            await self._zk.heartbeat(nodes, retry=retry)
            return
        fut = asyncio.get_running_loop().create_future()
        self._staged.append((list(nodes), retry, fut))
        if self._flush_task is None or self._flush_task.done():
            window = min(COALESCE_WINDOW_CAP_S, interval / 10.0)
            self._flush_task = spawn_owned(
                self._flush_after(window), self._tasks
            )
        err = await fut
        if err is not None:
            raise err

    async def _flush_after(self, window_s: float) -> None:
        try:
            await asyncio.sleep(window_s)
        except asyncio.CancelledError:
            # Cancelled mid-window: nothing will sweep this batch — fail
            # the staged futures over to their awaiting service loops
            # (which are themselves being cancelled in the stop() case)
            # instead of leaving them parked forever.
            batch, self._staged = self._staged, []
            self._flush_task = None
            for _, _, fut in batch:
                if not fut.done():
                    fut.cancel()
            raise
        batch, self._staged = self._staged, []
        self._flush_task = None
        # Group by retry-policy identity: services configured alike (the
        # normal fleet shape) share one flush; a divergent policy gets
        # its own heartbeat_many with its own schedule — run
        # CONCURRENTLY, so one round riding a failing group's backoff
        # never head-of-line blocks another policy's healthy sweep.
        rounds: dict = {}
        for nodes, retry, fut in batch:
            rounds.setdefault(id(retry), (retry, []))[1].append((nodes, fut))

        async def run_round(retry, members) -> None:
            futs = [f for _, f in members]

            def release(i: int, err) -> None:
                if not futs[i].done():
                    futs[i].set_result(err)

            try:
                await self._zk.heartbeat_many(
                    [nodes for nodes, _ in members],
                    retry=retry,
                    on_outcome=release,
                )
            except asyncio.CancelledError:
                for f in futs:
                    if not f.done():
                        f.cancel()
                raise
            except Exception as err:  # noqa: BLE001 - fan the failure out
                for f in futs:
                    if not f.done():
                        f.set_exception(err)

        if len(rounds) == 1:
            ((retry, members),) = rounds.values()
            await run_round(retry, members)
        elif rounds:
            await asyncio.gather(
                *(run_round(r, m) for r, m in rounds.values())
            )


#: per-client coalescer registry (weak: a closed client's coalescer dies
#: with it; nothing here outlives the session it serves)
_COALESCERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _coalescer_for(zk) -> HeartbeatCoalescer:
    co = _COALESCERS.get(zk)
    if co is None:
        co = _COALESCERS[zk] = HeartbeatCoalescer(zk)
    return co


async def _heartbeat_loop(
    ee: RegistrarEvents,
    zk: ZKClient,
    interval: float,
    retry: Optional[RetryPolicy] = None,
    repair=None,
    lock: Optional[asyncio.Lock] = None,
) -> None:
    """Hot loop #1 (SURVEY.md §3.2): self-rescheduling znode liveness probe.

    ``repair``: optional coroutine factory re-running the registration
    pipeline; invoked when a probe fails with NO_NODE (znodes vanished
    without our session expiring — e.g. an operator deleted them, or a
    reattach raced a cleanup) unless the health checker holds the host
    down.  None = reference behavior: failures only back off.  ``lock``
    is the agent-wide single-flight guard the repair runs under.

    Probes route through the per-client :class:`HeartbeatCoalescer`:
    when several services share this client, sweeps landing in the same
    window fuse into one pipelined flush; solo, it is a pass-through.
    """
    if lock is None:
        lock = asyncio.Lock()
    coalescer = _coalescer_for(zk)
    coalescer.attach()
    try:
        await _heartbeat_loop_body(
            ee, zk, interval, retry, repair, lock, coalescer
        )
    finally:
        coalescer.detach()


async def _heartbeat_loop_body(
    ee: RegistrarEvents,
    zk: ZKClient,
    interval: float,
    retry: Optional[RetryPolicy],
    repair,
    lock: asyncio.Lock,
    coalescer: HeartbeatCoalescer,
) -> None:
    while not ee.stopped:
        try:
            await coalescer.sweep(ee.znodes, retry, interval)
        except asyncio.CancelledError:
            raise
        except Exception as err:  # noqa: BLE001
            log.debug("zk.heartbeat(%s) failed: %r", ee.znodes, err)
            ee.emit("heartbeatFailure", err)
            # Snapshot the registration epoch at the moment the miss was
            # observed: if another recovery path re-registers while the
            # confirm probe / lock wait is in flight, the repair below
            # becomes a no-op instead of a delete+recreate over it.
            epoch_at_miss = ee.epoch
            if (
                repair is not None
                and not ee.down
                and not ee.stopped
                and isinstance(err, ZKError)
                and err.code == Err.NO_NODE
                and await _confirm_nodes_missing(zk, ee)
            ):
                try:
                    repaired = await _reregister_guarded(
                        ee, zk, repair, lock, expect_epoch=epoch_at_miss
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as r_err:  # noqa: BLE001
                    log.debug("heartbeat repair failed: %r", r_err)
                    ee.emit("error", r_err)
                else:
                    if repaired:
                        await asyncio.sleep(interval)
                        continue
            await asyncio.sleep(max(interval, HEARTBEAT_FAILURE_BACKOFF_S))
            continue
        log.debug("zk.heartbeat(%s): ok", ee.znodes)
        ee.emit("heartbeat", ee.znodes)
        await asyncio.sleep(interval)


async def _confirm_nodes_missing(zk: ZKClient, ee: RegistrarEvents) -> bool:
    """One fresh single-attempt probe before the repair pipeline runs.

    A NO_NODE from the probe retry chain can be a *transient* artifact —
    a stale read served by a lagging follower just before catch-up, or a
    probe raced with a session reattach — and the repair pipeline is not
    free: its cleanup stage deletes and re-creates the live znodes, a
    real (if brief) deregistration observable by Binder.  Repair only
    proceeds when a second, immediate probe confirms the znodes are
    really gone; any other outcome (probe passes, or fails for transient
    reasons like CONNECTION_LOSS) falls back to the reference's plain
    failure backoff.
    """
    try:
        await zk.heartbeat(ee.znodes, retry=RetryPolicy(max_attempts=1))
    except asyncio.CancelledError:
        raise
    except ZKError as err:
        return err.code == Err.NO_NODE
    except Exception:  # noqa: BLE001 - transient/unknown: do not repair
        return False
    return False


def _start_health_consumer(
    ee: RegistrarEvents,
    zk: ZKClient,
    do_register,
    check: HealthCheck,
    lock: Optional[asyncio.Lock] = None,
) -> None:
    """Hot loop #2 (SURVEY.md §3.3): health stream -> deregister/re-register.

    ``check`` is constructed by :func:`register_plus` (synchronously, so
    a bad mapping fails at the call site, not inside the task).
    Transitions run under the agent-wide single-flight ``lock`` so a
    rebirth/reconciler/heartbeat repair can never interleave its pipeline
    with a deliberate deregistration.  A failed ``unregister`` leaves
    ``ee.down`` latched with the znodes intact — the reconciler's
    down-state sweep (desired = absent) finishes the deregistration on a
    later tick (ISSUE 3 satellite fix; without a reconciler the error is
    surfaced for the operator, the pre-existing behavior).
    """
    ee._health = check
    if lock is None:
        lock = asyncio.Lock()
    transitioning = False

    async def on_fail(err: Exception) -> None:
        nonlocal transitioning
        ee.down = True
        transitioning = True
        try:
            log.debug("healthcheck failed, deregistering (znodes=%s)", ee.znodes)
            ee.emit("fail", err)
            try:
                async with lock:
                    deleted = await register_mod.unregister(zk, ee.znodes)
            except Exception as u_err:  # noqa: BLE001
                log.debug("healthcheck: unregister failed: %r", u_err)
                ee.emit("error", u_err)
            else:
                ee.emit("unregister", err, deleted)
        finally:
            transitioning = False

    async def on_recover() -> None:
        nonlocal transitioning
        transitioning = True
        try:
            ee.emit("ok")
            try:
                async with lock:
                    znodes = await do_register()
            except Exception as r_err:  # noqa: BLE001
                log.debug("register: reregister failed: %r", r_err)
                ee.emit("error", r_err)
            else:
                ee.znodes = znodes
                ee.epoch += 1
                ee._applied_desired = None  # pipeline wrote params' records
                ee.down = False
                ee.emit("register", znodes)
        finally:
            transitioning = False

    def on_data(record: Mapping[str, Any]) -> None:
        if ee.stopped or transitioning:
            # Mirrors the reference's implicit single-flight behavior: its
            # `down` flag only flips after the async transition completes.
            return
        rtype = record.get("type")
        if rtype == "ok":
            if ee.down:
                ee._track(on_recover())
        elif rtype == "fail":
            if (
                record.get("err") is not None
                and record.get("isDown")
                and not ee.down
            ):
                ee._track(on_fail(record["err"]))
        else:
            ee.emit("error", ValueError(f"unknown check type: {rtype!r}"))

    check.on("data", on_data)
    check.on("error", lambda err: ee.emit("error", err))
    check.start()
