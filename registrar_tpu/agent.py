"""The registrar orchestrator: registration + heartbeat + health checking.

Rebuild of the reference's default export ``register_plus``
(lib/index.js:33-182).  Ties the three subsystems together and exposes a
lifecycle event surface:

    register(znodes)           registration (or re-registration) completed
    unregister(err, znodes)    health check declared down; znodes holds what
                               was actually deleted (a shared service node
                               with sibling hosts under it stays, and is
                               not listed)
    heartbeat(znodes)          periodic znode liveness probe succeeded
    heartbeatFailure(err)      probe failed after bounded retries
    ok()                       health check recovered (was down)
    fail(err)                  health check crossed the failure threshold
    error(err)                 unexpected error from any subsystem

Loop behavior matches the reference exactly (BASELINE.md):

  * heartbeat every ``heartbeat_interval`` (default 3 s,
    lib/index.js:132), re-armed *after* each probe completes (the
    reference's self-rescheduling setTimeout chain, not a fixed-rate timer);
  * after a heartbeat failure the loop backs off to
    ``max(heartbeat_interval, 60 s)`` (lib/index.js:146);
  * a heartbeat failure does NOT deregister or exit — recovery rides on ZK
    session expiry + supervisor restart, or a health-check ``ok``
    re-registration (SURVEY.md §3.2 note).  SURVEY.md §3.2 flags re-creating
    missing ephemerals on heartbeat NO_NODE as a worthwhile but
    behavior-changing improvement: it is available here as the **opt-in**
    ``repair_heartbeat_miss`` flag (config key ``repairHeartbeatMiss``),
    default off for reference parity.  When enabled, a heartbeat that fails
    with NO_NODE re-runs the registration pipeline — unless the health
    checker has deliberately deregistered the host (``ee.down``);
  * on health ``fail`` with ``isDown`` the znodes are unregistered; on the
    next health ``ok`` the full registration pipeline runs again
    (lib/index.js:59-116).

Two opt-in recovery layers ride above the reference loops (ISSUE 3):

  * the ZK client's ``session_reborn`` event (``surviveSessionExpiry``)
    is consumed here — a fresh in-process session has no ephemerals, so
    the idempotent registration pipeline re-runs, honoring ``ee.down``
    (a health-deregistered host is never resurrected by a rebirth);
  * a level-triggered reconciler (:mod:`registrar_tpu.reconcile`, config
    ``reconcile: {intervalSeconds, repair}``) periodically diffs the
    owned znodes against the desired records and emits structured
    ``drift`` / ``driftRepaired`` / ``reconcile`` events — with
    ``repair`` on it converges through the same pipeline.

Every znode-mutating flow (heartbeat repair, rebirth re-registration,
health transitions, reconciler repair) is single-flight through one
``asyncio.Lock``, so two recovery paths can never interleave a cleanup
stage into each other's half-built registration.

Fixed here (reference warts that are unobservable in znode state):
``register_plus`` references an undefined ``cfg`` on initial-registration
failure (lib/index.js:48) — the error path here just emits ``error``; and
re-registration is guarded against overlapping ``ok`` events.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Mapping, Optional

from registrar_tpu import registration as register_mod
from registrar_tpu.events import EventEmitter, spawn_owned
from registrar_tpu.health import HealthCheck, create_health_check
from registrar_tpu.registration import SETTLE_DELAY_S
from registrar_tpu.retry import RetryPolicy
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import Err, ZKError

log = logging.getLogger("registrar_tpu.agent")

#: reference lib/index.js:132
DEFAULT_HEARTBEAT_INTERVAL_S = 3.0
#: reference lib/index.js:146 — floor of the post-failure re-arm delay
HEARTBEAT_FAILURE_BACKOFF_S = 60.0


class RegistrarEvents(EventEmitter):
    """Event surface returned by :func:`register_plus` (the reference's
    EventEmitter + ``.stop()``, lib/index.js:164-171)."""

    def __init__(self) -> None:
        super().__init__()
        self.znodes: list = []
        #: True while the health checker holds the host deregistered —
        #: gates heartbeat repair so it never races a deliberate
        #: deregistration.
        self.down = False
        #: bumped every time a registration pipeline run refreshes
        #: ``znodes``.  Recovery actors queued on the single-flight lock
        #: snapshot it when they DECIDE to repair and skip if it moved by
        #: the time they hold the lock: without this, the loser of the
        #: race re-runs the pipeline over the winner's fresh registration
        #: and its cleanup stage deletes the just-repaired znodes —
        #: re-minting the very drift that queued it (an unbounded
        #: repair tug-of-war between heartbeat repair and the
        #: reconciler; regression: tests/test_e2e_options.py).
        self.epoch = 0
        self._tasks: set = set()
        self._health: Optional[HealthCheck] = None
        self._stopped = False
        #: the level-triggered reconciler, when configured (test/metrics
        #: observability; None without the ``reconcile`` config block)
        self.reconciler = None

    def stop(self) -> None:
        """Stop the heartbeat loop and health checker.

        Does NOT delete the znodes — like the reference, a graceful library
        stop leaves cleanup to ZK session expiry (SURVEY.md §3.4)."""
        self._stopped = True
        if self._health is not None:
            self._health.stop()
        for task in list(self._tasks):
            task.cancel()
        self._tasks.clear()

    def _track(self, coro) -> "asyncio.Task":
        """Spawn ``coro`` as a task owned until done (finished tasks drop
        out, so a daemon with a flapping health check doesn't accumulate
        them forever) and cancelled by stop()."""
        return spawn_owned(coro, self._tasks)

    @property
    def stopped(self) -> bool:
        return self._stopped


def register_plus(
    zk: ZKClient,
    registration: Mapping[str, Any],
    admin_ip: Optional[str] = None,
    health_check: Optional[Mapping[str, Any]] = None,
    heartbeat_interval: float = DEFAULT_HEARTBEAT_INTERVAL_S,
    hostname: Optional[str] = None,
    settle_delay: float = SETTLE_DELAY_S,
    heartbeat_retry: Optional[RetryPolicy] = None,
    repair_heartbeat_miss: bool = False,
    register_retry: Optional[RetryPolicy] = None,
    reconcile: Optional[Mapping[str, Any]] = None,
) -> RegistrarEvents:
    """Register, then keep the registration alive; returns the event surface.

    Must be called with a running event loop (the daemon's mainline or a
    test harness).  ``health_check`` is the config's ``healthCheck`` object
    (seconds-based keys, see :mod:`registrar_tpu.config` for translation).
    ``heartbeat_retry`` overrides the per-probe retry policy (configured
    from the sample config's ``maxAttempts``, see config.py).
    ``repair_heartbeat_miss`` opts into re-registering when a heartbeat
    finds the znodes gone (module docstring; default off = reference
    behavior).  ``register_retry`` opts the registration pipeline (initial
    and every re-registration) into the transient-fault retry layer
    (:data:`registrar_tpu.registration.REGISTER_RETRY` is the shipped
    policy); default None = single attempt, reference behavior.
    ``reconcile`` starts the level-triggered reconciler (module
    docstring): ``{"interval_seconds": float, "repair": bool}`` — the
    config's ``reconcile`` object, seconds-based.  Default None = no
    reconciler, reference behavior.
    """
    ee = RegistrarEvents()
    ee._track(_run(ee, zk, registration, admin_ip,
                   health_check, heartbeat_interval,
                   hostname, settle_delay,
                   heartbeat_retry,
                   repair_heartbeat_miss,
                   register_retry,
                   reconcile))
    return ee


async def _run(
    ee: RegistrarEvents,
    zk: ZKClient,
    registration: Mapping[str, Any],
    admin_ip: Optional[str],
    health_check: Optional[Mapping[str, Any]],
    heartbeat_interval: float,
    hostname: Optional[str],
    settle_delay: float,
    heartbeat_retry: Optional[RetryPolicy] = None,
    repair_heartbeat_miss: bool = False,
    register_retry: Optional[RetryPolicy] = None,
    reconcile: Optional[Mapping[str, Any]] = None,
) -> None:
    async def do_register() -> list:
        """The one registration pipeline call every path shares."""
        return await register_mod.register(
            zk, registration, admin_ip=admin_ip, hostname=hostname,
            settle_delay=settle_delay, retry_policy=register_retry,
        )

    #: single-flight guard over every znode-mutating recovery flow
    #: (heartbeat repair, rebirth re-registration, health transitions,
    #: reconciler repair) — see module docstring.
    repair_lock = asyncio.Lock()

    try:
        znodes = await do_register()
    except asyncio.CancelledError:
        raise
    except Exception as err:  # noqa: BLE001
        log.debug("registration failed: %r", err)
        ee.emit("error", err)
        return

    ee.znodes = znodes
    ee.epoch += 1
    if ee.stopped:
        return

    ee._track(_heartbeat_loop(
        ee, zk, heartbeat_interval, heartbeat_retry,
        do_register if repair_heartbeat_miss else None,
        repair_lock,
    ))
    if health_check:
        _start_health_consumer(ee, zk, do_register, health_check, repair_lock)

    # Session lifecycle supervisor consumer (ISSUE 3): a reborn session
    # holds none of the old session's ephemerals — re-run the idempotent
    # pipeline, unless health deliberately deregistered the host.  One
    # long-lived task consumes a signal (not a task per event), so
    # back-to-back expiries cannot stack duplicate pipelines.
    rebirth_signal = asyncio.Event()
    zk.on("session_reborn", lambda _sid: rebirth_signal.set())
    ee._track(_rebirth_loop(ee, zk, do_register, repair_lock, rebirth_signal))

    if reconcile:
        from registrar_tpu.reconcile import Reconciler

        ee.reconciler = Reconciler(
            zk, ee, registration,
            admin_ip=admin_ip, hostname=hostname,
            interval_s=reconcile.get("interval_seconds", 60.0),
            repair=bool(reconcile.get("repair", False)),
            repair_fn=lambda epoch: _reregister_guarded(
                ee, zk, do_register, repair_lock, expect_epoch=epoch
            ),
            lock=repair_lock,
        )
        ee._track(ee.reconciler.run())
    ee.emit("register", znodes)


#: post-rebirth re-registration retry: unbounded like the connect path
#: (a live session with NO registration is a silent DNS outage — strictly
#: worse than the exit(1)+supervisor-restart the feature replaces, so the
#: agent must never give up while the client is alive), decorrelated
#: jitter so a fleet reborn by the same ensemble event does not re-run
#: its pipelines in lockstep.
REBIRTH_REREGISTER_RETRY = RetryPolicy(
    max_attempts=float("inf"), initial_delay=0.5, max_delay=30.0,
    jitter="decorrelated",
)


async def _rebirth_loop(ee, zk, do_register, lock, signal) -> None:
    """Consume ``session_reborn`` signals: re-run the idempotent pipeline
    until it lands, with jittered backoff across transient failures.

    A single attempt is not enough: rebirths happen exactly when the
    ensemble is flaky, so the first pipeline run frequently dies on the
    same turbulence that killed the session — and nothing else would
    retry it (the heartbeat loop sees NO_NODE but only repairs with the
    opt-in ``repairHeartbeatMiss``).  The loop stops retrying when the
    registration is refreshed (by this loop or any other recovery path —
    ``_reregister_guarded`` reports both as True), when health holds the
    host down (``on_recover`` owns the eventual re-registration), or
    when the client/agent is gone.  A new expiry mid-retry just re-sets
    the signal; the running retry chain continues against the newest
    session, since ``do_register`` always uses the live client.
    """
    while not ee.stopped:
        await signal.wait()
        signal.clear()
        delays = REBIRTH_REREGISTER_RETRY.schedule()
        while not ee.stopped and not zk.closed:
            try:
                done = await _reregister_guarded(ee, zk, do_register, lock)
            except asyncio.CancelledError:
                raise
            except Exception as err:  # noqa: BLE001
                delay = next(delays)
                log.warning(
                    "post-rebirth re-registration failed (%r); "
                    "retrying in %.1fs", err, delay,
                )
                ee.emit("error", err)
                await asyncio.sleep(delay)
                continue
            if not done:  # down/stopped: on_recover owns the comeback
                log.debug("post-rebirth re-registration skipped (down)")
            break


async def _reregister_guarded(
    ee, zk, do_register, lock, expect_epoch: Optional[int] = None
) -> bool:
    """Run the registration pipeline under the single-flight lock,
    honoring a health deregistration that lands at any point.

    Returns True when the registration was refreshed (``ee.znodes``
    updated, ``register`` emitted); False when the host is down/stopped —
    including the race where health crosses its threshold while the
    pipeline (1 s settle + RPCs) is in flight, in which case the freshly
    created znodes are rolled back out rather than resurrecting a host
    health just declared dead.  Pipeline errors propagate to the caller.

    ``expect_epoch`` is the ``ee.epoch`` the caller observed when it
    decided repair was needed: if another recovery actor refreshed the
    registration while this one waited on the lock, the stale repair is
    skipped (returns True — the registration IS fresh) instead of
    running the pipeline's delete+recreate over it.
    """
    if expect_epoch is None:
        expect_epoch = ee.epoch
    if ee.down or ee.stopped:
        return False
    async with lock:
        if ee.down or ee.stopped:
            return False
        if ee.epoch != expect_epoch:
            log.debug(
                "re-registration skipped: epoch moved %d -> %d while "
                "waiting (another recovery path already repaired)",
                expect_epoch, ee.epoch,
            )
            return True
        new_znodes = await do_register()
        if ee.down or ee.stopped:
            log.debug("re-registration rolled back (health down/stopped)")
            try:
                await register_mod.unregister(zk, new_znodes)
            except Exception as u_err:  # noqa: BLE001
                ee.emit("error", u_err)
            return False
        ee.znodes = new_znodes
        ee.epoch += 1
        log.debug("re-registered %s (epoch %d)", ee.znodes, ee.epoch)
        ee.emit("register", new_znodes)
        return True


async def _heartbeat_loop(
    ee: RegistrarEvents,
    zk: ZKClient,
    interval: float,
    retry: Optional[RetryPolicy] = None,
    repair=None,
    lock: Optional[asyncio.Lock] = None,
) -> None:
    """Hot loop #1 (SURVEY.md §3.2): self-rescheduling znode liveness probe.

    ``repair``: optional coroutine factory re-running the registration
    pipeline; invoked when a probe fails with NO_NODE (znodes vanished
    without our session expiring — e.g. an operator deleted them, or a
    reattach raced a cleanup) unless the health checker holds the host
    down.  None = reference behavior: failures only back off.  ``lock``
    is the agent-wide single-flight guard the repair runs under.
    """
    if lock is None:
        lock = asyncio.Lock()
    while not ee.stopped:
        try:
            await zk.heartbeat(ee.znodes, retry=retry)
        except asyncio.CancelledError:
            raise
        except Exception as err:  # noqa: BLE001
            log.debug("zk.heartbeat(%s) failed: %r", ee.znodes, err)
            ee.emit("heartbeatFailure", err)
            # Snapshot the registration epoch at the moment the miss was
            # observed: if another recovery path re-registers while the
            # confirm probe / lock wait is in flight, the repair below
            # becomes a no-op instead of a delete+recreate over it.
            epoch_at_miss = ee.epoch
            if (
                repair is not None
                and not ee.down
                and not ee.stopped
                and isinstance(err, ZKError)
                and err.code == Err.NO_NODE
                and await _confirm_nodes_missing(zk, ee)
            ):
                try:
                    repaired = await _reregister_guarded(
                        ee, zk, repair, lock, expect_epoch=epoch_at_miss
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as r_err:  # noqa: BLE001
                    log.debug("heartbeat repair failed: %r", r_err)
                    ee.emit("error", r_err)
                else:
                    if repaired:
                        await asyncio.sleep(interval)
                        continue
            await asyncio.sleep(max(interval, HEARTBEAT_FAILURE_BACKOFF_S))
            continue
        log.debug("zk.heartbeat(%s): ok", ee.znodes)
        ee.emit("heartbeat", ee.znodes)
        await asyncio.sleep(interval)


async def _confirm_nodes_missing(zk: ZKClient, ee: RegistrarEvents) -> bool:
    """One fresh single-attempt probe before the repair pipeline runs.

    A NO_NODE from the probe retry chain can be a *transient* artifact —
    a stale read served by a lagging follower just before catch-up, or a
    probe raced with a session reattach — and the repair pipeline is not
    free: its cleanup stage deletes and re-creates the live znodes, a
    real (if brief) deregistration observable by Binder.  Repair only
    proceeds when a second, immediate probe confirms the znodes are
    really gone; any other outcome (probe passes, or fails for transient
    reasons like CONNECTION_LOSS) falls back to the reference's plain
    failure backoff.
    """
    try:
        await zk.heartbeat(ee.znodes, retry=RetryPolicy(max_attempts=1))
    except asyncio.CancelledError:
        raise
    except ZKError as err:
        return err.code == Err.NO_NODE
    except Exception:  # noqa: BLE001 - transient/unknown: do not repair
        return False
    return False


def _start_health_consumer(
    ee: RegistrarEvents,
    zk: ZKClient,
    do_register,
    health_check: Mapping[str, Any],
    lock: Optional[asyncio.Lock] = None,
) -> None:
    """Hot loop #2 (SURVEY.md §3.3): health stream -> deregister/re-register.

    Transitions run under the agent-wide single-flight ``lock`` so a
    rebirth/reconciler/heartbeat repair can never interleave its pipeline
    with a deliberate deregistration.  A failed ``unregister`` leaves
    ``ee.down`` latched with the znodes intact — the reconciler's
    down-state sweep (desired = absent) finishes the deregistration on a
    later tick (ISSUE 3 satellite fix; without a reconciler the error is
    surfaced for the operator, the pre-existing behavior).
    """
    check = create_health_check(**health_check)
    ee._health = check
    if lock is None:
        lock = asyncio.Lock()
    transitioning = False

    async def on_fail(err: Exception) -> None:
        nonlocal transitioning
        ee.down = True
        transitioning = True
        try:
            log.debug("healthcheck failed, deregistering (znodes=%s)", ee.znodes)
            ee.emit("fail", err)
            try:
                async with lock:
                    deleted = await register_mod.unregister(zk, ee.znodes)
            except Exception as u_err:  # noqa: BLE001
                log.debug("healthcheck: unregister failed: %r", u_err)
                ee.emit("error", u_err)
            else:
                ee.emit("unregister", err, deleted)
        finally:
            transitioning = False

    async def on_recover() -> None:
        nonlocal transitioning
        transitioning = True
        try:
            ee.emit("ok")
            try:
                async with lock:
                    znodes = await do_register()
            except Exception as r_err:  # noqa: BLE001
                log.debug("register: reregister failed: %r", r_err)
                ee.emit("error", r_err)
            else:
                ee.znodes = znodes
                ee.epoch += 1
                ee.down = False
                ee.emit("register", znodes)
        finally:
            transitioning = False

    def on_data(record: Mapping[str, Any]) -> None:
        if ee.stopped or transitioning:
            # Mirrors the reference's implicit single-flight behavior: its
            # `down` flag only flips after the async transition completes.
            return
        rtype = record.get("type")
        if rtype == "ok":
            if ee.down:
                ee._track(on_recover())
        elif rtype == "fail":
            if (
                record.get("err") is not None
                and record.get("isDown")
                and not ee.down
            ):
                ee._track(on_fail(record["err"]))
        else:
            ee.emit("error", ValueError(f"unknown check type: {rtype!r}"))

    check.on("data", on_data)
    check.on("error", lambda err: ee.emit("error", err))
    check.start()
