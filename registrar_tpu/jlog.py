"""Bunyan-compatible structured JSON logging.

The reference logs bunyan records to stdout (reference main.js:23-28), and
downstream Triton/Manta log tooling (`bunyan` CLI, log shippers) consumes
that shape.  This module makes Python's stdlib logging emit the same
format so existing operational tooling keeps working::

    {"v":0,"level":30,"name":"registrar","hostname":"...","pid":123,
     "time":"2026-07-29T12:00:00.000Z","msg":"...", ...extra fields...}

Level mapping (bunyan numeric levels, main.js/-v escalation semantics):

    TRACE=10  DEBUG=20  INFO=30  WARN=40  ERROR=50  FATAL=60

Python's logging has no TRACE/FATAL; they are registered here.  Extra
structured fields ride on ``logging``'s ``extra=`` dict via the ``zdata``
key: ``log.info("registered", extra={"zdata": {"znodes": [...]}})``.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import sys
import time
from typing import Any, Dict, Mapping, Optional

TRACE = 5  # python numeric; rendered as bunyan 10
FATAL = logging.CRITICAL  # rendered as bunyan 60

logging.addLevelName(TRACE, "TRACE")

#: python level -> bunyan level
_BUNYAN_LEVELS = [
    (logging.CRITICAL, 60),
    (logging.ERROR, 50),
    (logging.WARNING, 40),
    (logging.INFO, 30),
    (logging.DEBUG, 20),
    (TRACE, 10),
]

#: bunyan level name -> python level (config logLevel / LOG_LEVEL env)
LEVELS: Dict[str, int] = {
    "trace": TRACE,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}


def _bunyan_level(py_level: int) -> int:
    for py, bun in _BUNYAN_LEVELS:
        if py_level >= py:
            return bun
    return 10


class BunyanFormatter(logging.Formatter):
    def __init__(self, name: str = "registrar"):
        super().__init__()
        self.name = name
        self.hostname = socket.gethostname()

    def format(self, record: logging.LogRecord) -> str:
        rec: Dict[str, Any] = {
            "name": self.name,
            "hostname": self.hostname,
            "pid": record.process,
            "component": record.name,
            "level": _bunyan_level(record.levelno),
            "msg": record.getMessage(),
            "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            )
            + f".{int(record.msecs):03d}Z",
            "v": 0,
        }
        if logging.getLogger(record.name).getEffectiveLevel() <= logging.DEBUG:
            # bunyan's `src: true` — caller provenance once debugging is on
            # (the reference enables it the same way, main.js:75-76).
            rec["src"] = {
                "file": record.pathname,
                "line": record.lineno,
                "func": record.funcName,
            }
        # Trace correlation (ISSUE 8): the TraceContextFilter (installed
        # only when the `observability` block enables tracing) stamps
        # these attributes; without it nothing is set and the output is
        # byte-identical to untraced builds.
        trace_id = getattr(record, "trace_id", None)
        if trace_id is not None:
            rec["trace_id"] = trace_id
            rec["span_id"] = getattr(record, "span_id", None)
        zdata = getattr(record, "zdata", None)
        if isinstance(zdata, Mapping):
            for key, value in zdata.items():
                rec.setdefault(key, _jsonable(value))
        if record.exc_info and record.exc_info[1] is not None:
            err = record.exc_info[1]
            rec["err"] = {
                "message": str(err),
                "name": type(err).__name__,
                "stack": self.formatException(record.exc_info),
            }
        return json.dumps(rec, separators=(",", ":"), ensure_ascii=False,
                          default=str)


def _jsonable(value: Any) -> Any:
    if isinstance(value, BaseException):
        return {"message": str(value), "name": type(value).__name__}
    return value


def setup(
    name: str = "registrar",
    level: Optional[int] = None,
    stream=None,
) -> logging.Logger:
    """Configure root logging for the daemon: one bunyan line per record.

    Level resolution order (reference main.js:24,66-76): explicit ``level``
    arg > ``LOG_LEVEL`` env > info.
    """
    if level is None:
        env = os.environ.get("LOG_LEVEL", "").lower()
        level = LEVELS.get(env, logging.INFO)
    handler = logging.StreamHandler(stream or sys.stdout)
    handler.setFormatter(BunyanFormatter(name))
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(level)
    return logging.getLogger(name)


def escalate(levels: int) -> int:
    """Apply ``-v`` escalation: each -v drops the root level by one notch
    toward TRACE (reference main.js:69-73)."""
    order = [logging.CRITICAL, logging.ERROR, logging.WARNING, logging.INFO,
             logging.DEBUG, TRACE]
    root = logging.getLogger()
    current = root.level
    idx = min(
        range(len(order)), key=lambda i: abs(order[i] - current)
    )
    new = order[min(idx + levels, len(order) - 1)]
    root.setLevel(new)
    return new
