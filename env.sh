#
# Developer environment helpers — source this from the repo root
# (the analog of the reference's env.sh, which put the bundled node on
# PATH and aliased `run`).
#
#     . ./env.sh
#     zkserve          # hermetic ZooKeeper on 127.0.0.1:21811
#     run              # the daemon against the shipped sample config, verbose
#     zkcli tree /
#

export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"

alias run='python3 -m registrar_tpu -f ./etc/config.coal.json -v'
alias zkserve='python3 -m registrar_tpu.testing.server --port 21811'
alias zkensemble='python3 -m registrar_tpu.testing.server --port 21811 --ensemble 3'
alias zkcli='python3 -m registrar_tpu.tools.zkcli -s 127.0.0.1:21811'
