#!/usr/bin/env python3
"""Availability-SLO runner: drive a churn trace, report, and gate.

The CLI over :mod:`registrar_tpu.testing.slo` (ISSUE 9).  One run:

    python tools/slo.py --trace quick --report slo-report.json

drives the named trace (a seeded fleet of in-process registrars under
deploy waves, crash loops, health flaps, expiry storms, and netem
episodes while a resolver polls continuously), writes the full SLO
report to ``--report``, prints a one-line JSON summary on stdout, and —
for the ``quick`` trace — gates the measured availability envelope
against ``SLO_BASELINE.json`` exactly the way bench.py gates perf:

  * ``SLO_HISTORY.json`` is the append-only record (``--record NAME``
    appends a round);
  * ``SLO_BASELINE.json`` is GENERATED from it by the same
    best-across-rounds + headroom rule (``--repin`` writes it,
    ``--check-baseline`` — run by ``make check-core`` — fails on any
    hand edit);
  * the gate allows ``tolerance_pct`` beyond the pinned floors
    (``SLO_TOLERANCE_PCT`` to widen on slower/noisier hardware,
    ``SLO_GATE=0`` to disable); one automatic retry absorbs scheduler
    noise, judging the per-metric best of the two runs.

``--prove-detection`` (the ``make slo-quick`` mode) additionally reruns
the same seed with repair disabled and fails unless the broken run's
nines measurably drop — the standing proof that the probe detects real
outages rather than vacuously passing.

Runs drive the ISSUE-20 availability levers by default (raced
connects, the tightened ping schedule, stale-while-revalidate in the
probe cache, and the trace's lever timing overrides); ``--reference``
restores the r19 reference-exact envelope.  ``--prove-levers`` (the
``make slo-nines`` mode) reruns the same seed reference-exact and
fails unless the levers measurably beat it — the standing proof the
engineered nines come from the levers, not the weather.

``SLO_SEED`` (or ``--seed``) pins the trace schedule; the seed is
echoed on stderr and recorded in the report so a failing run replays
exactly.
"""

import argparse
import asyncio
import json
import logging
import os
import random
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import bench  # noqa: E402  (the shared history/baseline/gate machinery)
from registrar_tpu.testing import slo as slo_mod  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HISTORY_PATH = os.environ.get(
    "SLO_HISTORY_PATH", os.path.join(REPO, "SLO_HISTORY.json")
)
BASELINE_PATH = os.environ.get(
    "SLO_BASELINE_PATH", os.path.join(REPO, "SLO_BASELINE.json")
)

#: the nines drop --prove-detection requires between the repaired and
#: the repair-disabled run of the same seed (the broken run must lose
#: at least this much, which a probe that detects nothing cannot show)
MIN_NINES_DROP = 0.2

#: the availability gain (percentage points) --prove-levers requires of
#: the levers-on run over the reference-exact rerun of the same seed —
#: levers that cannot clear this are noise, not engineering
MIN_LEVER_GAIN_PCT = 2.0


def _gate_result(report: dict) -> dict:
    """The bench.gate-shaped view of a report's gated metrics."""
    metrics = dict(report["gate_metrics"])
    return {
        "metric": "availability_pct",
        "value": metrics["availability_pct"],
        "extra": metrics,
    }


def _tolerance(baseline: dict) -> float:
    raw = os.environ.get(
        "SLO_TOLERANCE_PCT", baseline.get("tolerance_pct", 25)
    )
    try:
        return float(raw)
    except (TypeError, ValueError):
        print(
            f"slo: invalid SLO_TOLERANCE_PCT {raw!r}; expected a number",
            file=sys.stderr,
        )
        raise SystemExit(2)


def check_baseline() -> list:
    """Divergences between SLO_BASELINE.json and rule(SLO_HISTORY.json)."""
    if not os.path.exists(HISTORY_PATH):
        return [f"{HISTORY_PATH} is missing (nothing recorded yet)"]
    if not os.path.exists(BASELINE_PATH):
        # Answer before delegating: bench's missing-baseline branch
        # names ITS file and repin command, which would point an
        # operator at the perf baseline instead of this one.
        return [
            f"{BASELINE_PATH} is missing; run `python tools/slo.py --repin`"
        ]
    return bench.check_baseline(
        history_path=HISTORY_PATH, baseline_path=BASELINE_PATH
    )


def _summary_line(report: dict) -> str:
    return json.dumps(
        {
            "trace": report["trace"],
            "seed": report["seed"],
            "repair": report["repair"],
            "levers": (report.get("levers") or {}).get("enabled", False),
            "duration_s": report["duration_s"],
            "availability": report["availability"],
            "nines": report["nines"],
            **report["gate_metrics"],
        }
    )


def _fault_table(report: dict) -> str:
    """Per-fault-class downtime/availability table (ISSUE 20): the
    report's ``faults`` rollup as an aligned text block — the summary
    an operator (and the CI job summary) reads to see WHICH fault class
    owns the downtime, next to each class's own availability over its
    probe segments."""
    header = (
        "fault", "inj", "det", "downtime_s", "avail_pct",
        "mttd_s", "mttr_s",
    )
    rows = []
    for fid in sorted(report.get("faults") or {}):
        entry = report["faults"][fid]
        avail = entry.get("availability")
        rows.append((
            fid,
            str(entry["injected"]),
            str(entry["detected"]),
            f"{entry['outage_s']:.4f}",
            f"{avail * 100.0:.2f}" if avail is not None else "-",
            (
                f"{entry['mttd_s_mean']:.4f}"
                if entry.get("mttd_s_mean") is not None
                else "-"
            ),
            (
                f"{entry['mttr_s_mean']:.4f}"
                if entry.get("mttr_s_mean") is not None
                else "-"
            ),
        ))
    widths = [
        max(len(row[i]) for row in [header, *rows])
        for i in range(len(header))
    ]
    lines = [
        "  ".join(col.ljust(widths[i]) for i, col in enumerate(header))
    ]
    for row in rows:
        lines.append(
            "  ".join(col.ljust(widths[i]) for i, col in enumerate(row))
        )
    return "\n".join(lines)


def _run(trace: str, seed: int, repair: bool, levers: bool) -> dict:
    return asyncio.run(
        slo_mod.run_trace(trace, seed=seed, repair=repair, levers=levers)
    )


def _write_worst_trace(report: dict, report_path: str) -> None:
    """Dump the worst outage's ASSEMBLED cross-process trace tree next
    to the report (ISSUE 13): ``<report>.worst-trace.json`` (the raw
    tree — probe span, router relay, the owning worker's resolve
    subtree, one trace id) and ``.txt`` (the indented duration render,
    ``zkcli trace --id``'s view).  ``make slo-quick`` writes these by
    default and the CI SLO job uploads them with the report, so a bad
    nines number arrives with its causal tree attached."""
    from registrar_tpu import traceview

    tree = ((report.get("outages") or {}).get("worst") or {}).get(
        "trace_tree"
    )
    base = (
        report_path[: -len(".json")]
        if report_path.endswith(".json")
        else report_path
    )
    if not tree:
        # A flawless run has no worst outage to dissect — and must not
        # leave a PREVIOUS run's tree sitting next to the fresh report
        # (an always() artifact step would upload the mismatched pair).
        for suffix in (".worst-trace.json", ".worst-trace.txt"):
            try:
                os.remove(base + suffix)
            except OSError:
                pass
        return
    with open(f"{base}.worst-trace.json", "w", encoding="utf-8") as fh:
        json.dump(tree, fh, indent=2, default=str)
        fh.write("\n")
    with open(f"{base}.worst-trace.txt", "w", encoding="utf-8") as fh:
        fh.write(traceview.render_text(tree))
        fh.write("\n")
    print(
        f"slo: worst-outage trace tree written to {base}.worst-trace.json "
        f"/ .txt ({tree.get('spans', 0)} spans, "
        f"{tree.get('orphans', 0)} orphaned)",
        file=sys.stderr,
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="slo", description="availability-SLO trace runner + gate"
    )
    parser.add_argument(
        "--trace", choices=sorted(slo_mod.TRACES), default="quick",
        help="named trace to drive (default quick; only quick is gated)",
    )
    parser.add_argument(
        "--seed", type=int, default=None,
        help="trace schedule seed (default: SLO_SEED env, else random)",
    )
    parser.add_argument(
        "--report", metavar="PATH", default=None,
        help="write the full SLO report JSON here",
    )
    parser.add_argument(
        "--no-repair", action="store_true",
        help="inject faults but withhold every recovery action (the "
        "deliberately broken run; never gated)",
    )
    parser.add_argument(
        "--prove-detection", action="store_true",
        help="after the gated run, rerun the same seed with repair "
        "disabled and fail unless the nines measurably drop",
    )
    parser.add_argument(
        "--reference", action="store_true",
        help="run reference-exact (ISSUE-20 availability levers and "
        "trace timing overrides OFF; the r19 envelope — never recorded)",
    )
    parser.add_argument(
        "--prove-levers", action="store_true",
        help="after the gated levers run, rerun the same seed "
        "reference-exact and fail unless the levers beat it by at "
        f"least {MIN_LEVER_GAIN_PCT} availability points (make "
        "slo-nines)",
    )
    parser.add_argument(
        "--min-classes", type=int, default=None, metavar="N",
        help="fail unless at least N fault classes have measured "
        "MTTD+MTTR (default: 4 for quick, 0 otherwise)",
    )
    parser.add_argument(
        "--record", metavar="ROUND", default=None,
        help="append this run's gated metrics to SLO_HISTORY.json "
        "under the given round name",
    )
    parser.add_argument(
        "--repin", action="store_true",
        help="regenerate SLO_BASELINE.json from SLO_HISTORY.json",
    )
    parser.add_argument(
        "--check-baseline", action="store_true",
        help="verify SLO_BASELINE.json matches rule(SLO_HISTORY.json)",
    )
    args = parser.parse_args(argv)

    # The fleet's clients log every reconnect/refused-resume at
    # warn/error — which is the simulator working as intended, not an
    # operator signal.  SLO_VERBOSE=1 restores the firehose.
    if os.environ.get("SLO_VERBOSE", "0") != "1":
        logging.getLogger("registrar_tpu").setLevel(logging.CRITICAL)
        # ...but the simulator's OWN diagnostics (a prober that keeps
        # crashing, a scenario that never reconverges) stay visible —
        # availability 0.0 with no traceback is an unreplayable black
        # box even with the seed in hand.
        logging.getLogger("registrar_tpu.testing.slo").setLevel(
            logging.WARNING
        )

    if args.check_baseline:
        problems = check_baseline()
        for p in problems:
            print(f"slo: baseline drift: {p}", file=sys.stderr)
        if problems:
            print(
                "slo: SLO_BASELINE.json does not match the history rule — "
                "record results with `python tools/slo.py --record ROUND` "
                "and run `python tools/slo.py --repin` (never hand-edit "
                "the baseline)",
                file=sys.stderr,
            )
        return 1 if problems else 0
    if args.repin:
        history = bench.load_history(HISTORY_PATH)
        baseline = bench.baseline_from_history(history)
        baseline["comment"] = (
            "GENERATED from SLO_HISTORY.json by `python tools/slo.py "
            "--repin` — do not hand-edit (make check-core verifies this "
            "file matches the history rule; record new results in the "
            "history instead, `tools/slo.py --record ROUND`). Rule: "
            "per-metric best across recorded rounds with "
            f"{history['headroom_pct']}% headroom away from the best; "
            "the gate then allows tolerance_pct beyond these values at "
            "runtime (SLO_TOLERANCE_PCT to widen on slower hardware, "
            "SLO_GATE=0 to disable, SLO_BASELINE_PATH / "
            "SLO_HISTORY_PATH to relocate)."
        )
        with open(BASELINE_PATH, "w", encoding="utf-8") as fh:
            json.dump(baseline, fh, indent=2)
            fh.write("\n")
        print(f"slo: wrote {BASELINE_PATH} from {HISTORY_PATH}",
              file=sys.stderr)
        return 0

    seed = args.seed
    if seed is None:
        env_seed = os.environ.get("SLO_SEED")
        seed = (
            int(env_seed) if env_seed else random.randrange(2**32)
        )
    print(f"SLO_SEED={seed} (trace={args.trace})", file=sys.stderr)

    repair = not args.no_repair
    levers = not args.reference
    if args.prove_levers and args.reference:
        print(
            "slo: --prove-levers needs the levers run (drop --reference)",
            file=sys.stderr,
        )
        return 2
    report = _run(args.trace, seed, repair, levers)
    if args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2)
            fh.write("\n")
        print(f"slo: report written to {args.report}", file=sys.stderr)
        _write_worst_trace(report, args.report)
    print(_summary_line(report))
    if report.get("faults"):
        print(_fault_table(report), file=sys.stderr)

    failures = []
    min_classes = (
        args.min_classes
        if args.min_classes is not None
        else (4 if args.trace == "quick" and repair else 0)
    )
    measured = report["gate_metrics"]["fault_classes_measured"]
    if repair and measured < min_classes:
        failures.append(
            f"fault_classes_measured: {measured} < {min_classes} "
            "(the probe failed to measure enough fault classes)"
        )

    baseline = bench.load_baseline(BASELINE_PATH)
    gate_on = (
        repair
        and args.trace == "quick"
        and os.environ.get("SLO_GATE", "1") != "0"
        and baseline is not None
    )
    if gate_on:
        tolerance = _tolerance(baseline)
        gate_failures = bench.gate(
            _gate_result(report), baseline, tolerance,
            # SLO metric names live in SLO_HISTORY's directions map,
            # not bench.BENCH_METRICS — skip the bench-namespace
            # declaration check (it would fail every SLO metric).
            declared_metrics=None,
        )
        if gate_failures:
            # One retry absorbs scheduler noise; the gate judges the
            # per-metric best of both runs (bench.py's exact policy).
            print(
                "slo: possible regression, retrying: "
                + "; ".join(gate_failures),
                file=sys.stderr,
            )
            retry = _run(args.trace, seed, repair, levers)
            merged = bench.best_of(
                _gate_result(report), _gate_result(retry), baseline
            )
            best_view = {
                "metric": "availability_pct",
                "value": merged.get(
                    "availability_pct", report["gate_metrics"][
                        "availability_pct"
                    ]
                ),
                "extra": {k: v for k, v in merged.items() if v is not None},
            }
            gate_failures = bench.gate(
                best_view, baseline, tolerance, declared_metrics=None,
            )
        failures.extend(gate_failures)

    if args.prove_detection and repair:
        broken = _run(args.trace, seed, False, levers)
        drop = report["nines"] - broken["nines"]
        print(
            f"slo: detection proof: repaired nines={report['nines']} "
            f"broken nines={broken['nines']} (drop {round(drop, 3)})",
            file=sys.stderr,
        )
        if drop < MIN_NINES_DROP:
            failures.append(
                f"detection proof failed: disabling repair only dropped "
                f"the nines by {round(drop, 3)} (< {MIN_NINES_DROP}) — "
                "the probe is not detecting outages"
            )

    if args.prove_levers and repair:
        # Same seed, reference-exact: the r19 client/cache behavior and
        # the trace's r19 timings.  The levers must beat it — the
        # nines-past-90 claim is an A/B, not a single lucky run.
        reference = _run(args.trace, seed, repair, False)
        gain = (
            report["gate_metrics"]["availability_pct"]
            - reference["gate_metrics"]["availability_pct"]
        )
        print(
            "slo: lever proof: levers "
            f"availability={report['gate_metrics']['availability_pct']} "
            f"reference={reference['gate_metrics']['availability_pct']} "
            f"(gain {round(gain, 3)} pts; race_wins="
            f"{report['levers']['raced_connects']['race_wins']} "
            f"suspicions="
            f"{report['levers']['failure_detector']['suspicions']} "
            f"stale_serves="
            f"{report['levers']['swr_cache']['stale_serves']})",
            file=sys.stderr,
        )
        if gain < MIN_LEVER_GAIN_PCT:
            failures.append(
                f"lever proof failed: the levers only gained "
                f"{round(gain, 3)} availability points over the "
                f"reference run (< {MIN_LEVER_GAIN_PCT})"
            )

    if failures:
        print("slo: REGRESSION vs SLO_BASELINE.json:", file=sys.stderr)
        for failure in failures:
            print(f"  {failure}", file=sys.stderr)
        return 1

    # Recording happens LAST, and only for a clean quick-trace run: the
    # history generates the quick gate's floors, so a full/no-repair
    # run would mix a different measurement envelope in, and a round
    # with a null metric (an unmeasured fault class) would crash the
    # min()/max() of every later --repin/--check-baseline.
    if args.record is not None:
        metrics = dict(report["gate_metrics"])
        if args.trace != "quick" or not repair or not levers:
            print(
                "slo: refusing --record: only clean levers-on "
                "quick-trace runs belong in SLO_HISTORY.json (this was "
                f"trace={args.trace} repair={repair} levers={levers})",
                file=sys.stderr,
            )
            return 2
        if any(v is None for v in metrics.values()):
            missing = sorted(k for k, v in metrics.items() if v is None)
            print(
                f"slo: refusing --record: unmeasured metrics {missing} "
                "would poison the history rule",
                file=sys.stderr,
            )
            return 2
        history = (
            bench.load_history(HISTORY_PATH)
            if os.path.exists(HISTORY_PATH)
            else {
                "headroom_pct": 25,
                "tolerance_pct": 25,
                "directions": {},
                "rounds": [],
            }
        )
        history["rounds"].append(
            {"round": args.record, "metrics": metrics}
        )
        with open(HISTORY_PATH, "w", encoding="utf-8") as fh:
            json.dump(history, fh, indent=2)
            fh.write("\n")
        print(f"slo: recorded round {args.record!r} in {HISTORY_PATH}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
