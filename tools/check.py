#!/usr/bin/env python3
"""In-tree static analysis gate — CLI shim over :mod:`checklib`.

The reference gates its build on jsl + jsstyle with shipped configs
(reference Makefile:15,18 and tools/jsl.node.conf, tools/jsstyle.conf);
this is the rebuild's equivalent, implemented on the stdlib ``ast``
module because the image ships no third-party linter.  It grew from two
rules (undefined names, unused imports) into the multi-rule framework in
``tools/checklib/`` — file-local asyncio/hygiene rules plus a
whole-program generation (cross-module symbol table over the real
import graph, call graph with async propagation, event-name and
config-key contract diffs), inline suppressions, and a checked-in
baseline; see docs/CHECKS.md for the catalog, the suppression syntax,
and how to add a rule.

Usage::

    python tools/check.py [paths...] [--format json] [--output FILE]
                          [--no-baseline] [--write-baseline] [--list-rules]
                          [--changed-only] [--stats] [--max-seconds N]

Defaults to the package, tests, and top-level scripts; exits 1 if
anything is flagged (after suppressions and the baseline), 2 on a
missing target or malformed baseline.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from checklib import Finding, check_file, main  # noqa: E402

__all__ = ["Finding", "check_file", "main"]

if __name__ == "__main__":
    sys.exit(main(sys.argv))
