"""Generation-2 flow rules (whole-program; see program.py/callgraph.py).

These express what the file-local generation cannot: cross-module
coroutine misuse, event-loop stalls hidden behind sync helpers in other
modules, znode mutations that bypass the agent's single-flight lock, and
session secrets flowing into log lines.  Every rule consumes the shared
:class:`~checklib.program.ProgramModel` the engine builds once per run;
none of them re-parses anything.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from checklib.callgraph import CallGraph, chain_evidence, chain_names
from checklib.context import PACKAGE_PREFIX
from checklib.model import Finding
from checklib.program import ProgramModel
from checklib.registry import rule

#: The modules PR 3's single-flight-lock + epoch-guard invariant covers:
#: every znode-mutating flow that starts here must hold the repair lock.
LOCK_SCOPED_MODULES = frozenset(
    {
        PACKAGE_PREFIX + "agent.py",
        PACKAGE_PREFIX + "reconcile.py",
        PACKAGE_PREFIX + "main.py",
    }
)


def graph_for(model: ProgramModel) -> CallGraph:
    """One CallGraph per program model, shared by every flow rule."""
    g = getattr(model, "_callgraph", None)
    if g is None:
        g = CallGraph(model)
        model._callgraph = g
    return g


@rule(
    "cross-module-unawaited",
    "call to an async def imported from another module, never awaited",
    scope="program",
)
def cross_module_unawaited(model: ProgramModel) -> Iterator[Finding]:
    # The file-local unawaited-coroutine rule stops at the module edge by
    # design; this one resolves the call through the import graph.  Same
    # zero-false-positive contract: only single-binding, unshadowed names
    # resolve (program.py), so a name that is *ever* rebound stays silent.
    graph = graph_for(model)
    for site in model.all_call_sites():
        if not site.bare_stmt or site.awaited:
            continue
        res = graph.resolve(site)
        if res is None or res[0] != "func":
            continue
        target = res[1]
        if not target.is_async or target.module is site.func.module:
            continue  # same module: the file-local rule's jurisdiction
        yield Finding(
            "cross-module-unawaited",
            site.func.module.rel_path,
            site.lineno,
            f"coroutine '{site.render()}' ({target.module.name}."
            f"{target.qualname} is an async def) is never awaited",
        )


@rule(
    "transitive-blocking-call",
    "sync helper reached from async code blocks the event loop",
    scope="program",
)
def transitive_blocking_call(model: ProgramModel) -> Iterator[Finding]:
    # blocking-call-in-async flags the primitive lexically inside the
    # async def; this rule walks the call graph instead: an async frame
    # calling a sync helper (any module deep) that eventually hits
    # time.sleep / sync subprocess / blocking socket ops / write-mode
    # open stalls the loop exactly the same way.  The finding carries
    # the full chain.
    graph = graph_for(model)
    for site in model.all_call_sites():
        func = site.func
        if not func.is_async:
            continue
        if not func.module.rel_path.startswith(PACKAGE_PREFIX):
            continue  # package scope, like blocking-call-in-async
        res = graph.resolve(site)
        if res is None or res[0] != "func" or res[1].is_async:
            continue
        chain = graph.blocking_chain(res[1])
        if chain is None:
            continue
        full = [(func.ref, func.module.rel_path, site.lineno)] + chain
        primitive = chain[-1][0]
        yield Finding(
            "transitive-blocking-call",
            func.module.rel_path,
            site.lineno,
            f"async '{func.qualname}' blocks the event loop through "
            f"'{primitive}' (chain: {chain_names(full)})",
            chain=chain_evidence(full),
        )


@rule(
    "await-in-lock-free-mutator",
    "znode mutation reached from agent/reconcile/main outside the "
    "single-flight lock",
    scope="program",
)
def await_in_lock_free_mutator(model: ProgramModel) -> Iterator[Finding]:
    # PR 3's invariant: every znode-mutating flow in the agent's orbit
    # (heartbeat repair, rebirth, health transitions, reconciler repair,
    # reload delta) is single-flight through one asyncio.Lock plus the
    # registration-epoch guard, or two recovery actors interleave their
    # cleanup stages (the repair tug-of-war).  A mutator call site in the
    # scoped modules passes when it is lexically inside an
    # ``async with <...lock>`` block, or its enclosing function is only
    # ever called from lock-protected sites (interprocedural fixpoint).
    # To report each violation once, a site is only flagged where the
    # flow LEAVES the scoped modules (or hits a zk.* primitive
    # directly) — interior scoped-module callees get their own scan.
    graph = graph_for(model)
    locked = graph.always_locked()
    for site in model.all_call_sites():
        func = site.func
        if func.module.rel_path not in LOCK_SCOPED_MODULES:
            continue
        if site.under_lock or func in locked:
            continue
        primitive = graph.mutator_primitive(site)
        if primitive is None:
            res = graph.resolve(site)
            if res is None or res[0] != "func":
                continue
            if res[1].module.rel_path in LOCK_SCOPED_MODULES:
                continue  # its own sites are scanned directly
            chain = graph.mutator_chain(site)
            if chain is None:
                continue
            primitive = chain[-1][0]
        else:
            chain = graph.mutator_chain(site)
        yield Finding(
            "await-in-lock-free-mutator",
            func.module.rel_path,
            site.lineno,
            f"'{primitive}' reached from '{func.qualname}' outside the "
            f"single-flight lock + epoch guard "
            f"(chain: {chain_names(chain)})",
            chain=chain_evidence(chain),
        )


# -- secret-flow-to-log -------------------------------------------------------

#: Attribute / subscript names that hold the statefile session secret
#: (docs/OPERATIONS.md: "the state file IS the session secret").
SECRET_NAMES = frozenset({"passwd", "password", "session_passwd"})

_LOG_METHODS = frozenset(
    {"debug", "info", "warning", "warn", "error", "exception", "critical",
     "log"}
)


def _is_log_sink(call: ast.Call) -> bool:
    parts: List[str] = []
    node = call.func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name) or not parts:
        return False
    parts.append(node.id)
    parts.reverse()
    if parts[-1] not in _LOG_METHODS:
        return False
    return any("log" in p.lower() or p == "jlog" for p in parts[:-1])


def _mentions_secret(node, tainted: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in SECRET_NAMES:
            return True
        if isinstance(sub, ast.Subscript):
            sl = sub.slice
            if (
                isinstance(sl, ast.Constant)
                and isinstance(sl.value, str)
                and sl.value in SECRET_NAMES
            ):
                return True
        if isinstance(sub, ast.Name) and sub.id in (
            tainted | SECRET_NAMES
        ):
            return True
    return False


@rule(
    "secret-flow-to-log",
    "statefile session secret (passwd) flows into a log call",
    scope="program",
)
def secret_flow_to_log(model: ProgramModel) -> Iterator[Finding]:
    # PR 5's security posture: whoever holds the session passwd can adopt
    # the session and delete the host's DNS records, so it must never
    # reach a log line (logs ship to aggregators outside the statefile's
    # trust domain).  Lightweight per-scope dataflow: a name assigned
    # from an expression mentioning a secret source is tainted (iterated
    # to a local fixpoint), and any log.* / jlog sink whose arguments
    # mention a source or tainted name is flagged.  Cross-function flows
    # through calls are NOT tracked (conservative silence) — keep secret
    # values out of helper plumbing near log calls.
    for ctx in model.contexts:
        if not ctx.rel_path.startswith(PACKAGE_PREFIX):
            continue
        yield from _scan_scope(ctx.rel_path, ctx.tree.body, set())


def _name_targets(target) -> Iterator[str]:
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _name_targets(elt)
    elif isinstance(target, ast.Starred):
        yield from _name_targets(target.value)


def _scan_scope(rel_path: str, body, inherited: Set[str]):
    tainted = set(inherited)
    statements: List[ast.stmt] = list(body)
    nested: List[ast.AST] = []

    # local taint fixpoint over this scope's assignments (nested def
    # bodies are their own scopes — pruned here, recursed below)
    def iter_scope_nodes(root):
        stack = [root]
        while stack:
            n = stack.pop()
            yield n
            for c in ast.iter_child_nodes(n):
                if not isinstance(
                    c, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    stack.append(c)

    changed = True
    while changed:
        changed = False
        for node in statements:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in iter_scope_nodes(node):
                if isinstance(sub, ast.Assign):
                    if _mentions_secret(sub.value, tainted):
                        for t in sub.targets:
                            # Only NAME targets become tainted: an
                            # attribute target (self.session_passwd =
                            # resp.passwd) stores INTO an object — its
                            # base name ('self') is not the secret.
                            for n in _name_targets(t):
                                if n not in tainted:
                                    tainted.add(n)
                                    changed = True

    def walk(node) -> Iterator[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested.append(node)
            return
        if isinstance(node, ast.Call) and _is_log_sink(node):
            payload = list(node.args) + [kw.value for kw in node.keywords]
            if any(_mentions_secret(a, tainted) for a in payload):
                yield Finding(
                    "secret-flow-to-log",
                    rel_path,
                    node.lineno,
                    "session secret (passwd) reaches a log call "
                    "(the statefile secret must never be logged)",
                )
        for child in ast.iter_child_nodes(node):
            yield from walk(child)

    for stmt in statements:
        yield from walk(stmt)
    for fn in nested:
        # A closure sees the enclosing taint — minus any name its own
        # parameters shadow (an unrelated parameter named like a tainted
        # outer local is NOT the secret).
        args = fn.args
        params = {
            a.arg
            for a in (
                list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            )
        }
        yield from _scan_scope(rel_path, fn.body, tainted - params)
