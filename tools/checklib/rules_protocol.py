"""Wire-contract drift rules (generation 4).

The repo hand-rolls three binary protocols: the jute codec under
``registrar_tpu/zk/`` (PR-1), the shard tier's length-prefixed
op-byte protocol in ``registrar_tpu/shard.py`` (PRs 12-13), and the
DNS wire codec in ``registrar_tpu/dnsfront.py`` (PR-19, whose
``QTYPE_*``/``RCODE_*`` families are op codes in everything but
name).  Their encoder/decoder pairs are kept symmetric by golden
tests — which only catch drift on the paths the goldens exercise.
These rules check the *declared* contract statically:

``struct-format-drift``
    Every module-level ``NAME = struct.Struct("fmt")`` constant in the
    protocol modules is compiled with the stdlib (a format that does not
    compile is itself a finding), and every provable-arity use is
    checked against the format's field count: ``NAME.pack(a, b)``
    positional arity, ``a, b = NAME.unpack(...)`` /
    ``NAME.unpack_from(...)`` tuple destructures, and the jute reader's
    ``a, b = r.read_struct(NAME)`` idiom.  Literal
    ``struct.pack("fmt", ...)`` / ``struct.unpack("fmt", ...)`` calls
    get the same treatment.  Uses whose arity is not lexical — starred
    args, ``[0]`` subscripts, a result bound to one name — stay silent.

``opcode-dispatch-drift``
    The ``OP_*`` / ``QTYPE_*`` / ``RCODE_*`` constant families must
    agree in three places: the module-level definitions, at least one
    dispatch arm (a family name compared in an ``if``/``elif`` or used
    as a dispatch-dict key — a code nobody dispatches is dead protocol
    surface, and an arm for an undefined code is a decoder for frames
    nobody sends), and the protocol tables in docs/DESIGN.md +
    docs/OBSERVABILITY.md (backticked family-name rows with a numeric
    value column).  Doc legs are skipped entirely when neither doc
    carries a table row, so scratch trees without docs only get the
    code-side check.

``flag-bit-overlap``
    Flag constants are OR'd into the same byte as the op code
    (``op | TRACE_FLAG``), so within one module no two ``*FLAG*``
    constants may share bits, and no flag may share bits with an
    ``OP_*``/``STATUS_*`` code value — a collision makes a flagged
    frame indistinguishable from a different opcode.
"""

from __future__ import annotations

import ast
import os
import re
import struct
from typing import Dict, Iterator, List, Optional, Tuple

from checklib.model import Finding
from checklib.program import ModuleInfo, ProgramModel, _dotted
from checklib.registry import rule
from checklib.rules_contracts import read_doc_lines

#: The hand-rolled wire-protocol surface.  Everything else in the tree
#: may use ``struct`` casually; only these modules carry a contract.
_SHARD = "registrar_tpu/shard.py"
_DNSFRONT = "registrar_tpu/dnsfront.py"
_ZK_PREFIX = "registrar_tpu/zk/"

_PROTOCOL_DOCS = ("docs/DESIGN.md", "docs/OBSERVABILITY.md")

#: The op-code families: the shard tier's OP_* plus the DNS codec's
#: QTYPE_*/RCODE_* (wire-assigned code points with dispatch arms).
_OP_NAME = re.compile(r"^(?:OP|QTYPE|RCODE)_[A-Z0-9_]+$")
_STATUS_NAME = re.compile(r"^STATUS_[A-Z0-9_]+$")
#: A protocol-table row: first cell a backticked family name, some
#: later cell a bare decimal or 0x hex value.
_DOC_ROW = re.compile(r"^\s*\|\s*`((?:OP|QTYPE|RCODE)_[A-Z0-9_]+)`\s*\|(.*)$")
_DOC_VALUE = re.compile(r"^(?:0[xX][0-9a-fA-F]+|\d+)$")


def _protocol_modules(model: ProgramModel) -> List[ModuleInfo]:
    out = []
    for mod in model.modules.values():
        if mod.degraded or mod.ctx.tree is None:
            continue
        if (
            mod.rel_path in (_SHARD, _DNSFRONT)
            or mod.rel_path.startswith(_ZK_PREFIX)
        ):
            out.append(mod)
    return sorted(out, key=lambda m: m.rel_path)


def _toplevel_stmts(tree: ast.Module):
    """Module-level statements, looking through If/Try wrappers (the
    same notion of "module level" the binding table uses)."""
    stack = list(tree.body)
    while stack:
        node = stack.pop(0)
        if isinstance(node, ast.If):
            stack = node.body + node.orelse + stack
            continue
        if isinstance(node, ast.Try):
            extra = node.body + node.orelse + node.finalbody
            for h in node.handlers:
                extra += h.body
            stack = extra + stack
            continue
        yield node


def _single_name_assign(stmt) -> Optional[Tuple[str, ast.expr, int]]:
    if (
        isinstance(stmt, ast.Assign)
        and len(stmt.targets) == 1
        and isinstance(stmt.targets[0], ast.Name)
    ):
        return (stmt.targets[0].id, stmt.value, stmt.lineno)
    return None


def _const_int(expr) -> Optional[int]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        # bool is an int subclass; True as a wire constant is nonsense
        # but not this rule's business.
        if isinstance(expr.value, bool):
            return None
        return expr.value
    return None


# -- struct-format-drift -------------------------------------------------------


def _struct_ctor_fmt(value) -> Optional[str]:
    """The constant format string when ``value`` is a
    ``struct.Struct("fmt")`` call, else None."""
    if not isinstance(value, ast.Call) or value.keywords:
        return None
    d = _dotted(value.func)
    if d is None:
        return None
    base, attrs = d
    last = attrs[-1] if attrs else base
    if last != "Struct":
        return None
    if len(value.args) != 1:
        return None
    arg = value.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


def _field_count(s: struct.Struct) -> int:
    return len(s.unpack(b"\x00" * s.size))


class _StructConst:
    __slots__ = ("name", "fmt", "fields", "rel", "lineno")

    def __init__(self, name, fmt, fields, rel, lineno):
        self.name = name
        self.fmt = fmt
        self.fields = fields
        self.rel = rel
        self.lineno = lineno


def _positional_arity(call: ast.Call, skip: int = 0) -> Optional[int]:
    """Lexical positional-arg count, or None when not provable (starred
    args or any keywords)."""
    if call.keywords:
        return None
    if any(isinstance(a, ast.Starred) for a in call.args):
        return None
    n = len(call.args) - skip
    return n if n >= 0 else None


def _destructure_arity(stmt: ast.Assign) -> Optional[int]:
    """Number of names a tuple/list destructure binds, or None."""
    if len(stmt.targets) != 1:
        return None
    tgt = stmt.targets[0]
    if not isinstance(tgt, (ast.Tuple, ast.List)):
        return None
    if not all(isinstance(e, ast.Name) for e in tgt.elts):
        return None  # starred / nested targets: arity not lexical
    return len(tgt.elts)


@rule(
    "struct-format-drift",
    "a struct pack/unpack use whose lexical arity disagrees with its "
    "format string's field count",
    scope="program",
)
def struct_format_drift(model: ProgramModel) -> Iterator[Finding]:
    mods = _protocol_modules(model)
    if not mods:
        return

    consts: Dict[str, _StructConst] = {}
    ambiguous = set()
    for mod in mods:
        for stmt in _toplevel_stmts(mod.ctx.tree):
            bound = _single_name_assign(stmt)
            if bound is None:
                continue
            name, value, lineno = bound
            fmt = _struct_ctor_fmt(value)
            if fmt is None:
                continue
            try:
                fields = _field_count(struct.Struct(fmt))
            except struct.error as e:
                yield Finding(
                    "struct-format-drift",
                    mod.rel_path,
                    lineno,
                    f"struct format {fmt!r} bound to '{name}' does not "
                    f"compile: {e}",
                )
                continue
            if name in consts and consts[name].fmt != fmt:
                ambiguous.add(name)  # same name, two formats: punt
                continue
            consts[name] = _StructConst(
                name, fmt, fields, mod.rel_path, lineno
            )
    for name in ambiguous:
        consts.pop(name, None)

    def const_for(expr) -> Optional[_StructConst]:
        if isinstance(expr, ast.Name):
            return consts.get(expr.id)
        return None

    def check_call(call: ast.Call, rel: str):
        """Arity-check a pack-side call; yields at most one finding."""
        if not isinstance(call.func, ast.Attribute):
            return
        attr = call.func.attr
        sc = const_for(call.func.value)
        if sc is not None and attr in ("pack", "pack_into"):
            skip = 2 if attr == "pack_into" else 0  # buffer, offset
            n = _positional_arity(call, skip)
            if n is not None and n != sc.fields:
                yield Finding(
                    "struct-format-drift",
                    rel,
                    call.lineno,
                    f"'{sc.name}.{attr}' called with {n} value(s) but "
                    f"format {sc.fmt!r} packs {sc.fields} field(s)",
                )
            return
        # literal struct.pack("fmt", ...) / struct.calcsize twin
        if (
            isinstance(call.func.value, ast.Name)
            and call.func.value.id == "struct"
            and attr in ("pack", "pack_into")
            and call.args
            and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
        ):
            fmt = call.args[0].value
            try:
                fields = _field_count(struct.Struct(fmt))
            except struct.error as e:
                yield Finding(
                    "struct-format-drift",
                    rel,
                    call.lineno,
                    f"literal struct format {fmt!r} does not compile: {e}",
                )
                return
            skip = 3 if attr == "pack_into" else 1  # fmt(, buffer, offset)
            n = _positional_arity(call, skip)
            if n is not None and n != fields:
                yield Finding(
                    "struct-format-drift",
                    rel,
                    call.lineno,
                    f"'struct.{attr}' called with {n} value(s) but "
                    f"literal format {fmt!r} packs {fields} field(s)",
                )

    def unpack_source(value) -> Optional[Tuple[str, str, int]]:
        """(display, fmt-repr, field count) when ``value`` is an unpack
        call whose format is known, else None."""
        if not isinstance(value, ast.Call) or not isinstance(
            value.func, ast.Attribute
        ):
            return None
        attr = value.func.attr
        if attr in ("unpack", "unpack_from"):
            sc = const_for(value.func.value)
            if sc is not None:
                return (f"{sc.name}.{attr}", repr(sc.fmt), sc.fields)
            if (
                isinstance(value.func.value, ast.Name)
                and value.func.value.id == "struct"
                and value.args
                and isinstance(value.args[0], ast.Constant)
                and isinstance(value.args[0].value, str)
            ):
                fmt = value.args[0].value
                try:
                    fields = _field_count(struct.Struct(fmt))
                except struct.error:
                    return None  # reported by the pack-side scan if bound
                return (f"struct.{attr}", repr(fmt), fields)
            return None
        if attr == "read_struct" and len(value.args) == 1:
            sc = const_for(value.args[0])
            if sc is not None:
                return (f"read_struct({sc.name})", repr(sc.fmt), sc.fields)
        return None

    for mod in mods:
        rel = mod.rel_path
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.Call):
                yield from check_call(node, rel)
            elif isinstance(node, ast.Assign):
                arity = _destructure_arity(node)
                if arity is None:
                    continue
                src = unpack_source(node.value)
                if src is None:
                    continue
                display, fmt_repr, fields = src
                if arity != fields:
                    yield Finding(
                        "struct-format-drift",
                        rel,
                        node.lineno,
                        f"'{display}' result destructured into {arity} "
                        f"name(s) but format {fmt_repr} yields {fields} "
                        f"field(s)",
                    )


# -- opcode-dispatch-drift -----------------------------------------------------


def _doc_op_rows(root: str):
    """[(name, value, doc_rel, lineno)] from the protocol tables, or []
    when no doc carries a row (the legs are then skipped)."""
    rows = []
    for doc_rel in _PROTOCOL_DOCS:
        lines = read_doc_lines(os.path.join(root, *doc_rel.split("/")))
        if lines is None:
            continue
        for i, line in enumerate(lines, start=1):
            m = _DOC_ROW.match(line)
            if m is None:
                continue
            name = m.group(1)
            value = None
            for cell in m.group(2).split("|"):
                cell = cell.strip().strip("`")
                if _DOC_VALUE.match(cell):
                    value = int(cell, 0)
                    break
            if value is not None:
                rows.append((name, value, doc_rel, i))
    return rows


@rule(
    "opcode-dispatch-drift",
    "OP_* constants drift between definitions, dispatch arms, and the "
    "docs protocol tables",
    scope="program",
)
def opcode_dispatch_drift(model: ProgramModel) -> Iterator[Finding]:
    mods = _protocol_modules(model)
    if not mods:
        return

    defined: Dict[str, Tuple[int, str, int]] = {}  # name -> (value, rel, line)
    for mod in mods:
        for stmt in _toplevel_stmts(mod.ctx.tree):
            bound = _single_name_assign(stmt)
            if bound is None:
                continue
            name, value, lineno = bound
            if not _OP_NAME.match(name):
                continue
            iv = _const_int(value)
            if iv is not None and name not in defined:
                defined[name] = (iv, mod.rel_path, lineno)
    if not defined:
        return

    # A dispatch arm is an OP_* name compared against something, or used
    # as a dispatch-dict key.  Collect (name, rel, lineno) across every
    # protocol module: the router and worker legitimately split the arms.
    arms: Dict[str, Tuple[str, int]] = {}
    for mod in mods:
        for node in ast.walk(mod.ctx.tree):
            cands = ()
            if isinstance(node, ast.Compare):
                cands = [node.left] + list(node.comparators)
            elif isinstance(node, ast.Dict):
                cands = [k for k in node.keys if k is not None]
            for expr in cands:
                if isinstance(expr, ast.Name) and _OP_NAME.match(expr.id):
                    arms.setdefault(expr.id, (mod.rel_path, node.lineno))

    for name in sorted(defined):
        value, rel, lineno = defined[name]
        if name not in arms:
            yield Finding(
                "opcode-dispatch-drift",
                rel,
                lineno,
                f"op code '{name}' ({value}) has no dispatch arm in any "
                f"protocol module: dead wire surface, or a frame the "
                f"peer sends and nobody decodes",
            )
    for name in sorted(arms):
        if name not in defined:
            rel, lineno = arms[name]
            yield Finding(
                "opcode-dispatch-drift",
                rel,
                lineno,
                f"dispatch arm compares undefined op code '{name}': the "
                f"arm can never match a real frame",
            )

    root = model.package_root()
    if root is None:
        return
    rows = _doc_op_rows(root)
    if not rows:
        return  # no protocol table anywhere: skip the doc legs
    doc_names = {name for name, _, _, _ in rows}
    for name, value, doc_rel, doc_line in rows:
        if name not in defined:
            yield Finding(
                "opcode-dispatch-drift",
                doc_rel,
                doc_line,
                f"protocol table documents op code '{name}' but no "
                f"protocol module defines it",
            )
        elif defined[name][0] != value:
            yield Finding(
                "opcode-dispatch-drift",
                doc_rel,
                doc_line,
                f"protocol table says '{name}' = {value} but the code "
                f"defines {defined[name][0]} "
                f"({defined[name][1]}:{defined[name][2]})",
            )
    for name in sorted(defined):
        if name not in doc_names:
            value, rel, lineno = defined[name]
            yield Finding(
                "opcode-dispatch-drift",
                rel,
                lineno,
                f"op code '{name}' ({value}) is missing from the "
                f"protocol tables in {' / '.join(_PROTOCOL_DOCS)}",
            )


# -- flag-bit-overlap ----------------------------------------------------------


@rule(
    "flag-bit-overlap",
    "wire flag constants share bits with each other or with op/status "
    "codes in the same byte",
    scope="program",
)
def flag_bit_overlap(model: ProgramModel) -> Iterator[Finding]:
    for mod in _protocol_modules(model):
        flags: List[Tuple[str, int, int]] = []
        codes: List[Tuple[str, int, int]] = []
        for stmt in _toplevel_stmts(mod.ctx.tree):
            bound = _single_name_assign(stmt)
            if bound is None:
                continue
            name, value, lineno = bound
            iv = _const_int(value)
            if iv is None:
                continue
            if "FLAG" in name:
                flags.append((name, iv, lineno))
            elif _OP_NAME.match(name) or _STATUS_NAME.match(name):
                codes.append((name, iv, lineno))
        for i, (a, av, _) in enumerate(flags):
            for b, bv, bline in flags[i + 1:]:
                if av & bv:
                    yield Finding(
                        "flag-bit-overlap",
                        mod.rel_path,
                        bline,
                        f"flag constants '{a}' (0x{av:02x}) and '{b}' "
                        f"(0x{bv:02x}) share bits 0x{av & bv:02x}: the "
                        f"wire field cannot represent both",
                    )
        for fname, fv, _ in flags:
            for cname, cv, cline in codes:
                if fv & cv:
                    yield Finding(
                        "flag-bit-overlap",
                        mod.rel_path,
                        cline,
                        f"'{fname}' (0x{fv:02x}) shares bits with code "
                        f"'{cname}' ({cv}): a flagged frame becomes "
                        f"indistinguishable from op 0x{fv | cv:02x}",
                    )
