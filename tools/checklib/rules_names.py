"""Name-resolution rules: the two the original checker shipped.

The heavy lifting (scope chain, load resolution) runs once in
:class:`checklib.scopes.ScopeAnalyzer` during context construction;
these rules just re-emit its problems under their registered names so
suppressions and the baseline address them like any other rule.
"""

from __future__ import annotations

from checklib.model import Finding
from checklib.registry import rule


@rule(
    "undefined-name",
    "a Name load that resolves to no binding in the scope chain",
)
def undefined_name(ctx):
    for rule_name, lineno, message in ctx.scope_problems:
        if rule_name == "undefined-name":
            yield Finding(rule_name, ctx.rel_path, lineno, message)


@rule(
    "unused-import",
    "an import binding never referenced anywhere in the module",
)
def unused_import(ctx):
    for rule_name, lineno, message in ctx.scope_problems:
        if rule_name == "unused-import":
            yield Finding(rule_name, ctx.rel_path, lineno, message)
