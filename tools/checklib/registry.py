"""The rule registry.

A rule is a generator function registered with the :func:`rule`
decorator.  ``scope`` controls where — and over what — it runs:
``"all"`` (every checked file) and ``"package"`` (shipped daemon code
under ``registrar_tpu/`` only — tests and tooling legitimately assert,
block, and poke privates) rules receive one
:class:`~checklib.context.FileContext` per file; ``"program"`` rules run
ONCE per run over the shared :class:`~checklib.program.ProgramModel`
(built from every parsed file) and may yield findings anchored in any
file — the engine routes each finding through that file's inline
suppressions, so ``# check: disable=`` works identically.

Adding a rule (the full recipe is in docs/CHECKS.md):

    @rule("my-rule", "one-line description", scope="all")
    def my_rule(ctx):
        for node in ast.walk(ctx.tree):
            ...
            yield finding(ctx, "my-rule", node, "message")

then add a seeded-violation test to tests/test_check.py and a catalog
entry to docs/CHECKS.md.  Rule names are kebab-case and stable: they are
the suppression/baseline identity.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable

from checklib.model import Finding


class Rule:
    __slots__ = ("name", "description", "scope", "func")

    def __init__(self, name: str, description: str, scope: str, func: Callable):
        self.name = name
        self.description = description
        self.scope = scope  # "all" | "package" | "program"
        self.func = func

    @property
    def is_program(self) -> bool:
        return self.scope == "program"

    def applies_to(self, ctx) -> bool:
        if self.is_program:
            return False  # runs once per run, not per file
        return self.scope == "all" or ctx.in_package

    def run(self, ctx) -> Iterable[Finding]:
        return self.func(ctx)


#: name -> Rule, in registration order (the catalog order).
RULES: Dict[str, Rule] = {}

#: Finding rules that are not produced by registered rule functions but
#: by the engine itself; they share the rule namespace so suppressions
#: and the baseline treat them uniformly.
ENGINE_RULES = {
    "syntax-error": "file does not parse; nothing else can be checked",
    "bad-suppression": "malformed suppression comment (missing justification)",
    "unused-suppression": "suppression comment that matched no finding",
    "stale-baseline": "baseline entry that no longer matches any finding",
}


def rule(name: str, description: str, scope: str = "all"):
    if scope not in ("all", "package", "program"):
        raise ValueError(f"bad rule scope {scope!r}")

    def register(func: Callable) -> Callable:
        if name in RULES or name in ENGINE_RULES:
            raise ValueError(f"duplicate rule name {name!r}")
        RULES[name] = Rule(name, description, scope, func)
        return func

    return register


def finding(ctx, rule_name: str, node, message: str) -> Finding:
    """Convenience constructor anchoring a finding at an AST node."""
    return Finding(rule_name, ctx.rel_path, getattr(node, "lineno", 0), message)
