"""Orchestration: iterate files, run the file rules, build the
whole-program model once, run the program rules over it, apply
suppressions and the baseline, render text or JSON, exit nonzero on
anything left.

Two rule generations share one run (docs/CHECKS.md):

  * file rules see one :class:`~checklib.context.FileContext` each;
  * program rules (``scope="program"``) see the single
    :class:`~checklib.program.ProgramModel` built from EVERY parsed
    file, and their findings are routed back through the target file's
    inline suppressions before the unused-suppression sweep runs.

``--changed-only`` narrows the *file-rule* pass to ``git status`` files
plus their reverse-dependency closure over the import graph; the program
model (and every program rule) still sees the full target set, so a
change that breaks a cross-module contract is reported even when the
breakage surfaces in an unchanged file.  When the changed set does not
intersect the checked files at all (a doc-only diff), the run
short-circuits to a no-op BEFORE parsing anything — the documented
pre-commit path stays instant instead of paying for a full program
model that no rule will look at.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from checklib import baseline as baseline_mod
from checklib.context import PACKAGE_PREFIX, FileContext
from checklib.model import Finding
from checklib.registry import ENGINE_RULES, RULES
from checklib.suppress import (
    apply_suppressions,
    filter_findings,
    parse_suppressions,
    unused_findings,
)

DEFAULT_TARGETS = [
    "registrar_tpu",
    "tests",
    "tools",
    "bench.py",
    "__graft_entry__.py",
]

#: The tree this checker ships in (parent of tools/).  Report/baseline
#: paths and the package-scope test anchor here, NOT at the cwd, so
#: `python tools/check.py zk` run from inside registrar_tpu/ still arms
#: the package-scoped rules and produces stable baseline keys.
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: Default baseline location, resolved relative to the tools/ directory
#: (not the cwd) so `python tools/check.py` works from anywhere.
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "check-baseline.json")


def _default_rel_path(path: str) -> str:
    """Repo-root-relative for files in this repo; cwd-relative otherwise
    (scratch trees — e.g. the seeded-violation tests — carry their own
    registrar_tpu/ prefix relative to wherever the checker runs)."""
    ap = os.path.abspath(path)
    if ap == REPO_ROOT:
        return "."  # the repo root itself (normalizes to the
        # everything-in-scope coverage prefix), not cwd-relative
    root = REPO_ROOT + os.sep
    if ap.startswith(root):
        return ap[len(root):].replace(os.sep, "/")
    return os.path.relpath(path).replace(os.sep, "/")


def iter_python_files(targets):
    for target in targets:
        if os.path.isfile(target):
            yield target
        elif os.path.isdir(target):
            for dirpath, dirnames, filenames in os.walk(target):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        else:
            # A lint gate that silently checks zero files would report
            # success on a wrong cwd or a typo'd path; fail instead.
            raise FileNotFoundError(f"check target does not exist: {target}")


def _parse_file(path: str, rel_path: str):
    """(ctx, engine_findings): ctx is None when the file doesn't parse
    (the syntax-error finding replaces every analysis)."""
    with open(path, "rb") as fh:
        source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as err:
        return None, [
            Finding(
                "syntax-error",
                rel_path,
                err.lineno or 0,
                f"syntax error: {err.msg}",
            )
        ]
    ctx = FileContext(path, rel_path, source, tree)
    problems = parse_suppressions(ctx)
    return ctx, problems


def check_file(path: str, rel_path: Optional[str] = None) -> List[Finding]:
    """All FILE-rule findings for one file, inline suppressions applied
    (the baseline is a whole-run concept and is applied by :func:`run`;
    the whole-program rules need the full program model and only run
    there too).

    ``rel_path`` overrides the reported path — the package-scoped rules
    key off it (see checklib.context.PACKAGE_PREFIX), and tests use it
    to exercise them on fixtures outside the package tree.
    """
    if rel_path is None:
        rel_path = _default_rel_path(path)
    ctx, problems = _parse_file(path, rel_path)
    if ctx is None:
        return problems
    findings: List[Finding] = []
    for rule in RULES.values():
        if rule.applies_to(ctx):
            findings.extend(rule.run(ctx))
    findings = apply_suppressions(ctx, findings)
    findings.extend(problems)
    return findings


def _git_changed_rel_paths() -> List[str]:
    """REPO_ROOT-relative paths `git status --porcelain` reports changed
    (staged, unstaged, and untracked — the pre-commit surface).

    git prints paths relative to the repository TOP-LEVEL; when this
    tree is checked out as a subdirectory of a larger repo the subdir
    prefix must be stripped (and paths outside it dropped), or the
    intersection with the checked files would be empty and the narrowed
    run would silently pass on everything."""
    proc = subprocess.run(
        ["git", "-C", REPO_ROOT, "status", "--porcelain",
         "--untracked-files=all"],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        raise ValueError(
            "--changed-only needs a git checkout: "
            + proc.stderr.strip()
        )
    prefix_proc = subprocess.run(
        ["git", "-C", REPO_ROOT, "rev-parse", "--show-prefix"],
        capture_output=True,
        text=True,
    )
    prefix = prefix_proc.stdout.strip() if prefix_proc.returncode == 0 else ""
    out: List[str] = []
    for line in proc.stdout.split("\n"):
        if len(line) < 4:
            continue
        path = line[3:]
        if " -> " in path:  # rename: the new side is the checked one
            path = path.split(" -> ", 1)[1]
        path = path.strip().strip('"')
        if not path:
            continue
        path = path.replace(os.sep, "/")
        if prefix:
            if not path.startswith(prefix):
                continue  # changed outside this tree: not ours to lint
            path = path[len(prefix):]
        out.append(path)
    return out


#: rule module (checklib.<name>) -> checker generation, for the CI job
#: summary's per-generation digest.  New rule modules must be added
#: here; unknown modules land in generation 0 so the digest makes the
#: omission visible instead of silently folding it into another bucket.
_MODULE_GENERATIONS = {
    "rules_names": 1,
    "rules_async": 1,
    "rules_hygiene": 1,
    "rules_flow": 2,
    "rules_contracts": 2,
    "rules_errors": 3,
    "locks": 4,
    "lifecycle": 4,
    "rules_protocol": 4,
    "taint": 5,
    "rules_atomicity": 5,
}


def _rule_generations() -> Dict[str, int]:
    """{"1": count, ...}: how many registered rules each checker
    generation contributes (string keys: this lands in the JSON
    report)."""
    out: Dict[str, int] = {}
    for rule in RULES.values():
        mod = rule.func.__module__.rsplit(".", 1)[-1]
        gen = _MODULE_GENERATIONS.get(mod, 0)
        out[str(gen)] = out.get(str(gen), 0) + 1
    return dict(sorted(out.items()))


def run(
    targets,
    baseline_path: Optional[str] = None,
    changed_only: bool = False,
) -> "RunResult":
    """Check every file under ``targets``; apply the baseline if given."""
    from checklib.program import ProgramModel

    t0 = time.monotonic()
    findings: List[Finding] = []
    contexts: List[FileContext] = []
    checked_rel_paths = set()
    # Directory targets define the run's *coverage*: a baseline entry
    # under one of these prefixes was either checked, or names a file
    # that no longer exists — in both cases this run may judge it stale.
    # '.' (or the repo root) normalizes to the empty prefix = everything
    # in scope, so `check.py . --baseline ...` still detects staleness.
    covered_prefixes = []
    for t in targets:
        if not os.path.isdir(t):
            continue
        rel = _default_rel_path(t)
        covered_prefixes.append(
            "" if rel in (".", "") else rel.rstrip("/") + "/"
        )
    to_parse = []
    for path in iter_python_files(targets):
        rel = _default_rel_path(path)
        if rel in checked_rel_paths:
            continue  # overlapping targets: check (and count) each file once
        checked_rel_paths.add(rel)
        to_parse.append((path, rel))

    # --changed-only with a changed set that touches NO checked file
    # (a doc-only diff): nothing this run could report depends on the
    # diff, so skip the parse and the program model entirely — the
    # pre-commit path must be instant, not "fast".
    changed = set(_git_changed_rel_paths()) if changed_only else None
    if changed is not None and not (changed & checked_rel_paths):
        return RunResult(
            [],
            0,
            0,
            lambda p: False,
            {
                "elapsed_s": round(time.monotonic() - t0, 4),
                "checked_files": 0,
                "analyzed_files": 0,
                "changed_only_noop": True,
            },
        )

    for path, rel in to_parse:
        ctx, engine_findings = _parse_file(path, rel)
        findings.extend(engine_findings)
        if ctx is not None:
            contexts.append(ctx)

    t_parse = time.monotonic()
    model = ProgramModel(contexts)

    # --changed-only: the file-rule pass narrows to changed files plus
    # everything that imports them (a helper's contract change must
    # re-lint its consumers); the program rules below still see the
    # full model.
    if changed is not None:
        narrowed_set = model.reverse_import_closure(
            {c.rel_path for c in contexts if c.rel_path in changed}
        )
        narrowed = [c for c in contexts if c.rel_path in narrowed_set]
    else:
        narrowed = contexts

    t_model = time.monotonic()
    file_rules = [r for r in RULES.values() if not r.is_program]
    program_rules = [r for r in RULES.values() if r.is_program]
    for ctx in narrowed:
        ctx_findings: List[Finding] = []
        for rule in file_rules:
            if rule.applies_to(ctx):
                ctx_findings.extend(rule.run(ctx))
        findings.extend(filter_findings(ctx, ctx_findings))

    t_file_rules = time.monotonic()
    # Program rules need a real program: a run whose directory coverage
    # does not include the package root (`check.py registrar_tpu/zk`, a
    # single file) would hand them an artificially small model and turn
    # out-of-coverage listeners/accessors into false findings — skip
    # them instead; the gate invocations (full tree, --changed-only)
    # always cover the package.
    package_covered = any(
        pre == "" or PACKAGE_PREFIX.startswith(pre)
        for pre in covered_prefixes
    )
    if not package_covered:
        program_rules = []
    if program_rules:
        # Pre-build the shared analyses HERE, outside the per-rule
        # timing loop: built lazily by the first consuming rule, the
        # escape fixpoint's cost would be double-reported — attributed
        # to that rule AND printed as the 'escape fixpoint' phase — and
        # a maintainer chasing a --max-seconds regression would profile
        # the wrong module.
        from checklib.exceptions import flow_for
        from checklib.lifecycle import lifecycle_for
        from checklib.locks import lockgraph_for
        from checklib.rules_atomicity import atomicity_for
        from checklib.taint import taint_for

        flow_for(model)
        lockgraph_for(model)
        lifecycle_for(model)
        taint_for(model)
        atomicity_for(model)
    ctx_by_path = {c.rel_path: c for c in contexts}
    program_timings: Dict[str, float] = {}
    for rule in program_rules:
        r0 = time.monotonic()
        produced = list(rule.func(model))
        by_ctx: Dict[str, List[Finding]] = {}
        passthrough: List[Finding] = []
        for f in produced:
            if f.path in ctx_by_path:
                by_ctx.setdefault(f.path, []).append(f)
            else:
                passthrough.append(f)  # docs/json targets: no directives
        for rel, fs in by_ctx.items():
            findings.extend(filter_findings(ctx_by_path[rel], fs))
        findings.extend(passthrough)
        program_timings[rule.name] = round(time.monotonic() - r0, 4)

    # Unused-suppression sweep LAST, and only over files whose file
    # rules actually ran — in a narrowed run, a suppression in an
    # unchecked file may well cover a finding this run never produced.
    for ctx in narrowed:
        findings.extend(unused_findings(ctx))

    findings.sort(key=Finding.sort_key)
    grandfathered = 0

    if changed_only:
        # Staleness in a narrowed run is only judged for files the file
        # rules covered (program findings for other files still match
        # their baseline entries; they are just never condemned here).
        narrowed_rels = {c.rel_path for c in narrowed}

        def in_scope(p):
            return p in narrowed_rels
    else:
        def in_scope(p):
            return p in checked_rel_paths or any(
                p.startswith(pre) for pre in covered_prefixes
            )

    if baseline_path is not None:
        bl = baseline_mod.load(baseline_path)
        # Same repo-root anchoring as every finding path, so the JSON
        # report's stale-baseline entries don't vary with the cwd.
        rel_bl = _default_rel_path(baseline_path)
        findings, grandfathered = baseline_mod.apply(
            findings, bl, rel_bl, in_scope=in_scope
        )
        findings.sort(key=Finding.sort_key)
    t_end = time.monotonic()
    stats = {
        "elapsed_s": round(t_end - t0, 4),
        "parse_s": round(t_parse - t0, 4),
        "model_s": round(t_model - t_parse, 4),
        "file_rules_s": round(t_file_rules - t_model, 4),
        "program_rules_s": {
            k: v for k, v in sorted(program_timings.items())
        },
        "checked_files": len(checked_rel_paths),
        "analyzed_files": len(narrowed),
        "program": model.stats(),
    }
    graph = getattr(model, "_callgraph", None)
    if graph is not None:
        stats["program"].update(graph.stats())
    flow = getattr(model, "_excflow", None)
    if flow is not None:
        # the exception-escape phase (generation 3): built lazily by the
        # first errors rule, shared by the rest; its fixpoint cost is
        # what --max-seconds is guarding against growing quadratic
        stats["program"].update(flow.stats())
    lockg = getattr(model, "_lockgraph", None)
    if lockg is not None:
        stats["program"].update(lockg.stats())
    lifecycle = getattr(model, "_lifecycle", None)
    if lifecycle is not None:
        stats["program"].update(lifecycle.stats())
    taint = getattr(model, "_taint", None)
    if taint is not None:
        stats["program"].update(taint.stats())
    atomicity = getattr(model, "_atomicity", None)
    if atomicity is not None:
        stats["program"].update(atomicity.stats())
    stats["rule_generations"] = _rule_generations()
    return RunResult(
        findings, len(checked_rel_paths), grandfathered, in_scope, stats
    )


class RunResult:
    __slots__ = (
        "findings", "checked_files", "grandfathered", "in_scope", "stats",
    )

    def __init__(
        self, findings, checked_files, grandfathered, in_scope=None,
        stats=None,
    ):
        self.findings = findings
        self.checked_files = checked_files
        self.grandfathered = grandfathered
        #: rel-path -> bool: was this path covered by the run's targets?
        self.in_scope = in_scope or (lambda p: True)
        self.stats = stats or {}

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "checked_files": self.checked_files,
            "grandfathered": self.grandfathered,
            "problem_count": len(self.findings),
            "problems": [f.to_dict() for f in self.findings],
            "stats": self.stats,
        }


def _render_text(result: RunResult, out) -> None:
    for f in result.findings:
        print(f.render(), file=out)


def _summary(result: RunResult) -> str:
    extra = (
        f" ({result.grandfathered} grandfathered by baseline)"
        if result.grandfathered
        else ""
    )
    return (
        f"check: {len(result.findings)} problem(s) in "
        f"{result.checked_files} file(s){extra}"
    )


def _render_stats(result: RunResult) -> str:
    s = result.stats
    if s.get("changed_only_noop"):
        return (
            "check --stats: --changed-only: no checked file changed; "
            f"analysis skipped (total {s.get('elapsed_s', 0):.3f}s)"
        )
    prog = s.get("program", {})
    rule_times = ", ".join(
        f"{k}={v:.3f}s" for k, v in s.get("program_rules_s", {}).items()
    )
    return (
        "check --stats: "
        f"{s.get('checked_files', 0)} files "
        f"({s.get('analyzed_files', 0)} through file rules), "
        f"{prog.get('modules', 0)} modules, "
        f"{prog.get('import_edges', 0)} import edges, "
        f"{prog.get('functions', 0)} functions, "
        f"{prog.get('call_sites', 0)} call sites, "
        f"{prog.get('resolved_edges', 0)} resolved call edges, "
        f"{prog.get('event_sites', 0)} event sites; "
        f"parse {s.get('parse_s', 0):.3f}s, "
        f"model {s.get('model_s', 0):.3f}s, "
        f"file rules {s.get('file_rules_s', 0):.3f}s, "
        f"escape fixpoint {prog.get('escape_build_s', 0):.3f}s "
        f"({prog.get('escape_functions', 0)} functions, "
        f"{prog.get('escape_iterations', 0)} rounds), "
        f"lock graph {prog.get('lock_build_s', 0):.3f}s "
        f"({prog.get('lock_sites', 0)} sites, "
        f"{prog.get('lock_edges', 0)} edges), "
        f"lifecycle fixpoint {prog.get('lifecycle_build_s', 0):.3f}s "
        f"({prog.get('lifecycle_tracked', 0)} resources), "
        f"taint fixpoint {prog.get('taint_build_s', 0):.3f}s "
        f"({prog.get('taint_sources', 0)} sources, "
        f"{prog.get('taint_sinks', 0)} sinks, "
        f"{prog.get('taint_sanitized', 0)} sanitized), "
        f"atomicity scan {prog.get('atomicity_build_s', 0):.3f}s "
        f"({prog.get('atomicity_tracked', 0)} tracked attrs), "
        f"program rules [{rule_times}]; "
        f"total {s.get('elapsed_s', 0):.3f}s"
    )


#: SARIF 2.1.0 (the GitHub code-scanning ingestion format): findings
#: become `results`, rule metadata rides in the tool.driver block, and
#: whole-program chain evidence maps onto codeFlows/threadFlows so the
#: annotation UI can walk the call chain hop by hop.
_SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def _render_sarif(result: RunResult, out) -> None:
    rules_meta = [
        {
            "id": r.name,
            "shortDescription": {"text": r.description},
            "defaultConfiguration": {"level": "error"},
        }
        for r in RULES.values()
    ] + [
        {
            "id": name,
            "shortDescription": {"text": desc},
            "defaultConfiguration": {"level": "error"},
        }
        for name, desc in ENGINE_RULES.items()
    ]
    results = []
    for f in result.findings:
        entry = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        # SARIF regions are 1-based; line 0 ("whole
                        # file") findings anchor at the first line
                        "region": {"startLine": max(f.line, 1)},
                    }
                }
            ],
        }
        if f.chain:
            entry["codeFlows"] = [
                {
                    "threadFlows": [
                        {
                            "locations": [
                                {
                                    "location": {
                                        "physicalLocation": {
                                            "artifactLocation": {
                                                "uri": hop["path"]
                                            },
                                            "region": {
                                                "startLine": max(
                                                    hop["line"], 1
                                                )
                                            },
                                        },
                                        "message": {"text": hop["symbol"]},
                                    }
                                }
                                for hop in f.chain
                            ]
                        }
                    ]
                }
            ]
        results.append(entry)
    doc = {
        "version": "2.1.0",
        "$schema": _SARIF_SCHEMA,
        "runs": [
            {
                "tool": {
                    "driver": {
                        # informationUri is omitted: the spec requires
                        # an ABSOLUTE URI and this tree has no canonical
                        # home to point at; docs/CHECKS.md is the
                        # operator-facing reference
                        "name": "checklib",
                        "rules": rules_meta,
                    }
                },
                "results": results,
            }
        ],
    }
    json.dump(doc, out, indent=2)
    out.write("\n")


def _list_rules() -> str:
    lines = ["rules (suppress with '# check: disable=<rule> -- <why>'):"]
    for rule in RULES.values():
        if rule.is_program:
            where = "  [whole-program]"
        elif rule.scope == "package":
            where = "  [package-only]"
        else:
            where = ""
        lines.append(f"  {rule.name:24s} {rule.description}{where}")
    lines.append("engine findings (not directly suppressible rules):")
    for name, desc in ENGINE_RULES.items():
        lines.append(f"  {name:24s} {desc}")
    return "\n".join(lines)


def main(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="check",
        description="In-tree static analysis gate (see docs/CHECKS.md).",
    )
    parser.add_argument(
        "targets", nargs="*", help="files/directories (default: the tree)"
    )
    parser.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default="text",
        dest="fmt",
    )
    parser.add_argument(
        "--output", help="write the report here instead of stdout"
    )
    parser.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="baseline file (default: tools/check-baseline.json)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report grandfathered findings too",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="regenerate the baseline from the current findings and exit",
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="file rules only on `git status` files + their reverse-"
        "dependency closure; program rules still see the full target "
        "set (the fast pre-commit path)",
    )
    parser.add_argument(
        "--stats",
        action="store_true",
        help="print a program-model/timing summary to stderr",
    )
    parser.add_argument(
        "--max-seconds",
        type=float,
        default=None,
        help="fail (exit 1) when the run exceeds this wall-clock budget "
        "(the CI guard against an analysis-cost regression)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv[1:])

    if args.list_rules:
        print(_list_rules())
        return 0

    targets = args.targets or DEFAULT_TARGETS
    try:
        if args.write_baseline:
            result = run(targets, baseline_path=None)
            # Engine findings can NEVER be grandfathered: a baselined
            # syntax-error would green-light a file no rule analyzes at
            # all, and suppression problems are trivially fixable.
            rule_findings = [f for f in result.findings if f.rule in RULES]
            excluded = [f for f in result.findings if f.rule not in RULES]
            # A partial-target rewrite must PRESERVE entries for files
            # outside its coverage — otherwise `check.py a.py
            # --write-baseline` would silently drop every other file's
            # grandfathered findings and turn the next full gate red.
            preserved = [
                Finding(rule_name, path, 0, message)
                for (path, rule_name, message), n in sorted(
                    baseline_mod.load(args.baseline).items()
                )
                for _ in range(n)
                if not result.in_scope(path)
            ]
            count = baseline_mod.write(
                args.baseline, rule_findings + preserved
            )
            print(f"check: wrote {count} finding(s) to {args.baseline}")
            if excluded:
                for f in excluded:
                    print(f.render())
                print(
                    f"check: {len(excluded)} engine finding(s) cannot be "
                    "grandfathered; fix them",
                    file=sys.stderr,
                )
                return 1
            return 0
        result = run(
            targets,
            baseline_path=None if args.no_baseline else args.baseline,
            changed_only=args.changed_only,
        )
    except (FileNotFoundError, ValueError) as err:
        print(f"check: {err}", file=sys.stderr)
        return 2

    out = sys.stdout
    close = None
    if args.output:
        out = close = open(args.output, "w", encoding="utf-8")
    try:
        if args.fmt == "json":
            json.dump(result.to_dict(), out, indent=2)
            out.write("\n")
        elif args.fmt == "sarif":
            _render_sarif(result, out)
        else:
            _render_text(result, out)
    finally:
        if close is not None:
            close.close()

    if args.stats:
        print(_render_stats(result), file=sys.stderr)

    if args.max_seconds is not None:
        elapsed = result.stats.get("elapsed_s", 0.0)
        if elapsed > args.max_seconds:
            print(
                f"check: analysis took {elapsed:.2f}s, over the "
                f"--max-seconds {args.max_seconds:.2f}s budget "
                "(quadratic fixpoint regression?)",
                file=sys.stderr,
            )
            return 1

    if result.findings:
        print(_summary(result), file=sys.stderr)
        return 1
    return 0
