"""Orchestration: iterate files, run rules, apply suppressions and the
baseline, render text or JSON, exit nonzero on anything left."""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from typing import List, Optional

from checklib import baseline as baseline_mod
from checklib.context import FileContext
from checklib.model import Finding
from checklib.registry import ENGINE_RULES, RULES
from checklib.suppress import apply_suppressions, parse_suppressions

DEFAULT_TARGETS = [
    "registrar_tpu",
    "tests",
    "tools",
    "bench.py",
    "__graft_entry__.py",
]

#: The tree this checker ships in (parent of tools/).  Report/baseline
#: paths and the package-scope test anchor here, NOT at the cwd, so
#: `python tools/check.py zk` run from inside registrar_tpu/ still arms
#: the package-scoped rules and produces stable baseline keys.
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: Default baseline location, resolved relative to the tools/ directory
#: (not the cwd) so `python tools/check.py` works from anywhere.
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "check-baseline.json")


def _default_rel_path(path: str) -> str:
    """Repo-root-relative for files in this repo; cwd-relative otherwise
    (scratch trees — e.g. the seeded-violation tests — carry their own
    registrar_tpu/ prefix relative to wherever the checker runs)."""
    ap = os.path.abspath(path)
    if ap == REPO_ROOT:
        return "."  # the repo root itself (normalizes to the
        # everything-in-scope coverage prefix), not cwd-relative
    root = REPO_ROOT + os.sep
    if ap.startswith(root):
        return ap[len(root):].replace(os.sep, "/")
    return os.path.relpath(path).replace(os.sep, "/")


def iter_python_files(targets):
    for target in targets:
        if os.path.isfile(target):
            yield target
        elif os.path.isdir(target):
            for dirpath, dirnames, filenames in os.walk(target):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        else:
            # A lint gate that silently checks zero files would report
            # success on a wrong cwd or a typo'd path; fail instead.
            raise FileNotFoundError(f"check target does not exist: {target}")


def check_file(path: str, rel_path: Optional[str] = None) -> List[Finding]:
    """All findings for one file, inline suppressions applied (the
    baseline is a whole-run concept and is applied by :func:`run`).

    ``rel_path`` overrides the reported path — the package-scoped rules
    key off it (see checklib.context.PACKAGE_PREFIX), and tests use it
    to exercise them on fixtures outside the package tree.
    """
    if rel_path is None:
        rel_path = _default_rel_path(path)
    with open(path, "rb") as fh:
        source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as err:
        return [
            Finding(
                "syntax-error",
                rel_path,
                err.lineno or 0,
                f"syntax error: {err.msg}",
            )
        ]
    ctx = FileContext(path, rel_path, source, tree)
    problems = parse_suppressions(ctx)
    findings: List[Finding] = []
    for rule in RULES.values():
        if rule.applies_to(ctx):
            findings.extend(rule.run(ctx))
    findings = apply_suppressions(ctx, findings)
    findings.extend(problems)
    return findings


def run(
    targets,
    baseline_path: Optional[str] = None,
) -> "RunResult":
    """Check every file under ``targets``; apply the baseline if given."""
    findings: List[Finding] = []
    checked_rel_paths = set()
    # Directory targets define the run's *coverage*: a baseline entry
    # under one of these prefixes was either checked, or names a file
    # that no longer exists — in both cases this run may judge it stale.
    # '.' (or the repo root) normalizes to the empty prefix = everything
    # in scope, so `check.py . --baseline ...` still detects staleness.
    covered_prefixes = []
    for t in targets:
        if not os.path.isdir(t):
            continue
        rel = _default_rel_path(t)
        covered_prefixes.append(
            "" if rel in (".", "") else rel.rstrip("/") + "/"
        )
    for path in iter_python_files(targets):
        rel = _default_rel_path(path)
        if rel in checked_rel_paths:
            continue  # overlapping targets: check (and count) each file once
        checked_rel_paths.add(rel)
        findings.extend(check_file(path, rel_path=rel))
    findings.sort(key=Finding.sort_key)
    grandfathered = 0

    def in_scope(p):
        return p in checked_rel_paths or any(
            p.startswith(pre) for pre in covered_prefixes
        )

    if baseline_path is not None:
        bl = baseline_mod.load(baseline_path)
        # Same repo-root anchoring as every finding path, so the JSON
        # report's stale-baseline entries don't vary with the cwd.
        rel_bl = _default_rel_path(baseline_path)
        findings, grandfathered = baseline_mod.apply(
            findings, bl, rel_bl, in_scope=in_scope
        )
        findings.sort(key=Finding.sort_key)
    return RunResult(findings, len(checked_rel_paths), grandfathered, in_scope)


class RunResult:
    __slots__ = ("findings", "checked_files", "grandfathered", "in_scope")

    def __init__(self, findings, checked_files, grandfathered, in_scope=None):
        self.findings = findings
        self.checked_files = checked_files
        self.grandfathered = grandfathered
        #: rel-path -> bool: was this path covered by the run's targets?
        self.in_scope = in_scope or (lambda p: True)

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "checked_files": self.checked_files,
            "grandfathered": self.grandfathered,
            "problem_count": len(self.findings),
            "problems": [f.to_dict() for f in self.findings],
        }


def _render_text(result: RunResult, out) -> None:
    for f in result.findings:
        print(f.render(), file=out)


def _summary(result: RunResult) -> str:
    extra = (
        f" ({result.grandfathered} grandfathered by baseline)"
        if result.grandfathered
        else ""
    )
    return (
        f"check: {len(result.findings)} problem(s) in "
        f"{result.checked_files} file(s){extra}"
    )


def _list_rules() -> str:
    lines = ["rules (suppress with '# check: disable=<rule> -- <why>'):"]
    for rule in RULES.values():
        where = "" if rule.scope == "all" else "  [package-only]"
        lines.append(f"  {rule.name:24s} {rule.description}{where}")
    lines.append("engine findings (not directly suppressible rules):")
    for name, desc in ENGINE_RULES.items():
        lines.append(f"  {name:24s} {desc}")
    return "\n".join(lines)


def main(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="check",
        description="In-tree static analysis gate (see docs/CHECKS.md).",
    )
    parser.add_argument(
        "targets", nargs="*", help="files/directories (default: the tree)"
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text", dest="fmt"
    )
    parser.add_argument(
        "--output", help="write the report here instead of stdout"
    )
    parser.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="baseline file (default: tools/check-baseline.json)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report grandfathered findings too",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="regenerate the baseline from the current findings and exit",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv[1:])

    if args.list_rules:
        print(_list_rules())
        return 0

    targets = args.targets or DEFAULT_TARGETS
    try:
        if args.write_baseline:
            result = run(targets, baseline_path=None)
            # Engine findings can NEVER be grandfathered: a baselined
            # syntax-error would green-light a file no rule analyzes at
            # all, and suppression problems are trivially fixable.
            rule_findings = [f for f in result.findings if f.rule in RULES]
            excluded = [f for f in result.findings if f.rule not in RULES]
            # A partial-target rewrite must PRESERVE entries for files
            # outside its coverage — otherwise `check.py a.py
            # --write-baseline` would silently drop every other file's
            # grandfathered findings and turn the next full gate red.
            preserved = [
                Finding(rule_name, path, 0, message)
                for (path, rule_name, message), n in sorted(
                    baseline_mod.load(args.baseline).items()
                )
                for _ in range(n)
                if not result.in_scope(path)
            ]
            count = baseline_mod.write(
                args.baseline, rule_findings + preserved
            )
            print(f"check: wrote {count} finding(s) to {args.baseline}")
            if excluded:
                for f in excluded:
                    print(f.render())
                print(
                    f"check: {len(excluded)} engine finding(s) cannot be "
                    "grandfathered; fix them",
                    file=sys.stderr,
                )
                return 1
            return 0
        result = run(
            targets,
            baseline_path=None if args.no_baseline else args.baseline,
        )
    except (FileNotFoundError, ValueError) as err:
        print(f"check: {err}", file=sys.stderr)
        return 2

    out = sys.stdout
    close = None
    if args.output:
        out = close = open(args.output, "w", encoding="utf-8")
    try:
        if args.fmt == "json":
            json.dump(result.to_dict(), out, indent=2)
            out.write("\n")
        else:
            _render_text(result, out)
    finally:
        if close is not None:
            close.close()

    if result.findings:
        print(_summary(result), file=sys.stderr)
        return 1
    return 0
