"""Inline suppression comments.

Syntax (the justification after ``--`` is REQUIRED — a suppression that
doesn't say why is itself a finding)::

    something_flagged()  # check: disable=rule-name -- why this is safe
    # check: disable=rule-a,rule-b -- standalone form covers the NEXT line

A suppression on a code line covers findings of the listed rules on that
line; a standalone comment line covers the next non-blank, non-comment
line (so multi-clause statements can carry a suppression without blowing
the line length).  A suppression that matches no finding is reported as
``unused-suppression`` — stale opt-outs must not outlive the code they
excused.

Comments are found with :mod:`tokenize`, not a line regex, so the
directive text inside a string literal (e.g. a checker test fixture) is
never mistaken for a live suppression.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Iterator, List, Tuple

from checklib.model import Finding
from checklib.registry import ENGINE_RULES, RULES

_PATTERN = re.compile(
    r"#\s*check:\s*disable=(?P<rules>[A-Za-z0-9,_-]+)"
    r"(?:\s+--\s*(?P<why>.*\S))?"
)


class Suppression:
    __slots__ = (
        "line", "target_line", "target_end", "rules", "why", "used_rules",
    )

    def __init__(
        self,
        line: int,
        target_line: int,
        target_end: int,
        rules: List[str],
        why: str,
    ):
        self.line = line  # where the comment sits (for reporting)
        # [target_line, target_end]: the line span whose findings it
        # covers — a statement's full extent (its header only, for
        # compound statements), so a wrapped `def f(\n items=[],\n):`
        # can carry one suppression without it leaking into the body.
        self.target_line = target_line
        self.target_end = target_end
        self.rules = rules
        self.why = why
        # Tracked per rule: in `disable=a,b` where only `a` ever
        # matches, the stale `b` must still be reported as unused.
        self.used_rules: set = set()


def _stmt_spans(tree) -> list:
    """(start, end) line spans a suppression binds to: each statement's
    full extent, clamped to just above its body for compound statements
    (a comment above a def covers the signature's wrapped default
    arguments, NOT every finding in the body), and starting at the
    first decorator for decorated defs/classes."""
    spans = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        end = getattr(node, "end_lineno", None) or node.lineno
        body = getattr(node, "body", None)
        if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
            end = min(end, max(node.lineno, body[0].lineno - 1))
        start = node.lineno
        decorators = getattr(node, "decorator_list", None)
        if decorators:
            start = min(start, decorators[0].lineno)
        spans.append((start, end))
    return spans


def _covering_span(spans, line):
    """The innermost statement span containing ``line`` (so a trailing
    comment on a continuation line binds to the whole statement —
    including the finding anchored at its first line)."""
    best = None
    for start, end in spans:
        if start <= line <= end and (best is None or start > best[0]):
            best = (start, end)
    return best


def _iter_comments(text: str) -> Iterator[Tuple[int, int, str]]:
    """(line, column, comment-text) for every real comment token."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.start[1], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return  # the ast parse already vouched for the file; be lenient


def parse_suppressions(ctx) -> List[Finding]:
    """Attach ``ctx.suppressions``; return malformed-comment findings."""
    problems: List[Finding] = []
    suppressions: List[Suppression] = []
    lines = ctx.source_lines
    spans = _stmt_spans(ctx.tree)
    for lineno, col, comment in _iter_comments(ctx.source_text):
        m = _PATTERN.search(comment)
        if m is None:
            continue
        rules = [r for r in m.group("rules").split(",") if r]
        why = (m.group("why") or "").strip()
        if not rules:
            # `disable=,` must not be silently inert — no-op opt-outs
            # are findings, per the module invariant.
            problems.append(
                Finding(
                    "bad-suppression",
                    ctx.rel_path,
                    lineno,
                    "suppression names no rules "
                    "(write '# check: disable=<rule> -- <why>')",
                )
            )
            continue
        unknown = [
            r for r in rules if r not in RULES and r not in ENGINE_RULES
        ]
        engine = [r for r in rules if r in ENGINE_RULES]
        if unknown:
            problems.append(
                Finding(
                    "bad-suppression",
                    ctx.rel_path,
                    lineno,
                    f"suppression names unknown rule(s): {', '.join(unknown)}",
                )
            )
            continue
        if engine:
            # Engine findings are emitted outside the suppression pass
            # (a syntax-error precludes it entirely), so a disable=
            # naming one could only ever surface as a baffling
            # unused-suppression — say what is actually wrong instead.
            problems.append(
                Finding(
                    "bad-suppression",
                    ctx.rel_path,
                    lineno,
                    "engine finding(s) cannot be suppressed: "
                    + ", ".join(engine)
                    + " — fix them instead",
                )
            )
            continue
        if not why:
            problems.append(
                Finding(
                    "bad-suppression",
                    ctx.rel_path,
                    lineno,
                    "suppression lacks a justification "
                    "(write '# check: disable=<rule> -- <why>')",
                )
            )
            continue
        target = lineno
        if not lines[lineno - 1][:col].strip():
            # Standalone comment: covers the next non-blank, non-comment
            # line (or nothing, which unused-suppression will report).
            for j in range(lineno, len(lines)):
                stripped = lines[j].strip()
                if stripped and not stripped.startswith("#"):
                    target = j + 1
                    break
        # The covered span is the whole statement the target line falls
        # in, so a trailing comment on a continuation line suppresses
        # the finding anchored at the statement's first line, and a
        # comment above a wrapped signature covers its default
        # arguments.
        span = _covering_span(spans, target) or (target, target)
        suppressions.append(
            Suppression(lineno, span[0], span[1], rules, why)
        )
    ctx.suppressions = suppressions
    return problems


def filter_findings(ctx, findings: List[Finding]) -> List[Finding]:
    """Drop findings covered by this file's suppressions (marking the
    matched rules used).  The unused-suppression sweep is separate —
    the engine runs it only after EVERY producer (file rules first,
    whole-program rules later) has had its findings routed through."""
    kept: List[Finding] = []
    for f in findings:
        suppressed = False
        for s in ctx.suppressions:
            if s.target_line <= f.line <= s.target_end and f.rule in s.rules:
                s.used_rules.add(f.rule)
                suppressed = True
        if not suppressed:
            kept.append(f)
    return kept


def unused_findings(ctx, exempt=frozenset()) -> List[Finding]:
    """unused-suppression findings for directives nothing matched.

    ``exempt`` rules are never reported stale — the single-file path
    passes the program-scoped rule names, since those rules did not run
    and their suppressions legitimately matched nothing."""
    out: List[Finding] = []
    for s in ctx.suppressions:
        stale = [
            r for r in s.rules
            if r not in s.used_rules and r not in exempt
        ]
        if stale:
            out.append(
                Finding(
                    "unused-suppression",
                    ctx.rel_path,
                    s.line,
                    "suppression of "
                    + ", ".join(f"'{r}'" for r in stale)
                    + " matched no finding; remove it",
                )
            )
    return out


def apply_suppressions(ctx, findings: List[Finding]) -> List[Finding]:
    """One-shot filter + unused sweep (the single-file check_file path).

    Program-rule suppressions are exempt from the unused sweep here:
    check_file runs file rules only, so a directive the full gate
    REQUIRES (e.g. the drain walk's await-in-lock-free-mutator opt-out)
    must not read as "matched no finding; remove it"."""
    program_rules = frozenset(
        name for name, r in RULES.items() if r.is_program
    )
    return filter_findings(ctx, findings) + unused_findings(
        ctx, exempt=program_rules
    )
