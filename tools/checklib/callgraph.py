"""Call-graph construction and the interprocedural fixpoints.

Built lazily on top of :class:`checklib.program.ProgramModel`: every
call site is resolved (or deliberately left unresolved — see program.py
for the conservatism contract) to one of

  * ``("func", FunctionInfo)`` — a function/method the model holds;
  * ``("ext", "dotted.name")`` — a callable outside the model whose full
    dotted name is still known (``time.sleep``, ``subprocess.run``);
  * ``None`` — unknown (shadowed name, object-attribute dispatch,
    degraded module, dynamic anything).

On the resolved edges three analyses run:

  * **blocking facts** — per function, the event-loop-blocking
    primitives it calls *directly* (the rules_async.BLOCKING_CALLS set
    plus write-mode ``open``), and from those the shortest sync-only
    call chain from any function to a blocking primitive;
  * **lock protection** — a greatest fixpoint marking functions whose
    every resolved incoming call edge is protected by the single-flight
    lock (``async with <...lock>`` lexically, or a caller that is itself
    always-locked);
  * **mutator chains** — shortest resolved chain from a call site to a
    ZooKeeper-mutating primitive (program.ZK_MUTATORS), skipping
    interior call sites that are already under a lexical lock block
    (those sites honor the invariant on their own).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from checklib.program import (
    CallSite,
    FunctionInfo,
    ModuleInfo,
    ProgramModel,
    ZK_MUTATORS,
)
from checklib.rules_async import BLOCKING_CALLS, _open_mode


class CallGraph:
    def __init__(self, model: ProgramModel):
        self.model = model
        #: FunctionInfo -> list of (caller CallSite) — resolved edges in
        self.callers: Dict[FunctionInfo, List[CallSite]] = {}
        #: CallSite -> resolution (computed once, cached)
        self._resolved: Dict[int, object] = {}
        self.edge_count = 0
        for site in model.all_call_sites():
            res = self.resolve(site)
            if res is not None and res[0] == "func":
                self.callers.setdefault(res[1], []).append(site)
                self.edge_count += 1
        self._always_locked: Optional[Set[FunctionInfo]] = None
        self._blocking_facts: Optional[
            Dict[FunctionInfo, List[Tuple[str, int]]]
        ] = None

    # -- resolution -------------------------------------------------------

    def resolve(self, site: CallSite):
        key = id(site)
        if key not in self._resolved:
            self._resolved[key] = self._resolve(site)
        return self._resolved[key]

    def _resolve(self, site: CallSite):
        mod = site.func.module
        if site.shape[0] == "opaque":
            return None
        if site.shape[0] == "name":
            return self._resolve_name(site, mod, site.shape[1])
        base, attrs = site.shape[1], site.shape[2]
        if base in ("self", "cls"):
            if len(attrs) != 1 or site.func.cls is None:
                return None
            return self._resolve_method(mod, site.func.cls, attrs[0])
        if base in site.func.param_chain():
            return None  # the receiver is a parameter: unknown object
        target = self._module_binding_target(mod, base)
        if target is None:
            return None
        kind, value = target
        if kind == "module":
            return self._resolve_module_attr(value, attrs)
        if kind == "ext":
            return "ext", value + "." + ".".join(attrs)
        return None  # class/def/assign receiver: object dispatch

    def _resolve_name(self, site: CallSite, mod: ModuleInfo, name: str):
        if name in site.func.param_chain():
            return None
        # nested defs of the enclosing function chain win first
        f: Optional[FunctionInfo] = site.func
        while f is not None:
            if name in f.children:
                return "func", f.children[name]
            f = f.parent
        if mod.degraded:
            return None
        kinds = mod.bindings.get(name)
        if kinds is None or len(kinds) != 1:
            return None  # unbound here, or ambiguous (re-bound)
        kind = next(iter(kinds))
        if kind == "def":
            target = mod.functions.get(name)
            return ("func", target) if target is not None else None
        if kind == "import":
            if name in mod.from_imports:
                source, orig = mod.from_imports[name]
                sub = f"{source}.{orig}"
                if sub in self.model.modules:
                    return None  # a module object called bare: not a call
                if source in self.model.modules:
                    target = self.model.modules[source].functions.get(orig)
                    return ("func", target) if target is not None else None
                return "ext", f"{source}.{orig}"
            # `import x` then bare `x()` — not a function call we model
            return None
        return None

    def _resolve_method(self, mod: ModuleInfo, cls_name: str, attr: str):
        seen: Set[str] = set()
        frontier = [(mod, cls_name)]
        while frontier:
            m, cname = frontier.pop()
            if (m.name, cname) in seen:
                continue
            seen.add((m.name, cname))
            cls = m.classes.get(cname)
            if cls is None:
                continue
            if attr in cls.methods:
                return "func", cls.methods[attr]
            for base, battrs in cls.bases:
                resolved = self._resolve_class_ref(m, base, battrs)
                if resolved is not None:
                    frontier.append(resolved)
        return None

    def _resolve_class_ref(self, mod: ModuleInfo, base: str, attrs):
        """(module, class-name) for a base-class expression, if the model
        can see it."""
        if not attrs:
            if base in mod.classes:
                return mod, base
            src = mod.from_imports.get(base)
            if src is not None and src[0] in self.model.modules:
                target = self.model.modules[src[0]]
                if src[1] in target.classes:
                    return target, src[1]
            return None
        if len(attrs) == 1 and base in mod.imports:
            target_name = mod.imports[base]
            target = self.model.modules.get(target_name)
            if target is not None and attrs[0] in target.classes:
                return target, attrs[0]
        return None

    def _module_binding_target(self, mod: ModuleInfo, base: str):
        """What a dotted call's base name IS at module level: a model or
        external module, or an in-model object (class/def) — None when
        ambiguous or unknown."""
        if mod.degraded:
            return None
        kinds = mod.bindings.get(base)
        if kinds is None or len(kinds) != 1:
            return None
        kind = next(iter(kinds))
        if kind != "import":
            return ("obj", base) if kind in ("class", "def") else None
        if base in mod.imports:
            target = mod.imports[base]
            if target in self.model.modules:
                return "module", self.model.modules[target]
            return "ext", target
        if base in mod.from_imports:
            source, orig = mod.from_imports[base]
            sub = f"{source}.{orig}"
            if sub in self.model.modules:
                return "module", self.model.modules[sub]
            if source in self.model.modules:
                src_mod = self.model.modules[source]
                if orig in src_mod.classes:
                    return "obj", orig
                return None  # some in-model object we can't follow
            return "ext", f"{source}.{orig}"
        return None

    def _resolve_module_attr(self, target: ModuleInfo, attrs):
        if len(attrs) == 1:
            fn = target.functions.get(attrs[0])
            if fn is not None:
                return "func", fn
        return None

    # -- blocking facts ---------------------------------------------------

    def blocking_facts(self) -> Dict[FunctionInfo, List[Tuple[str, int]]]:
        """function -> [(primitive, lineno)] it calls *directly*."""
        if self._blocking_facts is not None:
            return self._blocking_facts
        facts: Dict[FunctionInfo, List[Tuple[str, int]]] = {}
        for site in self.model.all_call_sites():
            prim = self.blocking_primitive(site)
            if prim is not None:
                facts.setdefault(site.func, []).append(
                    (prim, site.lineno)
                )
        self._blocking_facts = facts
        return facts

    def blocking_primitive(self, site: CallSite) -> Optional[str]:
        """The loop-blocking primitive this site calls, if any."""
        if site.shape[0] == "name":
            if site.shape[1] == "open" and "open" not in (
                site.func.param_chain()
            ):
                mode = _open_mode(site.node)
                if mode is not None and any(c in mode for c in "wax+"):
                    return f"open(..., {mode!r})"
            res = self.resolve(site)
            if res is not None and res[0] == "ext" and res[1] in BLOCKING_CALLS:
                return res[1]
            return None
        if site.shape[0] == "dotted":
            dotted = ".".join((site.shape[1],) + site.shape[2])
            if dotted in BLOCKING_CALLS:
                # only when the base really is that module (not shadowed)
                if site.shape[1] not in site.func.param_chain():
                    return dotted
            res = self.resolve(site)
            if res is not None and res[0] == "ext" and res[1] in BLOCKING_CALLS:
                return res[1]
        return None

    def blocking_chain(
        self, start: FunctionInfo
    ) -> Optional[List[Tuple[str, str, int]]]:
        """Shortest sync-only chain ``[(func-ref, rel_path, line), ...,
        (primitive, rel_path, line)]`` from ``start`` (a sync function)
        to a blocking primitive, or None."""
        facts = self.blocking_facts()
        seen: Set[FunctionInfo] = {start}
        queue: deque = deque([(start, [])])
        while queue:
            func, path = queue.popleft()
            direct = facts.get(func)
            if direct:
                prim, line = direct[0]
                return path + [
                    (func.ref, func.module.rel_path, func.lineno),
                    (prim, func.module.rel_path, line),
                ]
            for site in func.calls:
                res = self.resolve(site)
                if res is None or res[0] != "func":
                    continue
                callee = res[1]
                if callee.is_async or callee in seen:
                    continue
                seen.add(callee)
                queue.append(
                    (
                        callee,
                        path + [(func.ref, func.module.rel_path,
                                 site.lineno)],
                    )
                )
        return None

    # -- lock protection --------------------------------------------------

    def always_locked(self) -> Set[FunctionInfo]:
        """Functions whose every resolved incoming call edge is lock-
        protected.  Greatest fixpoint: start from "every function with at
        least one caller", then drop any with an unprotected edge until
        stable.  (A call cycle with no outside caller stays optimistic —
        the conservative direction for a *reporting* rule is fewer
        findings, never a guessed one.)"""
        if self._always_locked is not None:
            return self._always_locked
        locked = {f for f in self.callers if self.callers[f]}
        changed = True
        while changed:
            changed = False
            for func in list(locked):
                for site in self.callers[func]:
                    if site.under_lock:
                        continue
                    if site.func in locked:
                        continue
                    locked.discard(func)
                    changed = True
                    break
        self._always_locked = locked
        return locked

    # -- mutator chains ---------------------------------------------------

    def mutator_primitive(self, site: CallSite) -> Optional[str]:
        """``zk.put``-style ZooKeeper mutator at this site, if any.

        The receiver must be an *opaque object* (a parameter, ``self``,
        a local) — a base resolving to a module (``os.unlink``) or to a
        model class/def (``Op.delete`` building a request) is something
        else wearing the same method name."""
        if site.shape[0] != "dotted":
            return None
        base, attrs = site.shape[1], site.shape[2]
        if attrs[-1] not in ZK_MUTATORS:
            return None
        if base not in ("self", "cls") and base not in (
            site.func.param_chain()
        ):
            if self._module_binding_target(site.func.module, base) is not None:
                return None
        return ".".join((base,) + attrs)

    def mutator_chain(
        self, site: CallSite
    ) -> Optional[List[Tuple[str, str, int]]]:
        """Shortest chain from ``site`` to a ZK mutator primitive through
        resolved, *unlocked* interior call sites.  The site itself being
        a primitive yields a single-hop chain."""
        prim = self.mutator_primitive(site)
        start_hop = (site.func.ref, site.func.module.rel_path, site.lineno)
        if prim is not None:
            return [start_hop, (prim, site.func.module.rel_path,
                                site.lineno)]
        res = self.resolve(site)
        if res is None or res[0] != "func":
            return None
        seen: Set[FunctionInfo] = {res[1]}
        queue: deque = deque([(res[1], [start_hop])])
        while queue:
            func, path = queue.popleft()
            hop = (func.ref, func.module.rel_path, func.lineno)
            for inner in func.calls:
                if inner.under_lock:
                    continue  # honors the invariant on its own
                prim = self.mutator_primitive(inner)
                if prim is not None:
                    return path + [
                        (func.ref, func.module.rel_path, inner.lineno),
                        (prim, func.module.rel_path, inner.lineno),
                    ]
            for inner in func.calls:
                if inner.under_lock:
                    continue
                r = self.resolve(inner)
                if r is None or r[0] != "func" or r[1] in seen:
                    continue
                seen.add(r[1])
                queue.append(
                    (r[1], path + [(func.ref, func.module.rel_path,
                                    inner.lineno)])
                )
        return None

    def stats(self) -> dict:
        return {"resolved_edges": self.edge_count}


def chain_names(chain) -> str:
    """Render a chain as ``a -> b -> c`` (names only: stable under line
    drift, so it can live in the finding message / baseline key)."""
    return " -> ".join(hop[0] for hop in chain)


def chain_evidence(chain) -> List[dict]:
    """Structured chain for the JSON report."""
    return [
        {"symbol": sym, "path": path, "line": line}
        for sym, path, line in chain
    ]
