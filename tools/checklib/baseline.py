"""Grandfathered findings (``tools/check-baseline.json``).

The baseline exists so a NEW rule can land with the gate still green
while pre-existing violations are burned down — never as a place to
park fresh ones.  Entries match on ``(path, rule, message)`` (no line
numbers, so unrelated edits above a site don't invalidate them), as a
*multiset*: two identical findings need two entries.  An entry that no
longer matches anything fails the gate as ``stale-baseline`` — burn-down
progress must be banked by shrinking the file (``--write-baseline``
regenerates it from the current tree).
"""

from __future__ import annotations

import json
from collections import Counter
from typing import List, Tuple

from checklib.model import Finding
from checklib.registry import ENGINE_RULES

BASELINE_VERSION = 1


def load(path: str) -> Counter:
    """The baseline as a Counter of (path, rule, message) keys.

    A missing file is an empty baseline; a malformed one raises
    ValueError (the gate must not silently run baseline-less).
    """
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return Counter()
    except json.JSONDecodeError as err:
        raise ValueError(f"malformed baseline {path}: {err}") from None
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has unsupported version {data.get('version')!r}"
        )
    out: Counter = Counter()
    findings = data.get("findings", [])
    if not isinstance(findings, list):
        raise ValueError(f"malformed baseline {path}: findings must be a list")
    for entry in findings:
        # Validate shape explicitly so a hand-edited file fails with the
        # documented 'malformed baseline' exit (2), not a raw traceback.
        if not isinstance(entry, dict) or not all(
            isinstance(entry.get(k), str) for k in ("path", "rule", "message")
        ):
            raise ValueError(
                f"malformed baseline {path}: each entry needs string "
                f"path/rule/message, got {entry!r}"
            )
        if entry["rule"] in ENGINE_RULES:
            # Defense against hand-edited baselines: a grandfathered
            # syntax-error would green-light an unanalyzable file.
            raise ValueError(
                f"baseline {path} grandfathers engine finding "
                f"'{entry['rule']}' ({entry['path']}); fix it instead"
            )
        out[(entry["path"], entry["rule"], entry["message"])] += 1
    return out


def apply(
    findings: List[Finding],
    baseline: Counter,
    baseline_path: str,
    in_scope=None,
) -> Tuple[List[Finding], int]:
    """(surviving findings + stale-baseline findings, grandfathered count).

    ``in_scope`` (rel-path -> bool) is the run's coverage predicate:
    staleness is only asserted for entries this run *would have checked
    had the file existed* — checked files, plus anything under a target
    directory.  A partial-target run (one file, one subtree) must not
    condemn entries belonging to files it never looked at, while a
    deleted file's entry IS condemned by any run whose targets cover
    its directory (otherwise dead entries would accumulate forever and
    the burn-down invariant — "an entry matching nothing fails the
    gate" — would silently stop holding).  None means everything is in
    scope.  Coverage, not filesystem probing: an existence check cannot
    tell a scratch tree's ``registrar_tpu/x.py`` from the checker's own
    repo's, and is cwd-dependent besides.
    """
    remaining = Counter(baseline)
    kept: List[Finding] = []
    grandfathered = 0
    for f in findings:
        if remaining[f.key()] > 0:
            remaining[f.key()] -= 1
            grandfathered += 1
        else:
            kept.append(f)
    for (path, rule_name, message), count in sorted(remaining.items()):
        if in_scope is not None and not in_scope(path):
            continue  # outside this run's targets: not this run's call
        if count > 0:
            kept.append(
                Finding(
                    "stale-baseline",
                    baseline_path,
                    0,
                    f"entry matches nothing: {path} [{rule_name}] {message}"
                    + (f" (x{count})" if count > 1 else "")
                    + " — regenerate with --write-baseline",
                )
            )
    return kept, grandfathered


def write(path: str, findings: List[Finding]) -> int:
    entries = [
        {"path": f.path, "rule": f.rule, "message": f.message}
        for f in sorted(findings, key=Finding.sort_key)
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(
            {"version": BASELINE_VERSION, "findings": entries}, fh, indent=2
        )
        fh.write("\n")
    return len(entries)
