"""The finding record every rule emits.

A finding identifies itself by ``(path, rule, message)`` — deliberately
NOT by line number — so the baseline survives unrelated edits above a
grandfathered site (lines drift; the message names the construct).
"""

from __future__ import annotations


class Finding:
    """One problem at one site.

    ``line`` is 1-based; 0 means "whole file" (e.g. a stale-baseline
    entry or an unreadable file).
    """

    __slots__ = ("rule", "path", "line", "message", "chain")

    def __init__(
        self, rule: str, path: str, line: int, message: str, chain=None
    ):
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message
        #: Optional structured call-chain evidence (whole-program rules):
        #: a list of {"symbol", "path", "line"} hops, rendered into the
        #: JSON report only.  The message carries the chain as names —
        #: stable under line drift — so the baseline identity (path,
        #: rule, message) still pins WHICH chain was grandfathered.
        self.chain = chain

    def key(self):
        """Baseline identity: everything but the line number."""
        return (self.path, self.rule, self.message)

    def sort_key(self):
        return (self.path, self.line, self.rule, self.message)

    def to_dict(self) -> dict:
        out = {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
        }
        if self.chain is not None:
            out["chain"] = self.chain
        return out

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def __repr__(self):  # debug aid only
        return f"Finding({self.render()!r})"

    def __eq__(self, other):
        return (
            isinstance(other, Finding)
            and self.key() == other.key()
            and self.line == other.line
        )

    def __hash__(self):
        return hash((self.key(), self.line))
