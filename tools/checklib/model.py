"""The finding record every rule emits.

A finding identifies itself by ``(path, rule, message)`` — deliberately
NOT by line number — so the baseline survives unrelated edits above a
grandfathered site (lines drift; the message names the construct).
"""

from __future__ import annotations


class Finding:
    """One problem at one site.

    ``line`` is 1-based; 0 means "whole file" (e.g. a stale-baseline
    entry or an unreadable file).
    """

    __slots__ = ("rule", "path", "line", "message")

    def __init__(self, rule: str, path: str, line: int, message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message

    def key(self):
        """Baseline identity: everything but the line number."""
        return (self.path, self.rule, self.message)

    def sort_key(self):
        return (self.path, self.line, self.rule, self.message)

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def __repr__(self):  # debug aid only
        return f"Finding({self.render()!r})"

    def __eq__(self, other):
        return (
            isinstance(other, Finding)
            and self.key() == other.key()
            and self.line == other.line
        )

    def __hash__(self):
        return hash((self.key(), self.line))
