"""Resource-lifecycle (must-release) analysis (generation 4).

The repo's dynamic history is a catalog of leaked lifecycles: the PR-5
subprocess leak, drifting ``ChaosProxy`` teardowns in test helpers,
tracer spans opened and never finished (an unfinished span is a lie in
the flight recorder — the operator sees an operation that "never
ended").  This module checks the *shape* statically: for every resource
with a registered acquire/release vocabulary, some path from the
acquire site must not provably reach function exit without a release.

The analysis is per-function and deliberately statement-structural —
the interprocedural half rides on the PR-7 exception-escape fixpoint
instead of a dataflow lattice of its own:

  * an **acquire** is a constructor call from the vocabulary
    (``ChaosProxy``, ``ZKCache``, ``ShardWorker``, ``ShardRouter``,
    ``subprocess.Popen``) or a ``.start_span(...)`` method call;
  * an acquire bound to a plain local (``proxy = await
    ChaosProxy(...).start()``) is **tracked**; every other destination
    is an ownership pattern the function-local analysis must not
    second-guess, and is exempt: used as a ``with``/``async with``
    context expression (the manager releases), returned or yielded
    (ownership transfer to the caller), stored into an attribute,
    subscript or container (the holder owns it — ``self._failover_span
    = tr.start_span(...)``), passed as a call argument
    (``proxies.append(p)``, ``stack.enter_context(...)``), closed over
    by a nested def, aliased or rebound;
  * a tracked local **leaks** (``leaked-resource`` /
    ``span-never-finished``) when

      - no release method from its vocabulary is ever called on it
        (the straight-line leak), or
      - releases exist but none sits in a ``finally``, and a *named*
        exception class provably escapes the function (PR-7's converged
        escape set, UNKNOWN never acted on) from a site strictly
        between the acquire and the first release — the escape edge
        skips the release, and the finding's chain is the acquire hop
        plus the full escape chain;

  * a bare-statement acquire (``subprocess.Popen(...)`` as an
    expression statement) discards the only handle outright and is
    reported immediately.

Anything the model cannot prove stays silent — same contract as every
other generation.
"""

from __future__ import annotations

import ast
import time
from typing import Dict, Iterator, List, Optional, Set, Tuple

from checklib.callgraph import chain_evidence, chain_names
from checklib.exceptions import display_name, flow_for
from checklib.model import Finding
from checklib.program import (
    FunctionInfo,
    ProgramModel,
    _dotted,
)
from checklib.registry import rule

#: Resource constructors -> the method names that release what they
#: acquire.  Names are distinctive by design (a fixture defining its own
#: ``ChaosProxy`` is exactly the point); ``Popen`` additionally waits on
#: ``communicate`` because reaping IS the release for a subprocess.
RESOURCE_CTORS: Dict[str, frozenset] = {
    "ChaosProxy": frozenset({"stop", "close", "aclose", "kill"}),
    "ZKCache": frozenset({"close", "aclose", "stop"}),
    "ShardWorker": frozenset({"close", "stop"}),
    "ShardRouter": frozenset({"close", "stop"}),
    "Popen": frozenset({"wait", "communicate", "terminate", "kill"}),
}

#: ``.start_span(...)`` outside a ``with`` must be finished explicitly
#: (trace.Span.finish is idempotent, so belt-and-braces is fine — zero
#: calls is not).
SPAN_ACQUIRE = "start_span"
SPAN_RELEASES = frozenset({"finish", "end", "close"})

#: Methods that return the resource itself in a builder chain
#: (``ChaosProxy(addr).start()``): the chained call stays the acquire.
_CHAIN_METHODS = frozenset({"start"})


class _Acquire:
    __slots__ = (
        "rule", "label", "releases", "func", "name", "lineno", "node",
        "assign",
    )

    def __init__(self, rule_name, label, releases, func, name, lineno,
                 node, assign):
        self.rule = rule_name
        self.label = label
        self.releases = releases
        self.func: FunctionInfo = func
        self.name: Optional[str] = name  # tracked local, None = discarded
        self.lineno = lineno
        self.node = node
        self.assign = assign  # the binding ast.Assign (tracked only)


def _parent_map(root) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for parent in ast.walk(root):
        for child in ast.iter_child_nodes(parent):
            out[id(child)] = parent
    return out


def _nested_scope_ids(root) -> Set[int]:
    """ids of every node inside a nested def/class/lambda under root."""
    out: Set[int] = set()
    for node in ast.walk(root):
        if node is root:
            continue
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
             ast.ClassDef),
        ):
            for sub in ast.walk(node):
                out.add(id(sub))
    return out


def _finally_try_lines(root) -> Dict[int, int]:
    """id(node-in-a-finalbody) -> lineno of the owning ``try``.  A
    finally-release is unconditional only from the try's first line on:
    an acquire BEFORE the try (the classic
    ``p = await Proxy().start()`` / ``try: ... finally: p.stop()``
    straggler) is still exposed to escapes in the gap.  Outer trys are
    walked first, so a nested finally keeps its innermost owner."""
    out: Dict[int, int] = {}
    for node in ast.walk(root):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    out[id(sub)] = node.lineno
    return out


def _acquire_vocab(func: FunctionInfo, call: ast.Call):
    """(rule, label, release set) when ``call`` acquires a registered
    resource, else None."""
    if (
        isinstance(call.func, ast.Attribute)
        and call.func.attr == SPAN_ACQUIRE
    ):
        return ("span-never-finished", "start_span(...)", SPAN_RELEASES)
    d = _dotted(call.func)
    if d is None:
        return None
    base, attrs = d
    last = attrs[-1] if attrs else base
    releases = RESOURCE_CTORS.get(last)
    if releases is None:
        return None
    if not attrs and base in func.param_chain():
        return None  # the "constructor" is a parameter: unknown object
    return ("leaked-resource", f"{last}(...)", releases)


class Lifecycle:
    """The analysis: build once per run (:func:`lifecycle_for`), query
    per rule."""

    def __init__(self, model: ProgramModel):
        self.model = model
        self.flow = flow_for(model)
        t0 = time.monotonic()
        self.tracked = 0
        #: rule name -> findings (computed once, served to both rules)
        self.findings: Dict[str, List[Finding]] = {
            "leaked-resource": [],
            "span-never-finished": [],
        }
        for func in model.functions():
            if func.node is not None:
                self._scan_function(func)
        self.build_seconds = round(time.monotonic() - t0, 4)

    # -- per-function scan ------------------------------------------------

    def _scan_function(self, func: FunctionInfo) -> None:
        parents = _parent_map(func.node)
        nested = _nested_scope_ids(func.node)
        acquires: List[_Acquire] = []
        for node in ast.walk(func.node):
            if id(node) in nested or not isinstance(node, ast.Call):
                continue
            vocab = _acquire_vocab(func, node)
            if vocab is None:
                continue
            rule_name, label, releases = vocab
            kind, name, assign = self._classify(parents, node)
            if kind == "exempt":
                continue
            self.tracked += 1
            acquires.append(
                _Acquire(
                    rule_name, label, releases, func, name,
                    node.lineno, node, assign,
                )
            )
        if not acquires:
            return
        finals = _finally_try_lines(func.node)
        for acq in acquires:
            finding = (
                self._judge_discarded(acq)
                if acq.name is None
                else self._judge_tracked(acq, parents, nested, finals)
            )
            if finding is not None:
                self.findings[acq.rule].append(finding)

    def _classify(self, parents, call: ast.Call):
        """Where does the acquired value GO?  ("local", name, assign) for
        a tracked plain-local binding, ("discarded", None, None) for a
        bare expression statement, ("exempt", None, None) otherwise."""
        cur: ast.AST = call
        while True:
            p = parents.get(id(cur))
            if p is None:
                return ("exempt", None, None)
            if isinstance(p, ast.Await):
                cur = p
                continue
            if isinstance(p, ast.Attribute) and p.attr in _CHAIN_METHODS:
                gp = parents.get(id(p))
                if isinstance(gp, ast.Call) and gp.func is p:
                    cur = gp  # ChaosProxy(addr).start(): still the resource
                    continue
                return ("exempt", None, None)
            if isinstance(p, ast.Assign) and cur is p.value:
                if len(p.targets) == 1 and isinstance(
                    p.targets[0], ast.Name
                ):
                    return ("local", p.targets[0].id, p)
                return ("exempt", None, None)  # attr/subscript/tuple:
                # stored — the holder owns the lifecycle
            if isinstance(p, ast.Expr):
                return ("discarded", None, None)
            # withitem (cm-managed), Call argument / keyword (transfer),
            # Return / Yield (transfer), container literal, comparison,
            # conditional expression, ... — every other destination is
            # either ownership transfer or something unmodeled: exempt.
            return ("exempt", None, None)

    def _judge_discarded(self, acq: _Acquire) -> Optional[Finding]:
        func = acq.func
        verb = (
            "finished" if acq.rule == "span-never-finished" else "released"
        )
        return Finding(
            acq.rule,
            func.module.rel_path,
            acq.lineno,
            f"result of '{acq.label}' in '{func.qualname}' is discarded: "
            f"the handle can never be {verb} "
            f"({'/'.join(sorted(acq.releases))})",
        )

    def _judge_tracked(
        self, acq: _Acquire, parents, nested, finals
    ) -> Optional[Finding]:
        func = acq.func
        name = acq.name
        #: (release lineno, owning-try lineno when inside a finally)
        releases: List[Tuple[int, Optional[int]]] = []
        for node in ast.walk(func.node):
            if not isinstance(node, ast.Name) or node.id != name:
                continue
            if id(node) in nested:
                return None  # closed over: lifetime escapes this frame
            if isinstance(node.ctx, ast.Store):
                p = parents.get(id(node))
                if isinstance(p, ast.Assign) and p is acq.assign:
                    continue  # the acquire binding itself
                return None  # rebound / aliased target: not provable
            verdict = self._use_verdict(parents, node, acq.releases)
            if verdict == "exempt":
                return None
            if verdict is not None:  # a release lineno
                releases.append((verdict, finals.get(id(node))))
        if not releases:
            verb = (
                "finished"
                if acq.rule == "span-never-finished"
                else "released"
            )
            return Finding(
                acq.rule,
                func.module.rel_path,
                acq.lineno,
                f"'{name}' ({acq.label}) acquired in '{func.qualname}' is "
                f"never {verb} ({'/'.join(sorted(acq.releases))}) on any "
                f"path to function exit",
            )
        guarded = [t for _, t in releases if t is not None]
        if guarded and min(guarded) <= acq.lineno:
            return None  # the finally's try encloses the acquire:
            # released on every path out
        # Either no finally release at all, or the try begins AFTER the
        # acquire: an escape in (acquire, window_end) skips every
        # release.
        window_end = (
            min(guarded) if guarded else min(line for line, _ in releases)
        )
        for token in sorted(self.flow.named_escapes(func)):
            wit = self.flow._witness.get((func, token))
            if wit is None:
                continue
            wline = wit[0]
            if not (acq.lineno < wline < window_end):
                continue
            chain = [
                (
                    f"{name} = {acq.label}",
                    func.module.rel_path,
                    acq.lineno,
                )
            ] + self.flow.escape_chain(func, token)
            return Finding(
                acq.rule,
                func.module.rel_path,
                acq.lineno,
                f"'{name}' ({acq.label}) leaks when "
                f"'{display_name(token)}' escapes '{func.qualname}' "
                f"between the acquire and the release — no release sits "
                f"in a finally (chain: {chain_names(chain)})",
                chain=chain_evidence(chain),
            )
        return None

    def _use_verdict(self, parents, node: ast.Name, release_names):
        """For one Load use of the tracked name: a release call's lineno,
        "exempt" (ownership transfer / aliasing / cm use), or None
        (neutral read)."""
        p = parents.get(id(node))
        if isinstance(p, ast.Attribute) and p.value is node:
            gp = parents.get(id(p))
            if (
                isinstance(gp, ast.Call)
                and gp.func is p
                and p.attr in release_names
            ):
                return gp.lineno
            return None  # attribute read / non-release method: neutral
        cur: ast.AST = node
        while True:
            if p is None:
                return None
            if isinstance(p, ast.Call) and cur is not p.func:
                return "exempt"  # passed along: ownership transfer
            if isinstance(p, (ast.keyword, ast.Starred)):
                return "exempt"
            if isinstance(
                p, (ast.Return, ast.Yield, ast.YieldFrom)
            ):
                return "exempt"
            if isinstance(p, ast.withitem):
                return "exempt"  # `async with proxy:` — the cm releases
            if isinstance(
                p,
                (ast.List, ast.Tuple, ast.Set, ast.Dict, ast.ListComp,
                 ast.SetComp, ast.DictComp, ast.GeneratorExp,
                 ast.comprehension),
            ):
                return "exempt"  # containered: the holder owns it
            if isinstance(p, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                return "exempt"  # aliased or stored somewhere
            if isinstance(p, ast.stmt):
                return None  # plain read in a statement: neutral
            cur = p
            p = parents.get(id(cur))

    def stats(self) -> dict:
        return {
            "lifecycle_tracked": self.tracked,
            "lifecycle_build_s": self.build_seconds,
        }


def lifecycle_for(model: ProgramModel) -> Lifecycle:
    """One Lifecycle per program model, shared by both resource rules
    (and surfaced into ``--stats`` by the engine)."""
    lc = getattr(model, "_lifecycle", None)
    if lc is None:
        lc = Lifecycle(model)
        model._lifecycle = lc
    return lc


@rule(
    "leaked-resource",
    "acquired transport/cache/worker/subprocess handle provably reaches "
    "function exit without a release",
    scope="program",
)
def leaked_resource(model: ProgramModel) -> Iterator[Finding]:
    yield from lifecycle_for(model).findings["leaked-resource"]


@rule(
    "span-never-finished",
    "tracer span started outside a with and never finished on some path",
    scope="program",
)
def span_never_finished(model: ProgramModel) -> Iterator[Finding]:
    yield from lifecycle_for(model).findings["span-never-finished"]
