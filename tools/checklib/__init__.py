"""In-tree static analysis framework behind ``make check``.

The reference gates its build on jsl + jsstyle with shipped configs
(reference Makefile:15,18, tools/jsl.node.conf, tools/jsstyle.conf).
This package is the rebuild's equivalent, grown from the original
two-rule ``tools/check.py`` (undefined names, unused imports) into a
rule framework tuned to an asyncio codebase: the checker walks each
file once, hands a shared :class:`~checklib.context.FileContext` to
every registered rule, applies inline suppressions and the checked-in
baseline, and renders text or JSON.

Layout:

  * ``model.py``     — the :class:`Finding` record every rule emits
  * ``scopes.py``    — scope-chain resolver (undefined-name / unused-import)
  * ``context.py``   — per-file parse + derived facts shared by rules
  * ``registry.py``  — the rule registry and ``@rule`` decorator
  * ``program.py``   — the whole-program model: cross-module symbol
    table over the real import graph, per-function call sites, event
    names, config-key reads (generation 2)
  * ``callgraph.py`` — resolved call graph + the interprocedural
    fixpoints (async→blocking chains, single-flight-lock protection)
  * ``exceptions.py`` — the exception-escape analysis: per-function
    escape sets by fixpoint over the call edges, try/except and class
    hierarchy modeled, unresolvable edges widened (generation 3)
  * ``rules_names.py``, ``rules_async.py``, ``rules_hygiene.py`` —
    file-local rules; ``rules_flow.py``, ``rules_contracts.py`` — the
    whole-program rules; ``rules_errors.py`` — the exception-flow
    rules (retry/blackhole/overbroad/fault-matrix contract drift)
  * ``locks.py``     — the lock-acquisition-order graph: lexical
    ``async with <lock>`` sites, held-set propagation over call edges,
    cycle (deadlock) detection (generation 4)
  * ``lifecycle.py`` — must-release analysis for registered resource
    vocabularies (transports, caches, workers, subprocesses, spans),
    escape-path leaks via the generation-3 fixpoint
  * ``rules_protocol.py`` — wire-contract drift: struct format arity,
    OP_* dispatch/docs symmetry, flag bit overlap
  * ``taint.py``     — interprocedural taint flow: peer-controlled
    integers/payloads from the docs/DESIGN.md trust boundary to
    allocation/loop/read sinks, sanitized by dominating bound checks
    (generation 5)
  * ``rules_atomicity.py`` — stale-read-across-await: check-then-act
    on a lock-relevant field across a suspension point without
    re-read, epoch re-check, or a held lock
  * ``suppress.py``  — ``# check: disable=<rule> -- why`` comments
  * ``baseline.py``  — grandfathered findings (tools/check-baseline.json)
  * ``engine.py``    — file iteration, program-model orchestration,
    output, ``--changed-only`` / ``--stats`` / ``--max-seconds``, exit
    code

``tools/check.py`` is the CLI shim; docs/CHECKS.md is the operator-facing
rule catalog (including how to add a rule).
"""

from checklib.model import Finding  # noqa: F401  (public surface)
from checklib.registry import RULES, rule  # noqa: F401
from checklib.engine import check_file, main, run  # noqa: F401

# Importing the rule modules registers their rules.
import checklib.rules_names  # check: disable=unused-import -- import registers the rules
import checklib.rules_async  # check: disable=unused-import -- import registers the rules
import checklib.rules_hygiene  # check: disable=unused-import -- import registers the rules
import checklib.rules_flow  # check: disable=unused-import -- import registers the rules
import checklib.rules_contracts  # check: disable=unused-import -- import registers the rules
import checklib.rules_errors  # check: disable=unused-import -- import registers the rules
import checklib.locks  # check: disable=unused-import -- import registers the rules
import checklib.lifecycle  # check: disable=unused-import -- import registers the rules
import checklib.rules_protocol  # check: disable=unused-import -- import registers the rules
import checklib.taint  # check: disable=unused-import -- import registers the rules
import checklib.rules_atomicity  # check: disable=unused-import -- import registers the rules

__all__ = ["Finding", "RULES", "rule", "check_file", "run", "main"]
