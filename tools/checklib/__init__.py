"""In-tree static analysis framework behind ``make check``.

The reference gates its build on jsl + jsstyle with shipped configs
(reference Makefile:15,18, tools/jsl.node.conf, tools/jsstyle.conf).
This package is the rebuild's equivalent, grown from the original
two-rule ``tools/check.py`` (undefined names, unused imports) into a
rule framework tuned to an asyncio codebase: the checker walks each
file once, hands a shared :class:`~checklib.context.FileContext` to
every registered rule, applies inline suppressions and the checked-in
baseline, and renders text or JSON.

Layout:

  * ``model.py``     — the :class:`Finding` record every rule emits
  * ``scopes.py``    — scope-chain resolver (undefined-name / unused-import)
  * ``context.py``   — per-file parse + derived facts shared by rules
  * ``registry.py``  — the rule registry and ``@rule`` decorator
  * ``rules_names.py``, ``rules_async.py``, ``rules_hygiene.py`` — rules
  * ``suppress.py``  — ``# check: disable=<rule> -- why`` comments
  * ``baseline.py``  — grandfathered findings (tools/check-baseline.json)
  * ``engine.py``    — file iteration, orchestration, output, exit code

``tools/check.py`` is the CLI shim; docs/CHECKS.md is the operator-facing
rule catalog (including how to add a rule).
"""

from checklib.model import Finding  # noqa: F401  (public surface)
from checklib.registry import RULES, rule  # noqa: F401
from checklib.engine import check_file, main, run  # noqa: F401

# Importing the rule modules registers their rules.
import checklib.rules_names  # check: disable=unused-import -- import registers the rules
import checklib.rules_async  # check: disable=unused-import -- import registers the rules
import checklib.rules_hygiene  # check: disable=unused-import -- import registers the rules

__all__ = ["Finding", "RULES", "rule", "check_file", "run", "main"]
