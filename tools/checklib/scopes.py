"""Scope-chain resolver shared by the name rules.

Ported intact from the original two-rule ``tools/check.py``: one visitor
builds the scope tree (function scopes, class bodies invisible to nested
scopes per Python's scoping rules, comprehension scopes, walrus/global/
nonlocal placement), records every Name load, and resolves them against
the chain afterwards.  The deliberate approximations are unchanged and
verified against this repository: default-argument expressions resolve
in the scope of the ``def`` rather than the enclosing scope, and a
module containing ``from x import *`` skips undefined-name resolution.
"""

from __future__ import annotations

import ast
import builtins

BUILTIN_NAMES = set(dir(builtins)) | {
    "__file__",
    "__name__",
    "__doc__",
    "__package__",
    "__spec__",
    "__loader__",
    "__builtins__",
    "__debug__",
    "__path__",
    "__all__",
    "__version__",
    "__annotations__",
    "__dict__",
    "__class__",  # implicit cell in methods using super()/__class__
}


# match-statement nodes exist only on Python 3.10+; isinstance against an
# empty tuple is simply False on 3.9 (the package's floor).
_MATCH_AS = getattr(ast, "MatchAs", ())
_MATCH_STAR = getattr(ast, "MatchStar", ())
_MATCH_MAPPING = getattr(ast, "MatchMapping", ())


def iter_all_args(args):
    """Every arg object of an arguments node, across all five kinds —
    the ONE copy of the flattening (scope binding, lambda binding, and
    the shadowable-name collection all consume it)."""
    return (
        list(args.posonlyargs)
        + list(args.args)
        + list(args.kwonlyargs)
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    )


def iter_defaults(args):
    """Every present default expression of an arguments node — the ONE
    copy of the sibling flattening (scope resolution visits these, the
    mutable-default rule inspects them)."""
    return list(args.defaults) + [
        d for d in args.kw_defaults if d is not None
    ]


class Scope:
    __slots__ = ("node", "kind", "bindings", "parent")

    def __init__(self, node, kind, parent):
        self.node = node
        self.kind = kind  # "module" | "function" | "class" | "comprehension"
        self.bindings = set()
        self.parent = parent


def _bind_target(scope, target):
    """Bind every name created by an assignment target node."""
    for node in ast.walk(target):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            scope.bindings.add(node.id)
        elif isinstance(node, _MATCH_AS) and node.name:
            scope.bindings.add(node.name)
        elif isinstance(node, _MATCH_STAR) and node.name:
            scope.bindings.add(node.name)
        elif isinstance(node, _MATCH_MAPPING) and node.rest:
            scope.bindings.add(node.rest)


def _function_scope(scope):
    """Nearest enclosing scope where a walrus/global binding lands."""
    s = scope
    while s.kind == "comprehension":
        s = s.parent
    return s


class ScopeAnalyzer(ast.NodeVisitor):
    """Collects bindings/loads/imports; :meth:`resolve` yields problems
    as ``(rule, lineno, message)`` tuples."""

    def __init__(self):
        self.module_scope = None
        self.scope = None
        self.loads = []  # (name, lineno, scope) resolved after collection
        self.used_names = set()  # every load anywhere, for unused-import
        self.imports = []  # (alias-name, lineno, is_reexport)
        self.has_star_import = False

    # -- scope plumbing ---------------------------------------------------

    def _push(self, node, kind):
        self.scope = Scope(node, kind, self.scope)
        if kind == "module":
            self.module_scope = self.scope
        return self.scope

    def _pop(self):
        self.scope = self.scope.parent

    # -- visitors ---------------------------------------------------------

    def visit_Module(self, node):
        self._push(node, "module")
        self.generic_visit(node)
        self._pop()

    def _visit_function(self, node):
        self.scope.bindings.add(node.name)
        for dec in node.decorator_list:
            self.visit(dec)
        if node.returns:
            self.visit(node.returns)
        scope = self._push(node, "function")
        args = node.args
        for a in iter_all_args(args):
            scope.bindings.add(a.arg)
            if a.annotation:
                self.visit(a.annotation)
        for default in iter_defaults(args):
            self.visit(default)
        for stmt in node.body:
            self.visit(stmt)
        self._pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node):
        scope = self._push(node, "function")
        args = node.args
        for a in iter_all_args(args):
            scope.bindings.add(a.arg)
        for default in iter_defaults(args):
            self.visit(default)
        self.visit(node.body)
        self._pop()

    def visit_ClassDef(self, node):
        self.scope.bindings.add(node.name)
        for dec in node.decorator_list:
            self.visit(dec)
        for base in list(node.bases) + [kw.value for kw in node.keywords]:
            self.visit(base)
        self._push(node, "class")
        for stmt in node.body:
            self.visit(stmt)
        self._pop()

    def _visit_comprehension(self, node):
        # First iterable evaluates in the enclosing scope.
        if node.generators:
            self.visit(node.generators[0].iter)
        scope = self._push(node, "comprehension")
        for i, gen in enumerate(node.generators):
            _bind_target(scope, gen.target)
            if i > 0:
                self.visit(gen.iter)
            for cond in gen.ifs:
                self.visit(cond)
        if isinstance(node, ast.DictComp):
            self.visit(node.key)
            self.visit(node.value)
        else:
            self.visit(node.elt)
        self._pop()

    visit_ListComp = _visit_comprehension
    visit_SetComp = _visit_comprehension
    visit_GeneratorExp = _visit_comprehension
    visit_DictComp = _visit_comprehension

    def visit_Import(self, node):
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.scope.bindings.add(name)
            if self.scope is self.module_scope:
                reexport = alias.asname is not None and alias.asname == alias.name
                self.imports.append((name, node.lineno, reexport))

    def visit_ImportFrom(self, node):
        for alias in node.names:
            if alias.name == "*":
                self.has_star_import = True
                continue
            name = alias.asname or alias.name
            self.scope.bindings.add(name)
            if self.scope is self.module_scope and node.module != "__future__":
                reexport = alias.asname is not None and alias.asname == alias.name
                self.imports.append((name, node.lineno, reexport))

    def visit_Global(self, node):
        for name in node.names:
            self.scope.bindings.add(name)
            self.module_scope.bindings.add(name)

    def visit_Nonlocal(self, node):
        for name in node.names:
            self.scope.bindings.add(name)

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.scope.bindings.add(node.id)
        else:
            self.loads.append((node.id, node.lineno, self.scope))
            self.used_names.add(node.id)

    def visit_NamedExpr(self, node):
        # walrus binds in the nearest function/module scope
        if isinstance(node.target, ast.Name):
            _function_scope(self.scope).bindings.add(node.target.id)
        self.visit(node.value)

    def visit_ExceptHandler(self, node):
        if node.name:
            self.scope.bindings.add(node.name)
        self.generic_visit(node)

    def visit_For(self, node):
        _bind_target(self.scope, node.target)
        self.generic_visit(node)

    visit_AsyncFor = visit_For

    def visit_withitem(self, node):
        if node.optional_vars:
            _bind_target(self.scope, node.optional_vars)
        self.visit(node.context_expr)

    def visit_match_case(self, node):
        _bind_target(self.scope, node.pattern)
        self.generic_visit(node)

    def visit_Constant(self, node):
        # __all__ entries and other string constants may name module
        # attributes; count them toward import usage (not name loads).
        if isinstance(node.value, str) and node.value.isidentifier():
            self.used_names.add(node.value)

    # -- resolution -------------------------------------------------------

    def resolve(self):
        problems = []
        for name, lineno, scope in self.loads:
            s = scope
            found = False
            while s is not None:
                # Class bodies are invisible to nested scopes (but visible
                # to loads occurring directly inside the class body).
                if s.kind != "class" or s is scope:
                    if name in s.bindings:
                        found = True
                        break
                s = s.parent
            if not found and name not in BUILTIN_NAMES:
                if not self.has_star_import:
                    problems.append(
                        ("undefined-name", lineno, f"undefined name '{name}'")
                    )
        for name, lineno, reexport in self.imports:
            if reexport or name == "_" or name.startswith("__"):
                continue
            if name not in self.used_names:
                problems.append(
                    ("unused-import", lineno, f"unused import '{name}'")
                )
        return problems
