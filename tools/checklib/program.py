"""The whole-program model (generation 2 of the checker).

One pass over every parsed file builds a *program* out of the per-file
contexts the engine already holds: a cross-module symbol table (module
-> exported defs/classes, resolved through the package's real import
graph), per-function call sites annotated with the facts the flow rules
need (enclosing async frame, lexical single-flight-lock block, awaited /
bare-statement position), the event-name surface (constant-string
``.emit``/``.on``/``.once``/``.wait_for`` sites), and the config-key
reads of the accessor modules.  :mod:`checklib.callgraph` turns the call
sites into a resolved call graph; the rules in ``rules_flow.py`` /
``rules_contracts.py`` consume both.

Resolution is deliberately conservative — the same zero-false-positive
contract as the file-local rules:

  * a name is only resolved when it has exactly ONE module-level binding
    kind (one ``def``, or one import) and is not shadowed by a parameter
    of any enclosing function at the call site;
  * a module containing ``from x import *`` or a dynamic import
    (``__import__``, ``importlib.import_module``) degrades to
    file-local: no name inside it resolves cross-module (its own
    top-level defs stay resolvable *from elsewhere* — a def is a def);
  * ``getattr`` dispatch, calls through parameters/attributes of
    unknown objects, and non-constant event names are simply not
    modeled (conservative silence, never a guess).

Import cycles are harmless by construction: the model never executes
imports, it only maps names, so ``a -> b -> a`` resolves exactly like
any other edge.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from checklib.context import FileContext, PACKAGE_PREFIX

#: Attribute names that mutate znode state on a ZKClient (or build the
#: mutating ops of a multi/pipeline) — the primitives the
#: await-in-lock-free-mutator rule treats as "touches ZooKeeper".
ZK_MUTATORS = frozenset(
    {
        "create",
        "create_ephemeral_plus",
        "put",
        "set_data",
        "unlink",
        "delete",
        "mkdirp",
        "multi",
        "pipeline",
    }
)

#: ``async with <name>:`` context expressions whose final component
#: matches this are treated as the agent's single-flight guard (the
#: PR 3 invariant: ``repair_lock`` / ``lock`` / ``self.lock``).
_LOCK_NAME = re.compile(r"(^|_)lock$", re.IGNORECASE)

#: Listener-registering EventEmitter methods with a constant event name.
_LISTEN_METHODS = frozenset({"on", "once", "wait_for"})


def module_name_for(rel_path: str) -> str:
    """Dotted module name a checked file imports as (posix rel path).

    The checker's own tree is special: ``tools/`` sits on sys.path (the
    tools/check.py shim inserts it), so ``tools/checklib/engine.py`` is
    imported as ``checklib.engine`` — without the strip, no import edge
    into checklib would ever resolve and --changed-only's
    reverse-dependency closure would silently miss its consumers."""
    name = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    if name.endswith("/__init__"):
        name = name[: -len("/__init__")]
    if name.startswith("tools/"):
        name = name[len("tools/"):]
    return name.replace("/", ".")


def _dotted(node) -> Optional[Tuple[str, Tuple[str, ...]]]:
    """(base-name, attr chain) for ``a.b.c`` — (``a``, (``b``, ``c``))."""
    attrs: List[str] = []
    while isinstance(node, ast.Attribute):
        attrs.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    return node.id, tuple(reversed(attrs))


def _is_lock_expr(expr) -> bool:
    d = _dotted(expr)
    if d is None:
        return False
    base, attrs = d
    last = attrs[-1] if attrs else base
    return bool(_LOCK_NAME.search(last))


class CallSite:
    """One call expression inside a function body."""

    __slots__ = (
        "node", "lineno", "shape", "awaited", "bare_stmt", "under_lock",
        "func",
    )

    def __init__(self, node, shape, awaited, bare_stmt, under_lock, func):
        self.node = node
        self.lineno = node.lineno
        #: ("name", id) | ("dotted", base, attrs) | ("opaque",)
        self.shape = shape
        self.awaited = awaited
        self.bare_stmt = bare_stmt  # Expr statement: result discarded
        self.under_lock = under_lock  # lexically inside async-with-lock
        self.func: "FunctionInfo" = func  # enclosing function

    def render(self) -> str:
        if self.shape[0] == "name":
            return f"{self.shape[1]}()"
        if self.shape[0] == "dotted":
            return ".".join((self.shape[1],) + self.shape[2]) + "()"
        return "<call>()"


class FunctionInfo:
    """One ``def``/``async def`` (module-level, method, or nested)."""

    __slots__ = (
        "module", "qualname", "name", "is_async", "lineno", "cls",
        "params", "parent", "children", "calls", "node",
    )

    def __init__(self, module, qualname, name, is_async, lineno, cls, parent):
        self.module: "ModuleInfo" = module
        self.qualname = qualname  # "mod:Outer.inner" style, module-relative
        self.name = name
        self.is_async = is_async
        self.lineno = lineno
        self.cls: Optional[str] = cls  # enclosing class name, if a method
        self.params: Set[str] = set()
        self.parent: Optional["FunctionInfo"] = None if parent is None else parent
        self.children: Dict[str, "FunctionInfo"] = {}
        self.calls: List[CallSite] = []
        #: the ast.FunctionDef/AsyncFunctionDef (None for the module
        #: pseudo-function) — the exception-escape analysis re-walks the
        #: body for raise sites and try/except structure (generation 3)
        self.node = None

    @property
    def ref(self) -> str:
        return f"{self.module.name}:{self.qualname}"

    def param_chain(self) -> Set[str]:
        out: Set[str] = set()
        f: Optional[FunctionInfo] = self
        while f is not None:
            out |= f.params
            f = f.parent
        return out


class ClassInfo:
    __slots__ = ("name", "methods", "bases")

    def __init__(self, name):
        self.name = name
        self.methods: Dict[str, FunctionInfo] = {}
        self.bases: List[Tuple[str, Tuple[str, ...]]] = []  # dotted refs


class EventSite:
    __slots__ = ("kind", "event", "lineno", "rel_path")

    def __init__(self, kind, event, lineno, rel_path):
        self.kind = kind  # "emit" | "listen"
        self.event = event
        self.lineno = lineno
        self.rel_path = rel_path


class ModuleInfo:
    """Symbol-table entry for one checked file."""

    __slots__ = (
        "name", "rel_path", "ctx", "imports", "from_imports", "bindings",
        "functions", "classes", "degraded", "dep_names", "module_func",
        "event_sites", "key_reads",
    )

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.rel_path = ctx.rel_path
        self.name = module_name_for(ctx.rel_path)
        #: local alias -> full module name (``import x.y as z``)
        self.imports: Dict[str, str] = {}
        #: local name -> (source module, original name)
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        #: module-level name -> set of binding kinds seen
        #: ({"def","class","import","assign"}) — >1 kind = ambiguous
        self.bindings: Dict[str, Set[str]] = {}
        self.functions: Dict[str, FunctionInfo] = {}  # top-level defs
        self.classes: Dict[str, ClassInfo] = {}
        #: star import or dynamic import: no cross-module resolution
        #: *inside* this module (its own defs stay visible from outside)
        self.degraded = False
        #: every module name ANY import statement references (function-
        #: level imports included) — the import-graph edge set, which is
        #: broader than the name-binding maps above (those stay
        #: top-level: a function-local import binds no module name)
        self.dep_names: Set[str] = set()
        #: pseudo-function holding module-level call sites
        self.module_func = FunctionInfo(
            self, "<module>", "<module>", False, 0, None, None
        )
        self.event_sites: List[EventSite] = []
        #: constant config keys read in this module: key -> first lineno
        self.key_reads: Dict[str, int] = {}

    def _bind(self, name: str, kind: str) -> None:
        self.bindings.setdefault(name, set()).add(kind)


class ProgramModel:
    """The program: every module, plus the shared lookup helpers."""

    def __init__(self, contexts: List[FileContext]):
        self.contexts = list(contexts)
        self.modules: Dict[str, ModuleInfo] = {}
        #: rel_path -> ModuleInfo (rule scoping is path-based)
        self.by_path: Dict[str, ModuleInfo] = {}
        for ctx in contexts:
            mod = _build_module(ctx)
            self.modules[mod.name] = mod
            self.by_path[mod.rel_path] = mod
        #: importer module name -> set of imported model-module names
        self.import_edges: Dict[str, Set[str]] = {}
        for mod in self.modules.values():
            deps = {d for d in mod.dep_names if d in self.modules}
            deps.discard(mod.name)
            self.import_edges[mod.name] = deps

    # -- lookups ----------------------------------------------------------

    def functions(self):
        for mod in self.modules.values():
            stack = list(mod.functions.values())
            for cls in mod.classes.values():
                stack.extend(cls.methods.values())
            while stack:
                f = stack.pop()
                yield f
                stack.extend(f.children.values())

    def all_call_sites(self):
        for f in self.functions():
            for site in f.calls:
                yield site
        for mod in self.modules.values():
            for site in mod.module_func.calls:
                yield site

    def reverse_import_closure(self, rel_paths) -> Set[str]:
        """rel paths + everything that (transitively) imports them."""
        by_name = {m.name: m for m in self.modules.values()}
        importers: Dict[str, Set[str]] = {name: set() for name in by_name}
        for src, deps in self.import_edges.items():
            for dep in deps:
                importers.setdefault(dep, set()).add(src)
        seeds = [
            self.by_path[p].name for p in rel_paths if p in self.by_path
        ]
        seen: Set[str] = set(seeds)
        frontier = list(seeds)
        while frontier:
            name = frontier.pop()
            for up in importers.get(name, ()):
                if up not in seen:
                    seen.add(up)
                    frontier.append(up)
        out = {p for p in rel_paths}
        out |= {by_name[n].rel_path for n in seen}
        return out

    def package_root(self) -> Optional[str]:
        """Filesystem directory containing the checked package tree —
        derived from any package file's (abs path, rel path) pair, so a
        scratch fixture tree resolves to its own docs/etc siblings."""
        for ctx in self.contexts:
            if not ctx.rel_path.startswith(PACKAGE_PREFIX):
                continue
            ap = os.path.abspath(ctx.path).replace(os.sep, "/")
            if ap.endswith("/" + ctx.rel_path):
                return ap[: -len("/" + ctx.rel_path)]
        return None

    def stats(self) -> dict:
        return {
            "modules": len(self.modules),
            "import_edges": sum(
                len(v) for v in self.import_edges.values()
            ),
            "functions": sum(1 for _ in self.functions()),
            "call_sites": sum(1 for _ in self.all_call_sites()),
            "event_sites": sum(
                len(m.event_sites) for m in self.modules.values()
            ),
        }


# -- per-module construction --------------------------------------------------


_DYNAMIC_IMPORT_CALLS = frozenset({"__import__", "import_module"})


def _build_module(ctx: FileContext) -> ModuleInfo:
    mod = ModuleInfo(ctx)
    pkg_parts = mod.name.split(".")[:-1]

    for node in ctx.tree.body:
        _collect_top_level(mod, node, pkg_parts)
    # Imports / assignments hiding below conditionals still bind at
    # module level; a second walk catches them (kind-ambiguity handles
    # the try/except-ImportError fallback shape without guessing).
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None:
                base, attrs = d
                last = attrs[-1] if attrs else base
                if last in _DYNAMIC_IMPORT_CALLS:
                    mod.degraded = True
        elif isinstance(node, ast.Import):
            for alias in node.names:
                mod.dep_names.add(alias.name)
        elif isinstance(node, ast.ImportFrom):
            if any(a.name == "*" for a in node.names):
                mod.degraded = True
            source = _resolve_from(mod, node, pkg_parts)
            if source is not None:
                mod.dep_names.add(source)
                for alias in node.names:
                    if alias.name != "*":
                        mod.dep_names.add(f"{source}.{alias.name}")

    _collect_functions(mod, ctx.tree)
    _collect_event_sites(mod, ctx.tree)
    _collect_key_reads(mod, ctx.tree)
    return mod


def _collect_top_level(mod: ModuleInfo, node, pkg_parts) -> None:
    if isinstance(node, ast.Import):
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            # `import a.b` binds `a`; `import a.b as c` binds c -> a.b
            target = alias.name if alias.asname else alias.name.split(".")[0]
            mod.imports[local] = target
            mod._bind(local, "import")
    elif isinstance(node, ast.ImportFrom):
        source = _resolve_from(mod, node, pkg_parts)
        for alias in node.names:
            if alias.name == "*":
                continue
            local = alias.asname or alias.name
            if source is not None:
                mod.from_imports[local] = (source, alias.name)
            mod._bind(local, "import")
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        mod._bind(node.name, "def")
    elif isinstance(node, ast.ClassDef):
        mod._bind(node.name, "class")
    elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    mod._bind(sub.id, "assign")
    elif isinstance(node, (ast.If, ast.Try)):
        # body/orelse/finalbody statements are direct child nodes; only
        # handler bodies hide behind a non-stmt (ExceptHandler) layer.
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                _collect_top_level(mod, child, pkg_parts)
        for handler in getattr(node, "handlers", []):
            for child in handler.body:
                _collect_top_level(mod, child, pkg_parts)


def _resolve_from(mod, node: ast.ImportFrom, pkg_parts) -> Optional[str]:
    if node.level == 0:
        return node.module
    # relative import: drop (level-1) package components beyond the
    # module's own package
    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
    if node.level - 1 > len(pkg_parts):
        return None
    parts = base + (node.module.split(".") if node.module else [])
    return ".".join(parts) if parts else None


def _collect_functions(mod: ModuleInfo, tree: ast.Module) -> None:
    """Register every def and its call sites, threading the lexical
    facts (enclosing function, class, async-with-lock block) along in
    one walk.  Lambda bodies are skipped entirely (deferred execution,
    conservative silence); decorators and argument defaults evaluate in
    the *enclosing* frame, like rules_async._walk_state."""

    def register(child, func, cls, in_class_body, qual) -> FunctionInfo:
        name = child.name
        child_qual = f"{qual}.{name}" if qual else name
        info = FunctionInfo(
            mod, child_qual, name,
            isinstance(child, ast.AsyncFunctionDef),
            child.lineno,
            cls.name if (cls is not None and in_class_body) else None,
            func if func is not mod.module_func else None,
        )
        info.node = child
        args = child.args
        for a in (
            list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            info.params.add(a.arg)
        if cls is not None and in_class_body:
            cls.methods[name] = info
        elif func is mod.module_func:
            mod.functions[name] = info
        else:
            func.children[name] = info
        return info

    def walk(node, func, cls, under_lock, in_class_body, qual) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = register(node, func, cls, in_class_body, qual)
            for dec in node.decorator_list:
                walk(dec, func, cls, under_lock, False, qual)
            for default in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]:
                walk(default, func, cls, under_lock, False, qual)
            for stmt in node.body:
                walk(stmt, info, cls, False, False, info.qualname)
            return
        if isinstance(node, ast.ClassDef):
            cinfo = mod.classes.setdefault(node.name, ClassInfo(node.name))
            for base in node.bases:
                d = _dotted(base)
                if d is not None:
                    cinfo.bases.append(d)
            for dec in node.decorator_list:
                walk(dec, func, cls, under_lock, False, qual)
            body_qual = f"{qual}.{node.name}" if qual else node.name
            for stmt in node.body:
                walk(stmt, func, cinfo, under_lock, True, body_qual)
            return
        if isinstance(node, ast.AsyncWith):
            locked = under_lock or any(
                _is_lock_expr(item.context_expr) for item in node.items
            )
            for item in node.items:
                walk(item.context_expr, func, cls, under_lock, False, qual)
                if item.optional_vars is not None:
                    walk(item.optional_vars, func, cls, under_lock,
                         False, qual)
            for stmt in node.body:
                walk(stmt, func, cls, locked, False, qual)
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is None:
                shape = ("opaque",)
            elif not d[1]:
                shape = ("name", d[0])
            else:
                shape = ("dotted", d[0], d[1])
            func.calls.append(
                CallSite(
                    node, shape,
                    awaited=bool(getattr(node, "_chk_awaited", False)),
                    bare_stmt=bool(getattr(node, "_chk_bare", False)),
                    under_lock=under_lock,
                    func=func,
                )
            )
        for child in ast.iter_child_nodes(node):
            walk(child, func, cls, under_lock, False, qual)

    # Pre-annotate awaited / bare-statement calls so the walker needs no
    # parent pointers.
    for node in ast.walk(tree):
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            node.value._chk_awaited = True
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            node.value._chk_bare = True

    for stmt in tree.body:
        walk(stmt, mod.module_func, None, False, False, "")


def _collect_event_sites(mod: ModuleInfo, tree: ast.Module) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        attr = node.func.attr
        if attr != "emit" and attr not in _LISTEN_METHODS:
            continue
        if not node.args:
            continue
        first = node.args[0]
        if not (
            isinstance(first, ast.Constant) and isinstance(first.value, str)
        ):
            continue  # dynamic event name: not modeled
        mod.event_sites.append(
            EventSite(
                "emit" if attr == "emit" else "listen",
                first.value,
                node.lineno,
                mod.rel_path,
            )
        )


#: functions whose second positional string argument is a config key
#: (config.py's `_ms(obj, "timeout", ...)` translation helpers).
_KEY_HELPER = re.compile(r"(^|_)(ms|optional_ms)$")


def _collect_key_reads(mod: ModuleInfo, tree: ast.Module) -> None:
    def record(key: str, lineno: int) -> None:
        if key and key not in mod.key_reads:
            mod.key_reads[key] = lineno

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "pop")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                record(node.args[0].value, node.lineno)
            elif (
                d is not None
                and not d[1]
                and _KEY_HELPER.search(d[0])
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                record(node.args[1].value, node.lineno)
        elif isinstance(node, ast.Subscript):
            # Load context only: a store (`out["stdout_match"] = sm`)
            # writes an INTERNAL dict, not a key the operator config
            # carries.
            sl = node.slice
            if (
                isinstance(node.ctx, ast.Load)
                and isinstance(sl, ast.Constant)
                and isinstance(sl.value, str)
            ):
                record(sl.value, node.lineno)
        elif isinstance(node, ast.Compare):
            if (
                len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and isinstance(node.left, ast.Constant)
                and isinstance(node.left.value, str)
            ):
                record(node.left.value, node.lineno)
        elif isinstance(node, ast.Assign):
            # KNOWN_*_KEYS = frozenset({...}) declarations
            names = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if any("KEYS" in n for n in names):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str
                    ):
                        record(sub.value, node.lineno)


# -- documentation / example sources for config-key-drift ---------------------


_BACKTICK = re.compile(r"`([^`]+)`")
_KEY_TOKEN = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*(\.[A-Za-z_][A-Za-z0-9_]*)*$")
_IDENTIFIER = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def parse_config_doc(path: str):
    """(table_keys, mentions): ``table_keys`` maps each documented key —
    the first backticked cell of a markdown table row, last dotted
    component — to its line; ``mentions`` is every identifier appearing
    in backticks or fenced code anywhere (the loose "is it documented at
    all" set, so `{host, port}` inside a type cell still counts).  None
    when the doc is absent/unreadable — the rule skips that leg instead
    of condemning every key as undocumented."""
    table_keys: Dict[str, int] = {}
    mentions: Set[str] = set()
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().split("\n")
    except OSError:
        return None
    in_fence = False
    for i, line in enumerate(lines, start=1):
        stripped = line.strip()
        if stripped.startswith("```"):
            in_fence = not in_fence
            continue
        if in_fence:
            mentions.update(_IDENTIFIER.findall(line))
            continue
        for m in _BACKTICK.finditer(line):
            mentions.update(_IDENTIFIER.findall(m.group(1)))
        if not stripped.startswith("|"):
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        first = cells[0]
        m = _BACKTICK.match(first)
        if m is None or m.end() != len(first):
            continue  # header / separator / prose cell
        token = m.group(1)
        if _KEY_TOKEN.match(token):
            key = token.split(".")[-1]
            if key not in table_keys:
                table_keys[key] = i
    return table_keys, mentions


def parse_config_example(path: str) -> Optional[Set[str]]:
    """Every object key (recursively) in a JSON config sample; None when
    the file is absent or unparseable (the rule then skips that leg)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    keys: Set[str] = set()

    def walk(value) -> None:
        if isinstance(value, dict):
            for k, v in value.items():
                keys.add(k)
                walk(v)
        elif isinstance(value, list):
            for v in value:
                walk(v)

    walk(data)
    return keys
