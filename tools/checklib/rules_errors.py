"""Generation-3 rules: the error contracts (see exceptions.py).

Every robustness feature since PR 2 rests on an exception-class
contract no test fully exercises: ``retry.is_transient`` decides what
gets retried vs what kills the process, the rebirth/resume/reload paths
each promise a specific fallback per exception shape, and
docs/FAULTS.md + docs/OPERATIONS.md publish a fault matrix operators
are told to trust.  These rules diff those contracts against what the
interprocedural escape analysis *proves* can flow where — the same
contract-drift move config-key-drift made for config keys, applied to
the failure domain.

All four consume the shared :class:`~checklib.exceptions.ExceptionFlow`
(built once per run, fixpoint over the PR-6 call graph) and act only on
**named** classes; the UNKNOWN widening marker never produces a finding.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from checklib.callgraph import chain_evidence, chain_names
from checklib.context import PACKAGE_PREFIX
from checklib.exceptions import (
    BUILTIN_PARENTS,
    EXT_ALIASES,
    UNKNOWN,
    display_name,
    flow_for,
)
from checklib.model import Finding
from checklib.program import ProgramModel
from checklib.registry import rule
from checklib.rules_contracts import read_doc_lines
from checklib.rules_flow import graph_for

#: The contract classes the robustness story names per-shape fallbacks
#: for (ISSUE 7): swallowing one in a catch-all handler destroys a
#: signal some caller was built to act on.
CONTRACT_CLASS_NAMES = frozenset(
    {
        "SessionExpiredError",
        "OwnershipError",
        "OperationTimeoutError",
        "StateFileError",
    }
)

FAULTS_DOC = "docs/FAULTS.md"
OPS_DOC = "docs/OPERATIONS.md"

_RETRY_PATH = PACKAGE_PREFIX + "retry.py"


def _contract_tokens(flow) -> Set[str]:
    out: Set[str] = set()
    for name in CONTRACT_CLASS_NAMES:
        out.update(flow.classes_by_name.get(name, ()))
    return out


def _sorted_named(tokens) -> List[str]:
    return sorted(t for t in tokens if t != UNKNOWN)


# -- retry-contract-drift ------------------------------------------------------


def _classified_tokens(flow, fn) -> Set[str]:
    """Every exception class ``retry.is_transient``'s body names —
    transient or fatal, an ``isinstance`` arm either way counts as
    'classified': the predicate made a deliberate call about it."""
    out: Set[str] = set()
    if fn.node is None:
        return out
    for stmt in fn.node.body:  # BODY only: the signature's
        # `err: BaseException` annotation must not classify everything
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Name, ast.Attribute)):
                token = flow.class_token(fn, node)
                if token != UNKNOWN:
                    out.add(token)
    return out


def _mentions_is_transient(expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id == "is_transient":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "is_transient":
            return True
    return False


def _root_tokens(flow, tokens: List[str]) -> List[str]:
    """Drop tokens whose ancestor is also in the list (reporting
    StateFileError subsumes StateFileMissing — one finding per family)."""
    return [
        t
        for t in tokens
        if not any(o != t and flow.is_subclass(t, o) for o in tokens)
    ]


@rule(
    "retry-contract-drift",
    "exception class reaches a retry boundary that is_transient never "
    "classified",
    scope="program",
)
def retry_contract_drift(model: ProgramModel) -> Iterator[Finding]:
    # A call_with_backoff boundary whose retryable predicate rides on
    # retry.is_transient retries what the predicate blesses and treats
    # EVERYTHING else as fatal-by-default.  An exception class that can
    # provably reach the boundary but that is_transient's body never
    # names (neither in a transient arm nor a fatal one) is a silent
    # non-retry: nobody ever decided it should kill the attempt chain.
    flow = flow_for(model)
    graph = graph_for(model)
    retry_mod = model.by_path.get(_RETRY_PATH)
    if retry_mod is None:
        return
    cwb = retry_mod.functions.get("call_with_backoff")
    is_transient = retry_mod.functions.get("is_transient")
    if cwb is None or is_transient is None:
        return
    classified = _classified_tokens(flow, is_transient)
    if not classified:
        return
    for site in model.all_call_sites():
        res = graph.resolve(site)
        if res is None or res[0] != "func" or res[1] is not cwb:
            continue
        node = site.node
        retryable = next(
            (kw.value for kw in node.keywords if kw.arg == "retryable"),
            None,
        )
        if retryable is None or not _mentions_is_transient(retryable):
            continue  # no predicate, or a custom one: no is_transient
            # contract to hold the boundary against
        thunk_expr = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "fn"), None
        )
        if thunk_expr is None:
            continue
        tokens, origins = flow.thunk_escapes(site, thunk_expr)
        unclassified = [
            t
            for t in _sorted_named(tokens)
            if not any(flow.is_subclass(t, c) for c in classified)
        ]
        for token in _root_tokens(flow, unclassified):
            origin = origins.get(token)
            chain = (
                flow.escape_chain(origin, token)
                if origin is not None
                else []
            )
            full = [
                (site.func.ref, site.func.module.rel_path, site.lineno)
            ] + chain
            yield Finding(
                "retry-contract-drift",
                site.func.module.rel_path,
                site.lineno,
                f"exception class '{display_name(token)}' can reach the "
                f"retry boundary in '{site.func.qualname}' but "
                "retry.is_transient neither classifies it transient nor "
                "names it fatal — today that is a silent non-retry "
                f"(chain: {chain_names(full)})",
                chain=chain_evidence(full),
            )


# -- task-exception-blackhole --------------------------------------------------


def _call_arg_parents(tree) -> Dict[int, ast.Call]:
    """id(call node) -> the call expression it sits inside as an
    ARGUMENT (transitively: through genexps, list comps, starred args)."""
    parents: Dict[int, ast.Call] = {}

    def walk(node, current: Optional[ast.Call]) -> None:
        if isinstance(node, ast.Call):
            if current is not None:
                parents[id(node)] = current
            walk(node.func, current)
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                walk(a, node)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, current)

    walk(tree, None)
    return parents


def _consumed_refs(tree) -> Tuple[Set[str], Set[str]]:
    """(names, attribute names) that appear anywhere under an ``await``
    expression or inside a ``gather``/``wait``/``wait_for``/``shield``
    call — a task handle reaching one of those has a consumer."""
    names: Set[str] = set()
    attrs: Set[str] = set()

    def collect(node) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                names.add(sub.id)
            elif isinstance(sub, ast.Attribute):
                attrs.add(sub.attr)

    for node in ast.walk(tree):
        if isinstance(node, ast.Await):
            collect(node.value)
        elif isinstance(node, ast.Call) and isinstance(
            node.func, (ast.Name, ast.Attribute)
        ):
            callee = (
                node.func.id
                if isinstance(node.func, ast.Name)
                else node.func.attr
            )
            if callee in ("gather", "wait", "wait_for", "shield", "result",
                          "exception"):
                collect(node)
    return names, attrs


def _assign_target_refs(tree) -> Dict[int, Tuple[Set[str], Set[str]]]:
    """id(value node) -> (target names, target attribute names) for
    every assignment — plain, annotated (``self._task: asyncio.Task =
    ...``), and walrus — so a spawn whose handle is stored can be
    checked against the module's consumed refs."""
    out: Dict[int, Tuple[Set[str], Set[str]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.NamedExpr):
            targets, value = [node.target], node.value
        else:
            continue
        names: Set[str] = set()
        attrs: Set[str] = set()
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
                elif isinstance(sub, ast.Attribute):
                    attrs.add(sub.attr)
        out[id(value)] = (names, attrs)
    return out


@rule(
    "task-exception-blackhole",
    "exception escapes a fire-and-forget task root or event handler "
    "with no consumer",
    scope="program",
)
def task_exception_blackhole(model: ProgramModel) -> Iterator[Finding]:
    # A tracked fire-and-forget task (`spawn_owned`, `create_task` into
    # a registry set) has an owner but no CONSUMER: nothing ever awaits
    # it, so an exception ending the coroutine is retrieved by nobody
    # and vanishes into the loop's default 'Task exception was never
    # retrieved' handler — the error contract equivalent of a dropped
    # task.  Long-lived roots must catch-and-report inside the loop.
    # Event-handler entry points get the narrower check: a CONTRACT
    # class escaping a listener dies in the emitter's generic
    # log.exception instead of the recovery path built for it.
    flow = flow_for(model)
    graph = graph_for(model)
    contract = _contract_tokens(flow)
    by_module = _functions_by_module(model)
    for mod in model.modules.values():
        if not mod.rel_path.startswith(PACKAGE_PREFIX):
            continue
        parents = _call_arg_parents(mod.ctx.tree)
        consumed_names, consumed_attrs = _consumed_refs(mod.ctx.tree)
        assigns = _assign_target_refs(mod.ctx.tree)
        for func in by_module.get(mod, ()):
            for site in func.calls:
                res = graph.resolve(site)
                if res is None or res[0] != "func" or not res[1].is_async:
                    continue
                if site.awaited:
                    continue
                outer = parents.get(id(site.node))
                if outer is None:
                    continue  # bare/assigned coroutine: dropped-task /
                    # unawaited-coroutine territory
                if getattr(outer, "_chk_awaited", False):
                    continue  # gather()-style: consumed
                if not _is_spawner(outer, mod.ctx.cm_bound_names):
                    continue  # only a real spawn makes a task root: a
                    # coroutine handed to append()/run()/anything else
                    # is consumed (or flagged) elsewhere, and a
                    # TaskGroup-style cm-bound receiver re-raises at
                    # block exit
                targets = assigns.get(id(outer))
                if targets is not None and (
                    targets[0] & consumed_names or targets[1] & consumed_attrs
                ):
                    continue  # the stored handle is awaited somewhere
                escaping = _sorted_named(flow.escapes(res[1]))
                if not escaping:
                    continue
                roots = _root_tokens(flow, escaping)
                classes = ", ".join(
                    f"'{display_name(t)}'" for t in roots
                )
                chain = flow.escape_chain(res[1], roots[0])
                full = [(func.ref, mod.rel_path, site.lineno)] + chain
                yield Finding(
                    "task-exception-blackhole",
                    mod.rel_path,
                    site.lineno,
                    f"exception class(es) {classes} escape fire-and-forget "
                    f"task '{res[1].qualname}' and no consumer ever awaits "
                    "it — the error vanishes into the event loop's default "
                    f"handler (chain: {chain_names(full)})",
                    chain=chain_evidence(full),
                )
        # event-handler entry points: .on/.once registrations
        for func in by_module.get(mod, ()):
            for site in func.calls:
                if site.shape[0] != "dotted" or site.shape[2][-1] not in (
                    "on", "once",
                ):
                    continue
                node = site.node
                if len(node.args) < 2:
                    continue
                first_arg = node.args[0]
                if not (
                    isinstance(first_arg, ast.Constant)
                    and isinstance(first_arg.value, str)
                ):
                    continue  # dynamic event: not modeled
                handler = flow.resolve_callable_ref(site, node.args[1])
                if handler is None:
                    continue  # lambda/unresolvable listener: unmodeled
                leaked = [
                    t
                    for t in _sorted_named(flow.escapes(handler))
                    if any(flow.is_subclass(t, c) for c in contract)
                ]
                if not leaked:
                    continue
                chain = flow.escape_chain(handler, leaked[0])
                full = [(func.ref, mod.rel_path, site.lineno)] + chain
                classes = ", ".join(
                    f"'{display_name(t)}'" for t in leaked
                )
                yield Finding(
                    "task-exception-blackhole",
                    mod.rel_path,
                    site.lineno,
                    f"contract class(es) {classes} escape the "
                    f"'{first_arg.value}' event handler "
                    f"'{handler.qualname}' — the structured recovery "
                    "signal dies in the emitter's generic exception log "
                    f"(chain: {chain_names(full)})",
                    chain=chain_evidence(full),
                )


#: outer calls that SPAWN a fire-and-forget task (docs/CHECKS.md: the
#: rule's scope is create_task/spawn_owned handles — a coroutine handed
#: to anything else is consumed by it or flagged by other rules).
#: ``_track`` is the agent's spawn_owned wrapper.
_SPAWNERS = frozenset(
    {"create_task", "ensure_future", "spawn_owned", "_track"}
)


def _is_spawner(call: ast.Call, cm_bound_names) -> bool:
    node = call.func
    if isinstance(node, ast.Attribute):
        if node.attr not in _SPAWNERS:
            return False
        # `async with TaskGroup() as tg: tg.create_task(...)` — the
        # context manager awaits (and re-raises) its tasks at block
        # exit; a cm-bound receiver is not a blackhole
        base = node.value
        if isinstance(base, ast.Name) and base.id in cm_bound_names:
            return False
        return True
    if isinstance(node, ast.Name):
        return node.id in _SPAWNERS
    return False


def _functions_by_module(model: ProgramModel) -> Dict[object, List]:
    """ModuleInfo -> its functions, grouped from the ONE model walk
    (``ProgramModel.functions()``) so the rules can never analyze a
    different function set than the escape fixpoint ran over."""
    out: Dict[object, List] = {}
    for f in model.functions():
        out.setdefault(f.module, []).append(f)
    return out


# -- overbroad-handler ---------------------------------------------------------


@rule(
    "overbroad-handler",
    "except Exception swallows a contract class a caller handles "
    "explicitly",
    scope="program",
)
def overbroad_handler(model: ProgramModel) -> Iterator[Finding]:
    # `except Exception` around a body that provably raises
    # SessionExpiredError / OwnershipError / OperationTimeoutError /
    # StateFileError swallows a class with documented per-shape
    # handling.  It is only a bug when somebody upstream CARES: the
    # finding fires when a caller on an incoming chain handles that
    # class explicitly — evidence that the broad handler starves a
    # narrow one that was built for the signal.  The incoming chain
    # rides as structured evidence, like transitive-blocking-call.
    flow = flow_for(model)
    graph = graph_for(model)
    contract = _contract_tokens(flow)
    if not contract:
        return
    by_module = _functions_by_module(model)
    for mod in model.modules.values():
        if not mod.rel_path.startswith(PACKAGE_PREFIX):
            continue
        for func in by_module.get(mod, ()):
            if func.node is None:
                continue
            for stmt in _function_statements(func.node):
                if not isinstance(stmt, ast.Try):
                    continue
                if not any(
                    _is_broad(flow.handler_tokens(func, h.type))
                    for h in stmt.handlers
                ):
                    continue
                # Clause ORDER matters: a narrow clause ahead of the
                # broad one receives the class first — the canonical
                # narrow-then-broad defensive pattern is not a swallow.
                remaining = set(flow.block_escapes(func, stmt.body))
                for handler in stmt.handlers:
                    tokens = flow.handler_tokens(func, handler.type)
                    caught_here = {
                        t
                        for t in remaining
                        if flow.caught_by(t, tokens)
                    }
                    remaining -= caught_here
                    if not _is_broad(tokens):
                        continue  # bare except is swallowed-cancel's beat
                    if any(
                        isinstance(n, ast.Raise)
                        for n in ast.walk(handler)
                    ):
                        continue  # may re-throw: not a swallow
                    caught = [
                        t
                        for t in _sorted_named(caught_here)
                        if any(flow.is_subclass(t, c) for c in contract)
                    ]
                    for token in _root_tokens(flow, caught):
                        upstream = _explicit_upstream_handler(
                            flow, graph, func, token
                        )
                        if upstream is None:
                            continue
                        chain_funcs, catcher, handler_line = upstream
                        hops = [
                            (g.ref, g.module.rel_path, g.lineno)
                            for g in chain_funcs
                        ] + [
                            (
                                f"except {display_name(token)}",
                                catcher.module.rel_path,
                                handler_line,
                            )
                        ]
                        yield Finding(
                            "overbroad-handler",
                            mod.rel_path,
                            handler.lineno,
                            f"'except {'/'.join(sorted(tokens))}' in "
                            f"'{func.qualname}' swallows contract class "
                            f"'{display_name(token)}', which caller "
                            f"'{catcher.qualname}' handles explicitly "
                            f"(chain: {chain_names(hops)})",
                            chain=chain_evidence(hops),
                        )


def _is_broad(tokens) -> bool:
    """A literal ``except Exception`` / ``except BaseException`` clause
    (bare ``except:`` is None — swallowed-cancel's beat, not ours)."""
    return tokens is not None and tokens <= {"Exception", "BaseException"}


def _function_statements(fn_node):
    """Every statement lexically inside ``fn_node``'s own body (nested
    defs excluded — their handlers are their own)."""
    stack = list(fn_node.body)
    while stack:
        stmt = stack.pop()
        yield stmt
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, list):
                stack.extend(
                    s
                    for s in sub
                    if isinstance(s, ast.stmt)
                    and not isinstance(
                        s, (ast.FunctionDef, ast.AsyncFunctionDef)
                    )
                )
        for h in getattr(stmt, "handlers", []):
            stack.extend(
                s
                for s in h.body
                if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
            )


def _explicit_upstream_handler(flow, graph, func, token):
    """BFS up the caller graph from ``func``: the nearest ancestor with
    an ``except`` clause explicitly naming ``token`` (or a non-catch-all
    ancestor class of it).  Returns ``([chain top..func], catcher)``."""
    seen = {func}
    queue = [(func, [func])]
    depth = 0
    while queue and depth < 8:
        next_queue = []
        for current, path in queue:
            for site in graph.callers.get(current, ()):
                caller = site.func
                if caller in seen or caller.node is None:
                    continue
                if not caller.module.rel_path.startswith(PACKAGE_PREFIX):
                    continue  # a TEST catching the class is test
                    # plumbing, not evidence the daemon design wants it
                seen.add(caller)
                handler_line = _handles_explicitly(
                    flow, caller, token, site.lineno
                )
                if handler_line is not None:
                    return (
                        list(reversed(path + [caller])),
                        caller,
                        handler_line,
                    )
                next_queue.append((caller, path + [caller]))
        queue = next_queue
        depth += 1
    return None


def _handles_explicitly(flow, func, token, call_lineno: int) -> Optional[int]:
    """The line of an ``except`` clause in ``func`` naming ``token``
    whose try BODY encloses the call site at ``call_lineno`` — or None.
    A narrow handler elsewhere in the function could never receive the
    exception flowing through this call, so it does not count (and the
    returned line anchors the evidence hop at the clause itself)."""
    for stmt in _function_statements(func.node):
        if not isinstance(stmt, ast.Try) or not stmt.body:
            continue
        body_start = stmt.body[0].lineno
        body_end = getattr(
            stmt.body[-1], "end_lineno", None
        ) or stmt.body[-1].lineno
        if not (body_start <= call_lineno <= body_end):
            continue
        for handler in stmt.handlers:
            tokens = flow.handler_tokens(func, handler.type)
            if tokens is None:
                continue
            named = {
                t
                for t in tokens
                if t not in (UNKNOWN, "Exception", "BaseException")
            }
            if any(flow.is_subclass(token, t) for t in named):
                return handler.lineno
    return None


# -- fault-matrix-drift --------------------------------------------------------

_ERROR_NAME = re.compile(r"\b([A-Z][A-Za-z0-9]*Error)\b")


def _doc_error_names(path: str) -> Optional[Dict[str, int]]:
    """Exception-class names a doc mentions -> first line; None when the
    doc is absent (the rule then skips that leg)."""
    lines = read_doc_lines(path)
    if lines is None:
        return None
    out: Dict[str, int] = {}
    for i, line in enumerate(lines, start=1):
        for m in _ERROR_NAME.finditer(line):
            out.setdefault(m.group(1), i)
    return out


@rule(
    "fault-matrix-drift",
    "docs/FAULTS.md + docs/OPERATIONS.md fault matrix drifts from the "
    "provable escape surface",
    scope="program",
)
def fault_matrix_drift(model: ProgramModel) -> Iterator[Finding]:
    # The operator-facing fault matrix names exception classes; the
    # escape analysis knows which classes actually exist and provably
    # flow.  One finding per drift direction:
    #   * doc -> code: a documented class that no longer exists, or that
    #     nothing in the program raises anymore (a rename leaves the old
    #     name in the runbook — operators grep for ghosts);
    #   * code -> doc: a package-defined *Error class that provably
    #     escapes ACROSS a module boundary (it is part of the
    #     inter-module error contract) but that neither doc names.
    flow = flow_for(model)
    root = model.package_root()
    if root is None:
        return
    docs = {
        rel: _doc_error_names(os.path.join(root, *rel.split("/")))
        for rel in (FAULTS_DOC, OPS_DOC)
    }
    if all(names is None for names in docs.values()):
        return  # tree ships no fault docs: nothing to hold it against

    raised = flow.raised_tokens() | flow.constructed_tokens()
    escaping: Set[str] = set()
    cross_module: Dict[str, Tuple[str, str]] = {}
    for func in model.functions():
        for token in flow.named_escapes(func):
            escaping.add(token)
            if ":" not in token:
                continue
            if not func.module.rel_path.startswith(PACKAGE_PREFIX):
                continue  # escaping a TEST helper is not a shipped
                # contract surface
            def_module = token.rsplit(":", 1)[0]
            if def_module != func.module.name and token not in cross_module:
                cross_module[token] = (func.ref, func.module.rel_path)

    known_names = set(flow.classes_by_name)
    mentioned: Set[str] = set()
    for names in docs.values():
        if names:
            mentioned.update(names)

    # doc -> code
    for rel, names in sorted(docs.items()):
        if names is None:
            continue
        for name, lineno in sorted(names.items()):
            tokens = flow.classes_by_name.get(name, [])
            if name not in known_names and name not in BUILTIN_DOC_EXEMPT:
                yield Finding(
                    "fault-matrix-drift",
                    rel,
                    lineno,
                    f"fault matrix names exception class '{name}' but no "
                    "such class exists in the program (renamed or "
                    "removed?)",
                )
                continue
            if tokens and not any(
                t in raised or t in escaping
                or any(flow.is_subclass(r, t) for r in raised)
                for t in tokens
            ):
                yield Finding(
                    "fault-matrix-drift",
                    rel,
                    lineno,
                    f"fault matrix names exception class '{name}' but "
                    "nothing in the program raises or constructs it "
                    "anymore (stale matrix row?)",
                )

    # code -> doc
    for token in sorted(cross_module):
        name = display_name(token)
        if not name.endswith("Error") or name in mentioned:
            continue
        def_module = token.rsplit(":", 1)[0]
        mod = model.modules.get(def_module)
        if mod is None or not mod.rel_path.startswith(PACKAGE_PREFIX):
            continue
        boundary_ref, _ = cross_module[token]
        yield Finding(
            "fault-matrix-drift",
            mod.rel_path,
            0,
            f"exception class '{name}' escapes across module boundaries "
            f"(e.g. out of '{boundary_ref}') but neither {FAULTS_DOC} nor "
            f"{OPS_DOC} names it in the fault matrix",
        )


#: Classes docs legitimately mention without the program defining them
#: (the doc->code existence leg exempts them; prose about ValueError /
#: BrokenPipeError is not matrix drift).  Derived from the analysis's
#: own builtin-hierarchy and ext-alias tables so a runbook may name ANY
#: builtin the analysis itself knows — a second hand-curated list would
#: drift behind the first.
BUILTIN_DOC_EXEMPT = frozenset(
    set(BUILTIN_PARENTS)
    | set(BUILTIN_PARENTS.values())
    | {"BaseException", "CancelledError"}
    | {k.rsplit(".", 1)[-1] for k in EXT_ALIASES}
)
