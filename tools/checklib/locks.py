"""Interprocedural lock-acquisition-order analysis (generation 4).

The PR-3 single-flight invariant says *who* must hold the lock; nothing
so far checks in what ORDER locks are taken when there is more than one.
Two coroutines acquiring ``{A, B}`` in opposite orders deadlock the
daemon silently — the process stays alive, its heartbeats stop, its
ephemerals rot (the exact liveness failure the paper's §2.6 contract
exists to prevent), and no test notices until the interleaving happens
to land.  This module makes the ordering a static artifact:

  * every lexical ``async with <lock>`` site whose lock expression
    resolves to a stable identity becomes an **acquisition site**;
  * held-lock sets propagate forward along the PR-6 resolved call edges
    (a callee invoked under a lock runs with it held), each held lock
    carrying the witness chain of hops that led to the hold;
  * each acquisition performed while other locks are held contributes
    **order edges** ``held -> acquired`` to a global graph;
  * a cycle in that graph is a deadlock candidate
    (``lock-order-cycle``), reported once per lock set with every
    participating acquisition chain as structured evidence — including
    the degenerate self-loop (``asyncio.Lock`` is not reentrant: taking
    a lock you already hold deadlocks immediately, no second coroutine
    required);
  * ``zk-op-under-lock`` flags a call site that is lexically under one
    of the agent-orbit locks (rules_flow.LOCK_SCOPED_MODULES) and
    provably reaches ``connect_with_backoff`` — the *unbounded*
    session-(re)establishment retry loop.  Holding the single-flight
    lock across it wedges every other repair/heartbeat flow for as long
    as the ensemble stays unreachable (the PR-2 drain-wedge class,
    caught before merge instead of in a chaos run).

Lock identity resolution is conservative in the file-local tradition
(zero false positives beats coverage):

  * ``self.<attr>`` with a known enclosing class -> ``module:Class.attr``
    (the per-class abstraction: all instances share an ordering
    discipline, which is exactly what an order graph is about);
  * a bare name assigned exactly once in an enclosing function scope
    from a ``...Lock()`` constructor -> that function's local lock;
  * a module-level name bound exactly once, by assignment from a
    ``...Lock()`` constructor -> a module-global lock;
  * anything else (parameters, rebindings, degraded modules, opaque
    expressions) does not resolve, and an unresolved lock contributes
    neither held-set entries nor order edges — conservative silence.
"""

from __future__ import annotations

import ast
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Set, Tuple

from checklib.callgraph import chain_evidence, chain_names
from checklib.model import Finding
from checklib.program import (
    CallSite,
    FunctionInfo,
    ModuleInfo,
    ProgramModel,
    _dotted,
    _is_lock_expr,
)
from checklib.registry import rule
from checklib.rules_flow import LOCK_SCOPED_MODULES, graph_for

#: Constructor names that build a mutual-exclusion primitive.  The
#: *name* being bound must also look like a lock (_is_lock_expr) before
#: this is ever consulted, so `cond = asyncio.Condition()` never enters
#: the domain through the back door.
_LOCK_CTORS = frozenset({"Lock", "RLock"})

#: The unbounded session-(re)establishment boundary zk-op-under-lock
#: guards: every retry loop the zk client exposes funnels through it.
_SESSION_RETRY = "connect_with_backoff"

#: A chain hop: (symbol, rel_path, line) — the same shape callgraph.py's
#: chains use, so chain_names/chain_evidence render them identically.
Hop = Tuple[str, str, int]


def _short(lock_id: str) -> str:
    """Operator-facing name for a lock id (last dotted component)."""
    return lock_id.rsplit(".", 1)[-1].rsplit(":", 1)[-1]


def _is_lock_ctor(value) -> bool:
    if isinstance(value, ast.Await):
        value = value.value
    if not isinstance(value, ast.Call):
        return False
    d = _dotted(value.func)
    if d is None:
        return False
    base, attrs = d
    return (attrs[-1] if attrs else base) in _LOCK_CTORS


def _scope_stmts(node) -> Iterator[ast.stmt]:
    """Statements belonging to one function scope: the body, recursing
    through compound statements but NOT into nested def/class bodies."""
    stack: List[ast.stmt] = list(node.body)
    while stack:
        stmt = stack.pop()
        yield stmt
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                stack.append(child)
            elif isinstance(child, ast.excepthandler):
                stack.extend(child.body)


def _local_binding_assigns(func: FunctionInfo, name: str) -> Optional[list]:
    """The ``name = ...`` assignment statements binding ``name`` in
    ``func``'s own scope, or None when the name is bound by anything
    other than plain assignments (with-as, for target, import, ...) —
    the ambiguous cases identity resolution refuses to guess about."""
    if func.node is None:
        return None
    assigns: List[ast.Assign] = []
    for stmt in _scope_stmts(func.node):
        if isinstance(stmt, ast.Assign):
            hit = False
            for t in stmt.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        hit = True
            if hit:
                if len(stmt.targets) != 1 or not isinstance(
                    stmt.targets[0], ast.Name
                ):
                    return None  # tuple/chained target: ambiguous
                assigns.append(stmt)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            t = stmt.target
            if isinstance(t, ast.Name) and t.id == name:
                return None
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(stmt.target):
                if isinstance(sub, ast.Name) and sub.id == name:
                    return None
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is None:
                    continue
                for sub in ast.walk(item.optional_vars):
                    if isinstance(sub, ast.Name) and sub.id == name:
                        return None
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for alias in stmt.names:
                if (alias.asname or alias.name.split(".")[0]) == name:
                    return None
    return assigns


def _module_lock_assign(mod: ModuleInfo, name: str) -> Optional[ast.Assign]:
    """The single module-level ``name = ...Lock()`` assignment, if the
    module binds ``name`` exactly that way and no other."""
    if mod.degraded:
        return None
    if mod.bindings.get(name) != {"assign"}:
        return None
    assigns: List[ast.Assign] = []

    def scan(stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name) and sub.id == name:
                            assigns.append(stmt)
            elif isinstance(stmt, (ast.If, ast.Try)):
                for child in ast.iter_child_nodes(stmt):
                    if isinstance(child, ast.stmt):
                        scan([child])
                for handler in getattr(stmt, "handlers", []):
                    scan(handler.body)

    scan(mod.ctx.tree.body)
    if len(assigns) != 1 or not _is_lock_ctor(assigns[0].value):
        return None
    return assigns[0]


class LockGraph:
    """The analysis: build once per run (:func:`lockgraph_for`), query
    per rule."""

    def __init__(self, model: ProgramModel):
        self.model = model
        self.graph = graph_for(model)
        t0 = time.monotonic()
        #: resolved acquisition events:
        #: (lock_id, func, lineno, lexical held {lock_id: chain})
        self._acquisitions: List[tuple] = []
        #: CallSite -> {lock_id: chain} held LEXICALLY at the site
        self._lexical_held: Dict[CallSite, Dict[str, List[Hop]]] = {}
        #: lock_id -> rel_path of the module defining it
        self._lock_paths: Dict[str, str] = {}
        self._functions = list(model.functions())
        for func in self._functions:
            if func.node is not None:
                self._walk_function(func)
        #: FunctionInfo -> {lock_id: chain} held at ENTRY on some path
        self._entry_held: Dict[FunctionInfo, Dict[str, List[Hop]]] = {}
        self._fixpoint()
        #: (held, acquired) -> first witness chain
        self.edges: Dict[Tuple[str, str], List[Hop]] = {}
        self._build_edges()
        self.lock_sites = len(self._acquisitions)
        self.build_seconds = round(time.monotonic() - t0, 4)

    # -- lock identity ----------------------------------------------------

    def _lock_id(self, func: FunctionInfo, expr) -> Optional[str]:
        d = _dotted(expr)
        if d is None:
            return None
        base, attrs = d
        if base in ("self", "cls"):
            if len(attrs) != 1 or func.cls is None:
                return None
            lock_id = f"{func.module.name}:{func.cls}.{attrs[0]}"
            self._lock_paths.setdefault(lock_id, func.module.rel_path)
            return lock_id
        if attrs:
            return None  # foreign-object / module-attr lock: not modeled
        if base in func.param_chain():
            return None  # a lock handed in: identity unknowable here
        f: Optional[FunctionInfo] = func
        while f is not None:
            assigns = _local_binding_assigns(f, base)
            if assigns is None:
                return None  # bound ambiguously somewhere on the chain
            if assigns:
                if len(assigns) != 1 or not _is_lock_ctor(assigns[0].value):
                    return None
                lock_id = f"{f.ref}.{base}"
                self._lock_paths.setdefault(lock_id, f.module.rel_path)
                return lock_id
            f = f.parent
        if _module_lock_assign(func.module, base) is not None:
            lock_id = f"{func.module.name}:{base}"
            self._lock_paths.setdefault(lock_id, func.module.rel_path)
            return lock_id
        return None

    def lock_path(self, lock_id: str) -> Optional[str]:
        return self._lock_paths.get(lock_id)

    # -- lexical walk -----------------------------------------------------

    def _walk_function(self, func: FunctionInfo) -> None:
        rel = func.module.rel_path
        sites = {id(s.node): s for s in func.calls}

        def walk(node, held: Dict[str, List[Hop]]) -> None:
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                 ast.Lambda),
            ):
                return  # separate scopes; the fixpoint covers their calls
            if isinstance(node, ast.AsyncWith):
                inner = held
                for item in node.items:
                    walk(item.context_expr, inner)
                    if item.optional_vars is not None:
                        walk(item.optional_vars, inner)
                    if not _is_lock_expr(item.context_expr):
                        continue
                    lock_id = self._lock_id(func, item.context_expr)
                    if lock_id is None:
                        continue
                    lineno = item.context_expr.lineno
                    self._acquisitions.append(
                        (lock_id, func, lineno, dict(inner))
                    )
                    if inner is held:
                        inner = dict(held)
                    inner[lock_id] = [
                        (func.ref, rel, lineno),
                        (f"async with {_short(lock_id)}", rel, lineno),
                    ]
                for stmt in node.body:
                    walk(stmt, inner)
                return
            if isinstance(node, ast.Call):
                site = sites.get(id(node))
                if site is not None and held:
                    self._lexical_held[site] = dict(held)
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in func.node.body:
            walk(stmt, {})

    # -- interprocedural held-set fixpoint --------------------------------

    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for func in self._functions:
                entry = self._entry_held.get(func)
                for site in func.calls:
                    held: Dict[str, List[Hop]] = dict(entry or {})
                    held.update(self._lexical_held.get(site, {}))
                    if not held:
                        continue
                    res = self.graph.resolve(site)
                    if res is None or res[0] != "func":
                        continue
                    callee = res[1]
                    target = self._entry_held.setdefault(callee, {})
                    for lock_id, chain in held.items():
                        if lock_id in target:
                            continue
                        target[lock_id] = chain + [
                            (
                                func.ref,
                                func.module.rel_path,
                                site.lineno,
                            )
                        ]
                        changed = True

    def _build_edges(self) -> None:
        for lock_id, func, lineno, lexical in self._acquisitions:
            held: Dict[str, List[Hop]] = dict(
                self._entry_held.get(func, {})
            )
            held.update(lexical)
            if not held:
                continue
            rel = func.module.rel_path
            suffix: List[Hop] = [
                (func.ref, rel, lineno),
                (f"async with {_short(lock_id)}", rel, lineno),
            ]
            for prior in sorted(held):
                key = (prior, lock_id)
                if key not in self.edges:
                    self.edges[key] = held[prior] + suffix

    # -- queries ----------------------------------------------------------

    def held_at(self, site: CallSite) -> Dict[str, List[Hop]]:
        """Every resolved lock provably held at ``site`` on some path
        (lexical block or caller chain), with its acquisition chain."""
        held = dict(self._entry_held.get(site.func, {}))
        held.update(self._lexical_held.get(site, {}))
        return held

    def lexically_held_sites(self):
        for site, held in self._lexical_held.items():
            yield site, held

    def cycles(self) -> List[Tuple[List[str], List[List[Hop]]]]:
        """Each distinct cyclic lock set, once: ``(locks in cycle order,
        witness chain per participating edge)``.  Deterministic: edges
        are explored in sorted order, so the reported representative
        cycle is stable across runs (it is the baseline identity)."""
        adj: Dict[str, Set[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, set()).add(b)
        out: List[Tuple[List[str], List[List[Hop]]]] = []
        reported: Set[frozenset] = set()
        for a, b in sorted(self.edges):
            if a == b:
                key = frozenset({a})
                if key not in reported:
                    reported.add(key)
                    out.append(([a], [self.edges[(a, b)]]))
                continue
            path = self._edge_path(b, a, adj)
            if path is None:
                continue
            locks = [a, b] + [edge[1] for edge in path[:-1]]
            key = frozenset(locks)
            if key in reported:
                continue
            reported.add(key)
            witnesses = [self.edges[(a, b)]] + [
                self.edges[edge] for edge in path
            ]
            out.append((locks, witnesses))
        return out

    def _edge_path(self, start: str, goal: str, adj) -> Optional[list]:
        """Shortest edge list start -> ... -> goal over the order graph."""
        seen = {start}
        queue: deque = deque([(start, [])])
        while queue:
            node, path = queue.popleft()
            for nxt in sorted(adj.get(node, ())):
                edge = (node, nxt)
                if nxt == goal:
                    return path + [edge]
                if nxt not in seen:
                    seen.add(nxt)
                    queue.append((nxt, path + [edge]))
        return None

    def session_retry_chain(self, site: CallSite) -> Optional[List[Hop]]:
        """Chain from ``site`` to a ``connect_with_backoff`` callee over
        resolved edges, or None.  Sync and async edges both count: the
        hold spans every await in the lexical block."""
        rel = site.func.module.rel_path
        start: List[Hop] = [(site.func.ref, rel, site.lineno)]
        hit = self._session_retry_target(site)
        if hit is not None:
            return start + [hit]
        res = self.graph.resolve(site)
        if res is None or res[0] != "func":
            return None
        seen: Set[FunctionInfo] = {res[1]}
        queue: deque = deque([(res[1], start)])
        while queue:
            func, path = queue.popleft()
            for inner in func.calls:
                hit = self._session_retry_target(inner)
                if hit is not None:
                    return path + [
                        (func.ref, func.module.rel_path, inner.lineno),
                        hit,
                    ]
            for inner in func.calls:
                r = self.graph.resolve(inner)
                if r is None or r[0] != "func" or r[1] in seen:
                    continue
                seen.add(r[1])
                queue.append(
                    (
                        r[1],
                        path + [
                            (func.ref, func.module.rel_path, inner.lineno)
                        ],
                    )
                )
        return None

    def _session_retry_target(self, site: CallSite) -> Optional[Hop]:
        res = self.graph.resolve(site)
        if res is None:
            return None
        if res[0] == "func" and res[1].name == _SESSION_RETRY:
            callee = res[1]
            return (callee.ref, callee.module.rel_path, callee.lineno)
        if res[0] == "ext" and (
            res[1] == _SESSION_RETRY
            or res[1].endswith("." + _SESSION_RETRY)
        ):
            return (res[1], site.func.module.rel_path, site.lineno)
        return None

    def stats(self) -> dict:
        return {
            "lock_sites": self.lock_sites,
            "lock_edges": len(self.edges),
            "lock_build_s": self.build_seconds,
        }


def lockgraph_for(model: ProgramModel) -> LockGraph:
    """One LockGraph per program model, shared by both lock rules (and
    surfaced into ``--stats`` by the engine)."""
    lg = getattr(model, "_lockgraph", None)
    if lg is None:
        lg = LockGraph(model)
        model._lockgraph = lg
    return lg


@rule(
    "lock-order-cycle",
    "locks acquired in inconsistent order on different call paths "
    "(deadlock)",
    scope="program",
)
def lock_order_cycle(model: ProgramModel) -> Iterator[Finding]:
    # One finding per cyclic lock SET, anchored where the first edge's
    # second lock is taken; the evidence concatenates every
    # participating acquisition chain so both (all) sides of the
    # inversion are walkable in the JSON/SARIF report.
    lg = lockgraph_for(model)
    for locks, witnesses in lg.cycles():
        evidence = [hop for w in witnesses for hop in w]
        anchor = witnesses[0][-1]
        if len(locks) == 1:
            message = (
                f"lock '{_short(locks[0])}' is re-acquired while already "
                f"held (asyncio locks are not reentrant: this deadlocks "
                f"immediately; chain: {chain_names(evidence)})"
            )
        else:
            order = " -> ".join(_short(l) for l in locks + locks[:1])
            chains = " vs ".join(chain_names(w) for w in witnesses)
            message = (
                f"locks acquired in inconsistent order ({order}): a "
                f"deadlock needs only the right interleaving "
                f"(chains: {chains})"
            )
        yield Finding(
            "lock-order-cycle",
            anchor[1],
            anchor[2],
            message,
            chain=chain_evidence(evidence),
        )


@rule(
    "zk-op-under-lock",
    "unbounded session-(re)establishment retry held under an agent-orbit "
    "lock",
    scope="program",
)
def zk_op_under_lock(model: ProgramModel) -> Iterator[Finding]:
    # connect_with_backoff retries until the ensemble answers — by
    # design, unbounded.  Reached under one of the agent-orbit locks
    # (rules_flow.LOCK_SCOPED_MODULES), the hold outlives any repair the
    # lock exists to serialize: heartbeat repair, rebirth and reload all
    # queue behind a coroutine that may never return (the PR-2 drain
    # wedge, as a static fact).  Only LEXICAL lock blocks in the scoped
    # modules are scanned — an interior helper that is sometimes called
    # under the lock gets its finding at the lexical site that created
    # the hold, never twice.
    lg = lockgraph_for(model)
    for site, held in lg.lexically_held_sites():
        if site.func.module.rel_path not in LOCK_SCOPED_MODULES:
            continue
        scoped = {
            lock_id: chain
            for lock_id, chain in held.items()
            if lg.lock_path(lock_id) in LOCK_SCOPED_MODULES
        }
        if not scoped:
            continue
        retry_chain = lg.session_retry_chain(site)
        if retry_chain is None:
            continue
        lock_id = sorted(scoped)[0]
        full = scoped[lock_id] + retry_chain
        yield Finding(
            "zk-op-under-lock",
            site.func.module.rel_path,
            site.lineno,
            f"'{_SESSION_RETRY}' (unbounded session retry) reached while "
            f"holding '{_short(lock_id)}': every flow serialized by the "
            f"lock wedges for as long as the ensemble stays unreachable "
            f"(chain: {chain_names(full)})",
            chain=chain_evidence(full),
        )
