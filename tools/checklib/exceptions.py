"""Interprocedural exception-escape analysis (generation 3).

Per-function *escape sets* — which exception classes can propagate out of
each ``def`` — computed by fixpoint over the PR-6 call graph:

  * **raise sites**: ``raise X(...)`` / ``raise X`` resolved through the
    cross-module symbol table to the defining class (in-model classes
    canonicalize to ``module:Class``; builtins to their bare name);
  * **callee propagation**: a resolved call contributes its callee's
    current escape set (awaited calls for ``async def`` callees — an
    un-awaited coroutine call raises nothing *here*, which is exactly
    what the task-blackhole rule reasons about instead);
  * **handler modeling**: ``try/except`` filters the body's escapes per
    clause, in clause order, including tuple clauses
    (``except (A, B):``), bare re-raise (``raise`` inside a handler
    re-throws the subset that clause caught), ``except X as e: raise e``
    (same), ``else``/``finally`` blocks, and the exception class
    hierarchy (an ``except StateFileError`` catches ``StateFileMissing``)
    resolved through in-model bases plus a curated builtin hierarchy;
  * **conservative widening**: every edge the model cannot resolve — an
    opaque call, a dynamic raise (``raise err``), an external callable —
    contributes the ``UNKNOWN`` token.  Escape sets therefore *over*-
    approximate with an explicit marker, and the rules in
    ``rules_errors.py`` only ever act on **named** classes: a finding
    claims "this class provably flows here", never "nothing else can".

Two deliberate asymmetries keep the zero-false-positive contract:

  * an **unresolvable handler clause** (``except plugin.Error:`` where
    the name doesn't resolve) is assumed to catch *everything* — the
    direction that yields fewer findings;
  * ``CancelledError`` / ``GeneratorExit`` / ``KeyboardInterrupt`` /
    ``SystemExit`` are excluded from the domain entirely: they are
    control-flow signals with their own rule (swallowed-cancel), not
    part of the error contract.

One resolution step goes beyond the call graph's: a method call on an
*opaque* receiver (``zk.heartbeat(...)`` where ``zk`` is a parameter)
resolves to the method when **exactly one** class in the whole model
defines that method name — the same duck-typing bet the mutator rule
makes for ``zk.put``, applied to exception propagation.  Ambiguous names
(``get``, ``close``, ``run``) stay unresolved.
"""

from __future__ import annotations

import ast
import time
from typing import Dict, List, Optional, Set, Tuple

from checklib.program import (
    CallSite,
    FunctionInfo,
    ModuleInfo,
    ProgramModel,
    _dotted,
)

#: The widening marker: "something the model cannot name can also
#: escape here".  Rules never act on it.
UNKNOWN = "<unknown>"

#: Control-flow signals excluded from the escape domain (module docstring).
_SIGNALS = frozenset(
    {"CancelledError", "GeneratorExit", "KeyboardInterrupt", "SystemExit"}
)

#: Curated builtin exception hierarchy (child -> parent).  Only classes
#: this tree can plausibly meet; anything absent resolves to UNKNOWN.
BUILTIN_PARENTS: Dict[str, str] = {
    "Exception": "BaseException",
    "ArithmeticError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
    "FloatingPointError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "AssertionError": "Exception",
    "AttributeError": "Exception",
    "BufferError": "Exception",
    "EOFError": "Exception",
    "ImportError": "Exception",
    "ModuleNotFoundError": "ImportError",
    "LookupError": "Exception",
    "IndexError": "LookupError",
    "KeyError": "LookupError",
    "MemoryError": "Exception",
    "NameError": "Exception",
    "UnboundLocalError": "NameError",
    "OSError": "Exception",
    "ConnectionError": "OSError",
    "BrokenPipeError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "ConnectionResetError": "ConnectionError",
    "BlockingIOError": "OSError",
    "ChildProcessError": "OSError",
    "FileExistsError": "OSError",
    "FileNotFoundError": "OSError",
    "InterruptedError": "OSError",
    "IsADirectoryError": "OSError",
    "NotADirectoryError": "OSError",
    "PermissionError": "OSError",
    "ProcessLookupError": "OSError",
    "TimeoutError": "OSError",
    "ReferenceError": "Exception",
    "RuntimeError": "Exception",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "StopIteration": "Exception",
    "StopAsyncIteration": "Exception",
    "SyntaxError": "Exception",
    "IndentationError": "SyntaxError",
    "SystemError": "Exception",
    "TypeError": "Exception",
    "ValueError": "Exception",
    "UnicodeError": "ValueError",
    "UnicodeDecodeError": "UnicodeError",
    "UnicodeEncodeError": "UnicodeError",
}

#: External dotted names that alias a builtin (version drift absorbed:
#: asyncio.TimeoutError IS TimeoutError on 3.11+, a distinct Exception
#: subclass before — parenting it at Exception is sound either way
#: because TimeoutError is itself an Exception subclass).
EXT_ALIASES: Dict[str, str] = {
    "asyncio.CancelledError": "CancelledError",
    "asyncio.TimeoutError": "TimeoutError",
    "asyncio.exceptions.CancelledError": "CancelledError",
    "socket.timeout": "TimeoutError",
    "socket.error": "OSError",
    "socket.gaierror": "OSError",
    "binascii.Error": "ValueError",  # parent, not alias — close enough
    "json.JSONDecodeError": "ValueError",
    "asyncio.IncompleteReadError": "EOFError",
}


def display_name(token: str) -> str:
    """Operator-facing class name for a token (``a.b:X`` -> ``X``)."""
    return token.rsplit(":", 1)[-1].rsplit(".", 1)[-1]


class ExceptionFlow:
    """The analysis: build once per run (``flow_for``), query per rule."""

    def __init__(self, model: ProgramModel, graph):
        self.model = model
        self.graph = graph
        t0 = time.monotonic()
        #: in-model class token -> list of parent tokens
        self.class_parents: Dict[str, List[str]] = {}
        #: bare class name -> list of in-model tokens carrying it
        self.classes_by_name: Dict[str, List[str]] = {}
        self._build_class_table()
        #: method name -> FunctionInfo when exactly ONE model class
        #: defines it (the opaque-receiver duck resolution); None when
        #: ambiguous.
        self._unique_methods: Dict[str, Optional[FunctionInfo]] = {}
        self._build_method_index()
        self._subclass_cache: Dict[Tuple[str, str], bool] = {}
        #: FunctionInfo -> compiled body IR
        self._ir: Dict[FunctionInfo, list] = {}
        #: FunctionInfo -> escape token set (the fixpoint result)
        self._escapes: Dict[FunctionInfo, Set[str]] = {}
        #: (FunctionInfo, token) -> witness hop: (lineno, callee|None)
        #: — callee None means a raise site in this very function.
        self._witness: Dict[Tuple[FunctionInfo, str], Tuple[int, object]] = {}
        #: every token with a literal raise site anywhere (caught or not)
        self._raised: Set[str] = set()
        #: synthetic CallSites (thunk/lambda resolution) pinned for the
        #: flow's lifetime: CallGraph.resolve caches by id(site), so a
        #: garbage-collected synthetic could let a NEW site inherit a
        #: stale resolution through id reuse
        self._pinned: List[CallSite] = []
        self._functions = list(model.functions())
        self._compile_all()
        self.iterations = self._fixpoint()
        self.build_seconds = round(time.monotonic() - t0, 4)

    # -- class table ------------------------------------------------------

    def _build_class_table(self) -> None:
        for mod in self.model.modules.values():
            for cname, cls in mod.classes.items():
                token = f"{mod.name}:{cname}"
                self.classes_by_name.setdefault(cname, []).append(token)
                parents: List[str] = []
                for base, battrs in cls.bases:
                    parent = self._resolve_class_ref(mod, base, battrs)
                    if parent is not None:
                        parents.append(parent)
                self.class_parents[token] = parents

    def _resolve_class_ref(self, mod: ModuleInfo, base: str, attrs) -> Optional[str]:
        """Token for a class *reference expression* in ``mod`` — an
        in-model token, a builtin name, an ext alias, or None."""
        if mod.degraded:
            # a star/dynamic import can shadow ANY name, builtins
            # included: nothing in this module resolves (program.py's
            # degradation contract applied to the class domain)
            return None
        if not attrs:
            if base in mod.classes:
                return f"{mod.name}:{base}"
            src = mod.from_imports.get(base)
            if src is not None:
                source, orig = src
                sub = f"{source}.{orig}"
                if sub in self.model.modules:
                    return None  # a module, not a class
                if source in self.model.modules:
                    target = self.model.modules[source]
                    if orig in target.classes:
                        return f"{target.name}:{orig}"
                    return None
                dotted = f"{source}.{orig}"
                return EXT_ALIASES.get(dotted, dotted)
            if base in mod.imports:
                return None  # a module called bare: not a class
            if base in BUILTIN_PARENTS or base == "BaseException":
                # only when nothing module-level shadows the builtin
                if base not in mod.bindings:
                    return base
            return None
        if len(attrs) == 1 and base in mod.imports:
            target_name = mod.imports[base]
            target = self.model.modules.get(target_name)
            if target is not None:
                if attrs[0] in target.classes:
                    return f"{target.name}:{attrs[0]}"
                return None
            dotted = f"{target_name}.{attrs[0]}"
            return EXT_ALIASES.get(dotted, dotted)
        if len(attrs) == 1 and base in mod.from_imports:
            source, orig = mod.from_imports[base]
            sub = f"{source}.{orig}"
            target = self.model.modules.get(sub)
            if target is not None:
                if attrs[0] in target.classes:
                    return f"{target.name}:{attrs[0]}"
                return None
        return None

    def _build_method_index(self) -> None:
        counts: Dict[str, List[FunctionInfo]] = {}
        for mod in self.model.modules.values():
            for cls in mod.classes.values():
                for name, fn in cls.methods.items():
                    counts.setdefault(name, []).append(fn)
        for name, fns in counts.items():
            self._unique_methods[name] = fns[0] if len(fns) == 1 else None

    def is_subclass(self, token: str, ancestor: str) -> bool:
        """Reflexive-transitive subclass test over in-model bases plus
        the builtin table.  UNKNOWN is a subclass of nothing."""
        if token == UNKNOWN:
            return False
        key = (token, ancestor)
        cached = self._subclass_cache.get(key)
        if cached is not None:
            return cached
        seen: Set[str] = set()
        frontier = [token]
        result = False
        while frontier:
            t = frontier.pop()
            if t in seen:
                continue
            seen.add(t)
            if t == ancestor:
                result = True
                break
            frontier.extend(self.class_parents.get(t, ()))
            parent = BUILTIN_PARENTS.get(t)
            if parent is not None:
                frontier.append(parent)
            # ext dotted names with no known parent simply contribute no
            # further ancestors — the walk ends there
        self._subclass_cache[key] = result
        return result

    def caught_by(self, token: str, handler_tokens) -> bool:
        """Would a handler naming ``handler_tokens`` catch ``token``?

        ``handler_tokens`` of None means a bare ``except:``.  An UNKNOWN
        *handler* element catches everything (conservative: fewer
        escapes).  An ``except Exception`` clause also catches EVERY
        token — UNKNOWN, external classes with no known hierarchy
        (``extlib.WireError``), and in-model classes whose base chain
        the model cannot follow: the only BaseException-not-Exception
        descendants this domain could meet are the control-flow signals,
        and those are excluded from it entirely.  Anything else would
        let a named external class "escape" a broad handler that
        provably swallows it — a false positive."""
        if handler_tokens is None:
            return True
        if (
            UNKNOWN in handler_tokens
            or "BaseException" in handler_tokens
            or "Exception" in handler_tokens
        ):
            return True
        if token == UNKNOWN:
            return False
        return any(self.is_subclass(token, h) for h in handler_tokens)

    # -- expression -> exception token ------------------------------------

    def class_token(self, func: FunctionInfo, expr) -> str:
        """Token for an exception-class expression at a site inside
        ``func`` (handler clause element, or the callee of
        ``raise X(...)``).  UNKNOWN when unresolvable or shadowed."""
        d = _dotted(expr)
        if d is None:
            return UNKNOWN
        base, attrs = d
        if base in func.param_chain():
            return UNKNOWN
        token = self._resolve_class_ref(func.module, base, attrs)
        return token if token is not None else UNKNOWN

    def handler_tokens(self, func: FunctionInfo, handler_type) -> Optional[frozenset]:
        """Clause classes for one except handler; None = bare except."""
        if handler_type is None:
            return None
        elts = (
            handler_type.elts
            if isinstance(handler_type, ast.Tuple)
            else [handler_type]
        )
        return frozenset(self.class_token(func, e) for e in elts)

    # -- IR ----------------------------------------------------------------

    def _compile_all(self) -> None:
        for func in self._functions:
            self._escapes[func] = set()
            if func.node is None:
                self._ir[func] = []
                continue
            sites = {id(s.node): s for s in func.calls}
            self._ir[func] = self._compile_block(func, func.node.body, sites)

    def _compile_block(self, func, stmts, sites) -> list:
        out: list = []

        def walk_expr(node) -> None:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                       ast.ClassDef)
            ):
                return  # separate scopes (lambdas: conservative silence)
            if isinstance(node, ast.Call):
                site = sites.get(id(node))
                if site is not None:
                    out.append(("call", site))
            for child in ast.iter_child_nodes(node):
                walk_expr(child)

        for stmt in stmts:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(stmt, ast.Raise):
                if stmt.exc is not None:
                    walk_expr(stmt.exc)  # constructor args can call too
                out.append(self._compile_raise(func, stmt))
                continue
            if isinstance(stmt, ast.Try):
                for item in getattr(stmt, "handlers", []):
                    if item.type is not None:
                        walk_expr(item.type)
                body = self._compile_block(func, stmt.body, sites)
                handlers = [
                    (
                        self.handler_tokens(func, h.type),
                        self._compile_block(func, h.body, sites),
                    )
                    for h in stmt.handlers
                ]
                orelse = self._compile_block(func, stmt.orelse, sites)
                final = self._compile_block(func, stmt.finalbody, sites)
                out.append(("try", body, handlers, orelse, final))
                continue
            match_cls = getattr(ast, "Match", None)
            if match_cls is not None and isinstance(stmt, match_cls):
                walk_expr(stmt.subject)
                for case in stmt.cases:
                    out.extend(self._compile_block(func, case.body, sites))
                continue
            # every other statement: harvest call sites in source order,
            # recursing into nested blocks (if/for/while/with bodies are
            # transparent to exception flow)
            nested_blocks = []
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list) and sub and isinstance(
                    sub[0], ast.stmt
                ):
                    nested_blocks.append(sub)
            if nested_blocks:
                for child in ast.iter_child_nodes(stmt):
                    if not isinstance(child, ast.stmt):
                        walk_expr(child)
                for sub in nested_blocks:
                    out.extend(self._compile_block(func, sub, sites))
            else:
                walk_expr(stmt)
        return out

    def _compile_raise(self, func: FunctionInfo, stmt: ast.Raise):
        if stmt.exc is None:
            return ("reraise", stmt.lineno)
        exc = stmt.exc
        # `raise X(...)` -> the class is the callee; `raise X` -> X itself
        target = exc.func if isinstance(exc, ast.Call) else exc
        if isinstance(target, ast.Name) and self._is_handler_bound(
            func, stmt, target.id
        ):
            return ("reraise", stmt.lineno)
        token = self.class_token(func, target)
        if display_name(token) in _SIGNALS:
            return ("raise", frozenset(), stmt.lineno)
        if token != UNKNOWN:
            self._raised.add(token)
        return ("raise", frozenset({token}), stmt.lineno)

    def _is_handler_bound(self, func, stmt, name: str) -> bool:
        """Is ``raise <name>`` at ``stmt`` re-raising the innermost
        enclosing ``except ... as <name>`` binding?"""
        if func.node is None:
            return False
        best: Optional[str] = None

        def walk(node, current):
            nonlocal best
            if node is stmt:
                best = current
                return True
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ) and node is not func.node:
                return False
            if isinstance(node, ast.Try):
                for child in node.body + node.orelse + node.finalbody:
                    if walk(child, current):
                        return True
                for h in node.handlers:
                    inner = h.name if h.name else current
                    for child in h.body:
                        if walk(child, inner):
                            return True
                return False
            for child in ast.iter_child_nodes(node):
                if walk(child, current):
                    return True
            return False

        walk(func.node, None)
        return best == name

    # -- fixpoint ----------------------------------------------------------

    def call_escapes(self, site: CallSite) -> Tuple[Set[str], object]:
        """(escape set, resolved callee or None) for one call site under
        the CURRENT fixpoint state."""
        res = self.graph.resolve(site)
        if res is not None and res[0] == "func":
            callee = res[1]
            if callee.is_async and not site.awaited:
                return set(), callee  # coroutine object only: raises nowhere
            return set(self._escapes.get(callee, ())), callee
        # a CLASS call is a constructor: its exceptions are __init__'s
        # (an in-model class with no modeled __init__ — field record,
        # plain Exception subclass — raises nothing named; a builtin
        # exception constructor likewise).  Checked before the ext
        # branch so `asyncio.CancelledError()` resolves as a signal
        # constructor, not an unknown external callable.
        ctor = self._constructor_target(site)
        if ctor is not None:
            init, token = ctor
            if init is not None:
                return set(self._escapes.get(init, ())), init
            return set(), None
        if res is not None and res[0] == "ext":
            return {UNKNOWN}, None
        # opaque-receiver duck resolution (module docstring)
        callee = self._duck_resolve(site)
        if callee is not None:
            if callee.is_async and not site.awaited:
                return set(), callee
            return set(self._escapes.get(callee, ())), callee
        return {UNKNOWN}, None

    def _constructor_target(self, site: CallSite):
        """(init FunctionInfo or None, class token) when the call's
        callee expression resolves to a class; None when it is not a
        class reference at all."""
        if site.shape[0] == "name":
            base, attrs = site.shape[1], ()
        elif site.shape[0] == "dotted":
            base, attrs = site.shape[1], site.shape[2]
        else:
            return None
        if base in site.func.param_chain():
            return None
        token = self._resolve_class_ref(site.func.module, base, attrs)
        if token is None:
            return None
        if ":" in token:
            mod_name, cname = token.rsplit(":", 1)
            mod = self.model.modules.get(mod_name)
            cls = mod.classes.get(cname) if mod is not None else None
            init = cls.methods.get("__init__") if cls is not None else None
            return init, token
        if (
            token in BUILTIN_PARENTS
            or token == "BaseException"
            or display_name(token) in _SIGNALS
        ):
            return None, token  # builtin exception ctor: raises nothing
        return None

    def _duck_resolve(self, site: CallSite) -> Optional[FunctionInfo]:
        if site.shape[0] != "dotted":
            return None
        base, attrs = site.shape[1], site.shape[2]
        method = attrs[-1]
        target = self._unique_methods.get(method)
        if target is None:
            return None
        # the receiver must be opaque: a parameter, self/cls, or a name
        # with no module-level resolution (a local) — a base resolving
        # to a module or model object is something else entirely.
        if base not in ("self", "cls") and base not in site.func.param_chain():
            if self.graph._module_binding_target(site.func.module, base) is not None:
                return None
        return target

    def _eval_block(self, func, block, caught: Dict[str, tuple]) -> Dict[str, tuple]:
        """token -> witness hop ``(lineno, callee|None)`` for everything
        escaping ``block``.  Witnesses travel WITH their tokens through
        the handler filtering, so a raise that is subsequently caught
        can never end up as the evidence for a token that escaped some
        other way (the JSON/SARIF chains operators are told to trust)."""
        out: Dict[str, tuple] = {}
        for node in block:
            kind = node[0]
            if kind == "raise":
                for token in node[1]:
                    out.setdefault(token, (node[2], None))
            elif kind == "reraise":
                for token in caught:
                    out.setdefault(token, (node[1], None))
            elif kind == "call":
                site = node[1]
                escapes, callee = self.call_escapes(site)
                for token in escapes:
                    out.setdefault(token, (site.lineno, callee))
            else:  # try
                _, body, handlers, orelse, final = node
                remaining = self._eval_block(func, body, caught)
                for handler_tokens, handler_block in handlers:
                    caught_here = {
                        t: hop
                        for t, hop in remaining.items()
                        if self.caught_by(t, handler_tokens)
                    }
                    for t in caught_here:
                        del remaining[t]
                    for t, hop in self._eval_block(
                        func, handler_block, caught_here
                    ).items():
                        out.setdefault(t, hop)
                for t, hop in remaining.items():
                    out.setdefault(t, hop)
                for sub in (orelse, final):
                    for t, hop in self._eval_block(func, sub, caught).items():
                        out.setdefault(t, hop)
        return out

    def _fixpoint(self) -> int:
        iterations = 0
        changed = True
        while changed and iterations < 50:
            changed = False
            iterations += 1
            for func in self._functions:
                new = self._eval_block(func, self._ir[func], {})
                fresh = set(new) - self._escapes[func]
                if fresh:
                    self._escapes[func] |= set(new)
                    changed = True
                for token, hop in new.items():
                    self._witness.setdefault((func, token), hop)
        return iterations

    # -- public query surface ---------------------------------------------

    def escapes(self, func: FunctionInfo) -> frozenset:
        """Every token that can escape ``func`` (UNKNOWN included)."""
        return frozenset(self._escapes.get(func, ()))

    def named_escapes(self, func: FunctionInfo) -> frozenset:
        return frozenset(
            t for t in self._escapes.get(func, ()) if t != UNKNOWN
        )

    def raised_tokens(self) -> frozenset:
        """Every class with a literal, resolvable raise site anywhere in
        the program — caught or not (the fault-matrix rule's 'is this
        class still real' test must not condemn a class whose raises are
        all handled)."""
        return frozenset(self._raised)

    def constructed_tokens(self) -> frozenset:
        """Every in-model/builtin class with a resolvable *construction*
        site — ``HealthCheckError(...)`` passed as a value is as alive
        as a raise (the reference's err-object callback style)."""
        cached = getattr(self, "_constructed", None)
        if cached is not None:
            return cached
        out: Set[str] = set()
        for site in self.model.all_call_sites():
            token = self.class_token(site.func, site.node.func)
            if token != UNKNOWN:
                out.add(token)
        self._constructed = frozenset(out)
        return self._constructed

    def block_escapes(self, func: FunctionInfo, stmts) -> Set[str]:
        """Escape set of an arbitrary statement block inside ``func``
        under the converged fixpoint state (the overbroad-handler rule
        evaluates try bodies in isolation with it)."""
        sites = {id(s.node): s for s in func.calls}
        ir = self._compile_block(func, stmts, sites)
        return set(self._eval_block(func, ir, {}))

    def escape_chain(self, func: FunctionInfo, token: str) -> List[Tuple[str, str, int]]:
        """Witness chain ``[(symbol, rel_path, line), ...]`` from ``func``
        down to a raise site of ``token`` (or to the last resolvable hop)."""
        chain: List[Tuple[str, str, int]] = []
        seen: Set[FunctionInfo] = set()
        current: Optional[FunctionInfo] = func
        while current is not None and current not in seen:
            seen.add(current)
            hop = self._witness.get((current, token))
            if hop is None:
                chain.append(
                    (current.ref, current.module.rel_path, current.lineno)
                )
                break
            lineno, callee = hop
            chain.append((current.ref, current.module.rel_path, lineno))
            if callee is None or not isinstance(callee, FunctionInfo):
                chain.append(
                    (
                        f"raise {display_name(token)}",
                        current.module.rel_path,
                        lineno,
                    )
                )
                break
            current = callee
        return chain

    def thunk_escapes(self, site: CallSite, expr) -> Tuple[Set[str], Dict[str, FunctionInfo]]:
        """Escape set of a *callable-valued argument* (the ``fn`` handed
        to ``call_with_backoff``): a name/attribute resolving to a model
        function, a ``lambda: f(...)`` body, or ``functools.partial(f,
        ...)``.  Returns ``(tokens, origins)`` where ``origins`` maps
        each token to the resolved callee it escaped FROM — the chain
        anchor.  A lambda combining several calls attributes every token
        to its own contributor, so evidence never names an innocent
        function.  Tokens are UNKNOWN-only when nothing resolves."""
        if isinstance(expr, ast.Lambda):
            out: Set[str] = set()
            origins: Dict[str, FunctionInfo] = {}
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Call):
                    fake = self._pin(_synthetic_site(sub, site.func))
                    if fake is None:
                        out.add(UNKNOWN)
                        continue
                    # synthetic sites are built awaited=True: the retry
                    # boundary awaits the thunk's awaitable, so an async
                    # callee's escapes count here
                    escapes, callee = self.call_escapes(fake)
                    out |= escapes
                    if isinstance(callee, FunctionInfo):
                        for token in escapes:
                            origins.setdefault(token, callee)
            return (out or {UNKNOWN}), origins
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            if d is not None and d[1][-1:] == ("partial",) or (
                d is not None and not d[1] and d[0] == "partial"
            ):
                if expr.args:
                    return self.thunk_escapes(site, expr.args[0])
            return {UNKNOWN}, {}
        callee = self.resolve_callable_ref(site, expr)
        if callee is None:
            return {UNKNOWN}, {}
        tokens = set(self._escapes.get(callee, ()))
        return tokens, {t: callee for t in tokens}

    def resolve_callable_ref(self, site: CallSite, expr) -> Optional[FunctionInfo]:
        """The model function a bare callable REFERENCE names — the
        ``on_data`` in ``check.on("data", on_data)``, the
        ``self._connect_once`` handed to a retry boundary — or None."""
        fake = self._pin(_synthetic_site(expr, site.func, is_ref=True))
        if fake is None:
            return None
        res = self.graph.resolve(fake)
        callee = res[1] if (res is not None and res[0] == "func") else None
        if callee is None:
            callee = self._duck_resolve(fake)
        return callee

    def _pin(self, site: Optional[CallSite]) -> Optional[CallSite]:
        if site is not None:
            self._pinned.append(site)
        return site

    def stats(self) -> dict:
        return {
            "escape_functions": len(self._functions),
            "escape_iterations": self.iterations,
            "escape_build_s": self.build_seconds,
        }


class _FakeCall:
    __slots__ = ("lineno",)

    def __init__(self, lineno):
        self.lineno = lineno


def _synthetic_site(expr, func: FunctionInfo, is_ref: bool = False) -> Optional[CallSite]:
    """A CallSite for an expression that is not one of the function's
    collected sites: a call inside a lambda body, or a bare callable
    reference (``self._connect_once``) handed to a retry boundary."""
    target = expr if is_ref else expr.func
    d = _dotted(target)
    if d is None:
        return None
    if not d[1]:
        shape = ("name", d[0])
    else:
        shape = ("dotted", d[0], d[1])
    fake = _FakeCall(getattr(expr, "lineno", func.lineno))
    return CallSite(fake, shape, awaited=True, bare_stmt=False,
                    under_lock=False, func=func)


def flow_for(model: ProgramModel):
    """One ExceptionFlow per program model, shared by every errors rule
    (and surfaced into ``--stats`` by the engine)."""
    flow = getattr(model, "_excflow", None)
    if flow is None:
        from checklib.rules_flow import graph_for

        flow = ExceptionFlow(model, graph_for(model))
        model._excflow = flow
    return flow
