"""Package-hygiene rules (shipped daemon code only).

Tests and tooling poke private attributes and assert by design, so the
private-attr and assert rules arm only under ``registrar_tpu/`` (see
``checklib.context.PACKAGE_PREFIX``); mutable defaults are a hazard
everywhere.
"""

from __future__ import annotations

import ast

from checklib.context import FileContext
from checklib.registry import finding, rule
from checklib.scopes import iter_defaults


@rule(
    "unguarded-private-attr",
    "private attribute access on a foreign object without a getattr guard",
    scope="package",
)
def unguarded_private_attr(ctx: FileContext):
    # ``proc._transport`` / ``reader._buffer`` style pokes at another
    # library's internals break silently when that library's internals
    # move; the sanctioned form is ``getattr(obj, "_attr", None)`` plus a
    # None check (which this rule naturally does not see — getattr is a
    # Call, not an Attribute).  Private attributes that any class in the
    # *same module* defines are cooperation, not pokes, and are exempt.
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Attribute):
            continue
        attr = node.attr
        if not attr.startswith("_") or attr.startswith("__"):
            continue
        if isinstance(node.value, ast.Name) and node.value.id in (
            "self",
            "cls",
        ):
            continue
        if attr in ctx.local_private_attrs:
            continue
        yield finding(
            ctx,
            "unguarded-private-attr",
            node,
            f"unguarded private attribute access '.{attr}' on a foreign "
            "object (use getattr(..., None) and handle absence)",
        )


#: Built-in factory calls whose results are as mutable as a literal.
_MUTABLE_FACTORIES = frozenset({"list", "dict", "set", "bytearray"})


@rule(
    "mutable-default",
    "mutable default argument shared across calls",
)
def mutable_default(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        name = getattr(node, "name", "<lambda>")
        for default in iter_defaults(node.args):
            if isinstance(
                default,
                (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                 ast.SetComp),
            ) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in _MUTABLE_FACTORIES
            ):
                yield finding(
                    ctx,
                    "mutable-default",
                    default,
                    f"mutable default argument in '{name}()' is shared "
                    "across calls (default to None and create inside)",
                )


@rule(
    "assert-in-package",
    "assert statement in shipped package code (vanishes under -O)",
    scope="package",
)
def assert_in_package(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assert):
            yield finding(
                ctx,
                "assert-in-package",
                node,
                "assert in package code is stripped under -O; raise an "
                "exception for runtime invariants",
            )
