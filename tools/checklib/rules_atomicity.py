"""Await-atomicity race detection (generation 5).

An ``await`` is a scheduling point: every coroutine sharing the loop
may run between the read and the write it separates.  The check-then-
act shape —

    snap = self._registered          # read
    await self._zk.create(...)       # suspension: world may change
    self._registered = snap + 1      # act on the STALE read

— is exactly what the PR-3 single-flight + registration-epoch machinery
exists to prevent, and the repaired sites all share one of three
sanctioning shapes: re-read the field after the await, re-check an
epoch/generation marker on the same object, or hold the same lock
across both sides.  ``stale-read-across-await`` pins the convention:

  * the **tracked vocabulary** is discovered, not hard-coded — any
    attribute some package function assigns inside an ``async with
    <lock>`` block (the gen-4 lock vocabulary via ``_is_lock_expr``)
    is lock-relevant, plus anything epoch-ish by name
    (``epoch``/``generation``);
  * a finding needs the full shape in one async function: a local
    snapshot of ``recv.attr``, an ``await`` (or ``async with`` /
    ``async for`` suspension) after it, the snapshot local still used
    after that suspension, and a write back to the same ``recv.attr``
    after it;
  * **sanctioners** stay silent: a re-read of the field between the
    suspension and the write, ANY attribute of the receiver inspected
    in a test/comparison in that window (the epoch-guard and
    ``reconciler``-recheck shapes), or snapshot and write sitting in
    the same lexical lock block (the lock is held across the await).

Purely lexical and per-function, like the rest of the program rules:
no alias tracking, receivers are plain names, one finding per
(function, receiver, field).  Conservative by construction — a shape
the scan cannot prove racy stays silent.
"""

from __future__ import annotations

import ast
import re
import time
from typing import Dict, Iterator, List, Optional, Set, Tuple

from checklib.callgraph import chain_evidence, chain_names
from checklib.context import PACKAGE_PREFIX
from checklib.model import Finding
from checklib.program import FunctionInfo, ProgramModel, _is_lock_expr
from checklib.registry import rule

#: Epoch-ish field names are lock-relevant even when never assigned
#: under a lock — they ARE the optimistic-concurrency protocol.
_EPOCHISH = re.compile(r"epoch|generation", re.IGNORECASE)

_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _lock_item(stmt) -> bool:
    return any(_is_lock_expr(item.context_expr) for item in stmt.items)


class AtomicityScan:
    """Two passes over the program model: discover the lock-protected
    attribute vocabulary, then scan every async package function for
    the read→await→stale-write shape."""

    def __init__(self, model: ProgramModel):
        t0 = time.monotonic()
        self.model = model
        self._locked_attrs: Set[str] = set()
        package = [
            f
            for f in model.functions()
            if f.node is not None
            and f.module.rel_path.startswith(PACKAGE_PREFIX)
        ]
        for func in package:
            self._collect_locked_writes(func.node, under_lock=False)
        self.findings: List[Finding] = []
        for func in sorted(
            package, key=lambda f: (f.module.rel_path, f.lineno, f.qualname)
        ):
            if isinstance(func.node, ast.AsyncFunctionDef):
                self._scan(func)
        self.findings.sort(key=lambda f: (f.path, f.line, f.message))
        self.build_seconds = round(time.monotonic() - t0, 4)

    def _tracked(self, attr: str) -> bool:
        return attr in self._locked_attrs or _EPOCHISH.search(attr) is not None

    # -- pass 1: what does the tree protect with locks? -------------------

    def _collect_locked_writes(self, node, under_lock: bool) -> None:
        for stmt in ast.iter_child_nodes(node):
            if isinstance(stmt, _NESTED):
                continue
            inside = under_lock
            if isinstance(stmt, (ast.With, ast.AsyncWith)) and _lock_item(
                stmt
            ):
                inside = True
            if under_lock and isinstance(stmt, (ast.Assign, ast.AugAssign)):
                targets = (
                    stmt.targets
                    if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for target in targets:
                    if isinstance(target, ast.Attribute) and isinstance(
                        target.value, ast.Name
                    ):
                        self._locked_attrs.add(target.attr)
            self._collect_locked_writes(stmt, inside)

    # -- pass 2: the shape ------------------------------------------------

    def _scan(self, func: FunctionInfo) -> None:
        rel = func.module.rel_path
        # (local, recv, attr, line, lock_id)
        snapshots: List[Tuple[str, str, str, int, Optional[int]]] = []
        # (recv, attr, line, lock_id)
        writes: List[Tuple[str, str, int, Optional[int]]] = []
        awaits: List[int] = []
        rereads: Dict[Tuple[str, str], List[int]] = {}
        guards: Dict[str, List[int]] = {}
        uses: Dict[str, List[int]] = {}

        def walk_expr(node, in_test: bool) -> None:
            if node is None or isinstance(node, _NESTED):
                return
            if isinstance(node, ast.Await):
                awaits.append(node.lineno)
                walk_expr(node.value, in_test)
                return
            if isinstance(node, ast.Compare):
                in_test = True
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
            ):
                if in_test:
                    guards.setdefault(node.value.id, []).append(node.lineno)
                if self._tracked(node.attr):
                    rereads.setdefault(
                        (node.value.id, node.attr), []
                    ).append(node.lineno)
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                uses.setdefault(node.id, []).append(node.lineno)
            for child in ast.iter_child_nodes(node):
                walk_expr(child, in_test)

        def record_write_target(target, lineno, lock_id) -> None:
            if isinstance(target, ast.Attribute) and isinstance(
                target.value, ast.Name
            ):
                if self._tracked(target.attr):
                    writes.append(
                        (target.value.id, target.attr, lineno, lock_id)
                    )
            elif isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    record_write_target(elt, lineno, lock_id)

        def walk_stmt(stmt, lock_id: Optional[int]) -> None:
            if isinstance(stmt, _NESTED):
                return
            if isinstance(stmt, ast.Assign):
                walk_expr(stmt.value, False)
                if (
                    len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Attribute)
                    and isinstance(stmt.value.value, ast.Name)
                    and self._tracked(stmt.value.attr)
                ):
                    snapshots.append(
                        (
                            stmt.targets[0].id,
                            stmt.value.value.id,
                            stmt.value.attr,
                            stmt.lineno,
                            lock_id,
                        )
                    )
                for target in stmt.targets:
                    record_write_target(target, stmt.lineno, lock_id)
                return
            if isinstance(stmt, ast.AugAssign):
                walk_expr(stmt.value, False)
                record_write_target(stmt.target, stmt.lineno, lock_id)
                return
            if isinstance(stmt, (ast.If, ast.While)):
                walk_expr(stmt.test, True)
                for s in stmt.body:
                    walk_stmt(s, lock_id)
                for s in stmt.orelse:
                    walk_stmt(s, lock_id)
                return
            if isinstance(stmt, ast.Assert):
                walk_expr(stmt.test, True)
                return
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                if isinstance(stmt, ast.AsyncWith):
                    awaits.append(stmt.lineno)
                inner = id(stmt) if _lock_item(stmt) else lock_id
                for item in stmt.items:
                    walk_expr(item.context_expr, False)
                for s in stmt.body:
                    walk_stmt(s, inner)
                return
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                if isinstance(stmt, ast.AsyncFor):
                    awaits.append(stmt.lineno)
                walk_expr(stmt.iter, False)
                for s in stmt.body:
                    walk_stmt(s, lock_id)
                for s in stmt.orelse:
                    walk_stmt(s, lock_id)
                return
            if isinstance(stmt, ast.Try):
                for s in stmt.body:
                    walk_stmt(s, lock_id)
                for handler in stmt.handlers:
                    for s in handler.body:
                        walk_stmt(s, lock_id)
                for s in stmt.orelse:
                    walk_stmt(s, lock_id)
                for s in stmt.finalbody:
                    walk_stmt(s, lock_id)
                return
            if isinstance(stmt, _NESTED):
                return
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    walk_stmt(child, lock_id)
                elif isinstance(child, ast.expr):
                    walk_expr(child, False)

        for stmt in func.node.body:
            walk_stmt(stmt, None)

        fired: Set[Tuple[str, str]] = set()
        for local, recv, attr, s_line, s_lock in snapshots:
            if (recv, attr) in fired:
                continue
            for w_recv, w_attr, w_line, w_lock in writes:
                if (w_recv, w_attr) != (recv, attr) or w_line <= s_line:
                    continue
                between = [a for a in awaits if s_line < a < w_line]
                if not between:
                    continue
                first_await = min(between)
                # the stale value must actually matter after suspension
                if not any(
                    u > first_await for u in uses.get(local, ())
                ):
                    continue
                # sanctioner 1: the field is re-read after suspending
                if any(
                    first_await < r < w_line
                    for r in rereads.get((recv, attr), ())
                ):
                    continue
                # sanctioner 2: epoch-guard shape — any attribute of the
                # receiver re-checked in a test between await and write
                if any(
                    first_await < g <= w_line for g in guards.get(recv, ())
                ):
                    continue
                # sanctioner 3: lock held across both sides
                if s_lock is not None and s_lock == w_lock:
                    continue
                hops = [
                    (f"read {recv}.{attr}", rel, s_line),
                    ("await", rel, first_await),
                    (f"write {recv}.{attr}", rel, w_line),
                ]
                self.findings.append(
                    Finding(
                        "stale-read-across-await",
                        rel,
                        s_line,
                        f"lock-relevant field {recv}.{attr} is read before "
                        f"an await and written after it without re-read or "
                        f"epoch re-check (chain: {chain_names(hops)})",
                        chain=chain_evidence(hops),
                    )
                )
                fired.add((recv, attr))
                break

    def stats(self) -> dict:
        return {
            "atomicity_tracked": len(self._locked_attrs),
            "atomicity_build_s": self.build_seconds,
        }


def atomicity_for(model: ProgramModel) -> AtomicityScan:
    """One AtomicityScan per program model (pre-built by the engine so
    ``--stats`` can report the phase even on a clean run)."""
    scan = getattr(model, "_atomicity", None)
    if scan is None:
        scan = AtomicityScan(model)
        model._atomicity = scan
    return scan


@rule(
    "stale-read-across-await",
    "a lock-relevant field read before an await is written after it "
    "without re-read or epoch re-check",
    scope="program",
)
def stale_read_across_await(model: ProgramModel) -> Iterator[Finding]:
    for f in atomicity_for(model).findings:
        yield f
