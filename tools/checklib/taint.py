"""Interprocedural taint-flow analysis (generation 5).

Every length, count, and offset a peer can put on the wire eventually
sizes something: an allocation (``bytes(n)``), a loop (``range(n)``), a
stream read (``readexactly(n)``), a slice.  The decode-bound invariants
that keep those safe exist in the tree as hand-written guards
(``framing.py``'s MAX_FRAME rejection, ``jute.py``'s
count-vs-remaining check) — this module turns the convention into a
machine-checked contract over the PR-6 program model:

  * **sources** are peer-controlled reads, declared per wire module in
    :data:`BOUNDARY_SOURCES` (mirrored by the trust-boundary table in
    docs/DESIGN.md, which ``taint-boundary-drift`` cross-checks both
    directions).  A source yields either a peer integer (kind ``num``)
    or a peer payload (kind ``buf``; subscripting a ``buf`` with a
    constant index yields a ``num``);
  * **taint propagates** through arithmetic, tuple destructuring, and
    along resolved call edges — positional/keyword arguments into
    callee parameters, callee returns back to the call expression —
    with the generation-3 duck resolution reused for opaque receivers
    (``r.read_int()`` on a parameter);
  * **sinks** are the size-sensitive operations: ``bytes(n)`` /
    ``bytearray(n)`` allocations, sequence repetition (``b"x" * n``),
    ``range(n)`` loops, unresolved ``readexactly(n)`` / ``_take(n)`` /
    ``_skip(n)`` reads, slice bounds, and self-recursion reached with a
    tainted argument.  A size call that resolves to an in-model
    function is NOT a sink — the taint flows into the callee instead,
    where an internal guard is visible to the analysis (this is why
    ``jute.Reader._take``'s ``remaining()`` check silences every
    ``_take`` call site);
  * **sanitizers** kill ``num`` taint: an ordered comparison
    (``< <= > >=``) whose other side is boundish — a constant, an
    ALL-CAPS or cap-ish name (max/cap/limit/bound/size/budget), a
    ``.size`` attribute, ``len()`` / ``remaining()`` / ``min()`` /
    ``max()`` arithmetic — cleanses the compared name for the rest of
    the scope.  ``min(n, CAP)`` and ``int()``-style transforms are
    modeled directly.  Cleansing is deliberately direction-insensitive
    (``if n < 0`` alone cleanses) — documented in docs/CHECKS.md as the
    price of a lexical, path-insensitive pass.

Every finding carries the source→sink witness chain as structured
evidence (JSON ``chain``, SARIF codeFlows), like
transitive-blocking-call.  Conservatism follows the house contract:
taint dies at unresolved calls, constructors, attribute stores, and
anything else the model cannot follow — silence, never a guess.
Findings are only reported for package files (``registrar_tpu/``);
tests exercising the decoders on crafted bytes are not decode surface.
"""

from __future__ import annotations

import ast
import os
import re
import time
from typing import Dict, Iterator, List, Optional, Set, Tuple

from checklib.callgraph import chain_evidence, chain_names
from checklib.context import PACKAGE_PREFIX
from checklib.model import Finding
from checklib.program import FunctionInfo, ProgramModel, _dotted
from checklib.registry import rule
from checklib.rules_contracts import read_doc_lines
from checklib.rules_flow import graph_for

#: The trust boundary: per wire module, the callee names whose results
#: are peer-controlled, and the taint kind they yield.  ``num`` is a
#: peer integer (lengths, counts, offsets), ``buf`` a peer payload.
#: docs/DESIGN.md's "trust boundary" appendix mirrors this table and
#: ``taint-boundary-drift`` keeps the two in sync — against the ACTUAL
#: call sites, both directions, so neither the doc nor this vocabulary
#: can go stale.
BOUNDARY_SOURCES: Dict[str, Dict[str, str]] = {
    "registrar_tpu/zk/jute.py": {
        "unpack_from": "num",
        "read_int": "num",
    },
    "registrar_tpu/zk/framing.py": {
        "from_bytes": "num",
        "_peek4": "num",
        "read": "buf",
    },
    "registrar_tpu/zk/client.py": {
        "from_bytes": "num",
        "readexactly": "buf",
    },
    "registrar_tpu/zk/protocol.py": {
        "read_int": "num",
        "read_long": "num",
        "read_struct": "num",
        "long_at": "num",
        "read_buffer": "buf",
        "unpack_from": "num",
    },
    "registrar_tpu/shard.py": {
        "unpack": "num",
        "unpack_from": "num",
        "readexactly": "buf",
    },
    "registrar_tpu/dnsfront.py": {
        "unpack_from": "num",
    },
    "registrar_tpu/health.py": {
        "read": "buf",
    },
}

#: Sink vocabulary (the names the docs table documents on the sink
#: side; set-compared both directions by taint-boundary-drift).
SINK_VOCAB = frozenset(
    {
        "bytes",
        "bytearray",
        "range",
        "readexactly",
        "_take",
        "_skip",
        "slice",
        "sequence-repeat",
        "recursion",
    }
)

#: Stream-read callables whose first argument is a read size.  Only
#: UNRESOLVED calls (external stream methods) are sinks; a resolved
#: in-model callee receives the taint as a parameter instead.
_SIZE_READS = frozenset({"readexactly", "_take", "_skip"})

#: Callables that return their first argument unchanged (taint-wise).
_PASSTHROUGH = frozenset({"wait_for", "shield", "memoryview", "abs", "int"})

#: Cap-ish identifier fragments that make a comparison side "boundish".
_CAPISH = re.compile(r"max|cap|limit|bound|size|budget", re.IGNORECASE)

#: Boundish call targets: buffer arithmetic and explicit clamping.
_BOUND_CALLS = frozenset({"len", "remaining", "min", "max", "calcsize"})

#: A chain hop, shaped like callgraph.py's: (symbol, rel_path, line).
Hop = Tuple[str, str, int]

#: (kind, chain): kind is "num" | "buf".
Taint = Tuple[str, List[Hop]]

_ORDERED_OPS = (ast.Lt, ast.LtE, ast.Gt, ast.GtE)


class TaintFlow:
    """The analysis: build once per run (:func:`taint_for`), query per
    rule.  A worklist-free fixpoint: full passes over every function
    that can carry taint, until the interprocedural state (parameter
    and return taint, both first-wins) stops growing, then one
    recording pass that collects findings and stats."""

    def __init__(self, model: ProgramModel):
        self.model = model
        self.graph = graph_for(model)
        from checklib.exceptions import flow_for

        self._flow = flow_for(model)  # duck resolution (generation 3)
        t0 = time.monotonic()
        self._param_taint: Dict[FunctionInfo, Dict[str, Taint]] = {}
        self._return_taint: Dict[FunctionInfo, Taint] = {}
        #: Per-element taint for ``return a, b, c`` literals, merged
        #: element-wise (first non-None wins per slot) across returns
        #: and passes.  Without this, ``op, ctx, body =
        #: split_traced(...)`` smears the trace-context ints' num taint
        #: onto the payload view and every downstream byte-copy fires.
        self._return_tuple: Dict[FunctionInfo, List[Optional[Taint]]] = {}
        self._functions = sorted(
            (f for f in model.functions() if f.node is not None),
            key=lambda f: (f.module.rel_path, f.lineno, f.qualname),
        )
        #: func -> (has own source sites, resolved+duck callee set) —
        #: the pruning facts: a function with no source, no tainted
        #: parameter, and no callee carrying return taint cannot change
        #: the fixpoint state or produce a finding.
        self._facts: Dict[FunctionInfo, Tuple[bool, List[FunctionInfo]]] = {}
        for func in self._functions:
            self._facts[func] = self._function_facts(func)
        self.findings: List[Finding] = []
        #: (module rel_path, source pattern) -> first lineno seen — the
        #: actual-call-site inventory taint-boundary-drift checks the
        #: docs table against.
        self.source_sites: Dict[Tuple[str, str], int] = {}
        self.sources = 0
        self.sinks = 0
        self.sanitized = 0
        self._recording = False
        self._seen: Set[tuple] = set()
        self.iterations = 0
        for _ in range(30):
            self.iterations += 1
            self._changed = False
            for func in self._functions:
                if self._relevant(func):
                    self._analyze(func)
            if not self._changed:
                break
        self._recording = True
        for func in self._functions:
            if self._relevant(func):
                self._analyze(func)
        self.findings.sort(
            key=lambda f: (f.path, f.line, f.rule, f.message)
        )
        self.build_seconds = round(time.monotonic() - t0, 4)

    # -- pruning ----------------------------------------------------------

    def _function_facts(self, func: FunctionInfo):
        vocab = BOUNDARY_SOURCES.get(func.module.rel_path)
        has_source = False
        callees: List[FunctionInfo] = []
        for site in func.calls:
            if site.shape[0] == "name":
                last: Optional[str] = site.shape[1]
            elif site.shape[0] == "dotted":
                last = site.shape[2][-1]
            else:
                last = None
            if vocab and last is not None and last in vocab:
                has_source = True
            res = self.graph.resolve(site)
            if res is not None and res[0] == "func":
                callees.append(res[1])
            elif res is None and site.shape[0] == "dotted":
                duck = self._flow._duck_resolve(site)
                if duck is not None:
                    callees.append(duck)
        return has_source, callees

    def _relevant(self, func: FunctionInfo) -> bool:
        has_source, callees = self._facts[func]
        if has_source or self._param_taint.get(func):
            return True
        return any(c in self._return_taint for c in callees)

    # -- per-function walk ------------------------------------------------

    def _analyze(self, func: FunctionInfo) -> None:
        self._func = func
        self._rel = func.module.rel_path
        self._sites = {id(s.node): s for s in func.calls}
        env: Dict[str, Taint] = dict(self._param_taint.get(func) or {})
        self._walk_block(func.node.body, env)

    def _walk_block(self, stmts, env: Dict[str, Taint]) -> None:
        for stmt in stmts:
            self._walk_stmt(stmt, env)

    def _walk_stmt(self, stmt, env: Dict[str, Taint]) -> None:
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return  # separate scopes: covered by their own analysis
        if isinstance(stmt, ast.Assign):
            val = self._expr(stmt.value, env)
            elements = self._tuple_return_of(stmt.value)
            for target in stmt.targets:
                if (
                    elements is not None
                    and isinstance(target, (ast.Tuple, ast.List))
                    and len(target.elts) == len(elements)
                    and not any(
                        isinstance(e, ast.Starred) for e in target.elts
                    )
                ):
                    hop = (self._func.ref, self._rel, stmt.value.lineno)
                    for elt, taint in zip(target.elts, elements):
                        self._assign(
                            elt,
                            (taint[0], taint[1] + [hop]) if taint else None,
                            env,
                        )
                else:
                    self._assign(target, val, env)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign(stmt.target, self._expr(stmt.value, env), env)
        elif isinstance(stmt, ast.AugAssign):
            val = self._expr(stmt.value, env)
            if isinstance(stmt.target, ast.Name):
                merged = env.get(stmt.target.id) or val
                if merged is not None:
                    env[stmt.target.id] = merged
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                if isinstance(stmt.value, ast.Tuple):
                    elts = [
                        self._expr(e, env) for e in stmt.value.elts
                    ]
                    self._merge_tuple_return(elts)
                    val = next(
                        (t for t in elts if t is not None and t[0] == "buf"),
                        None,
                    ) or next((t for t in elts if t is not None), None)
                else:
                    val = self._expr(stmt.value, env)
                if val is not None and self._func not in self._return_taint:
                    self._return_taint[self._func] = val
                    if not self._recording:
                        self._changed = True
        elif isinstance(stmt, ast.If):
            self._expr(stmt.test, env)
            self._cleanse(stmt.test, env)
            then_env = dict(env)
            self._walk_block(stmt.body, then_env)
            else_env = dict(env)
            self._walk_block(stmt.orelse, else_env)
            # branch merge: taint survives only when BOTH arms leave it
            # (the guard-and-raise shape kills it in the raising arm's
            # sibling via _cleanse already; this handles rebindings)
            env.clear()
            for name, taint in then_env.items():
                if name in else_env:
                    env[name] = taint
        elif isinstance(stmt, (ast.While,)):
            self._expr(stmt.test, env)
            self._cleanse(stmt.test, env)
            self._walk_block(stmt.body, env)
            self._walk_block(stmt.orelse, env)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self._expr(stmt.iter, env)
            if it is not None:
                # iterating peer data yields peer values (bytes -> ints)
                self._assign(stmt.target, ("num", it[1]), env)
            self._walk_block(stmt.body, env)
            self._walk_block(stmt.orelse, env)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                val = self._expr(item.context_expr, env)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, val, env)
            self._walk_block(stmt.body, env)
        elif isinstance(stmt, ast.Try):
            self._walk_block(stmt.body, env)
            for handler in stmt.handlers:
                handler_env = dict(env)
                self._walk_block(handler.body, handler_env)
            self._walk_block(stmt.orelse, env)
            self._walk_block(stmt.finalbody, env)
        elif isinstance(stmt, ast.Expr):
            self._expr(stmt.value, env)
        elif isinstance(stmt, ast.Assert):
            self._expr(stmt.test, env)
            self._cleanse(stmt.test, env)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._expr(stmt.exc, env)
        elif isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    env.pop(target.id, None)

    def _assign(self, target, val: Optional[Taint], env) -> None:
        if isinstance(target, ast.Name):
            if val is None:
                env.pop(target.id, None)
            else:
                env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                inner = elt.value if isinstance(elt, ast.Starred) else elt
                self._assign(inner, val, env)
        # attribute / subscript stores: taint dies (not modeled)

    def _merge_tuple_return(self, elts: List[Optional[Taint]]) -> None:
        if not any(t is not None for t in elts):
            return
        current = self._return_tuple.get(self._func)
        if current is None:
            self._return_tuple[self._func] = list(elts)
            if not self._recording:
                self._changed = True
            return
        if len(current) != len(elts):
            return  # ragged returns: the scalar collapse still applies
        for i, taint in enumerate(elts):
            if current[i] is None and taint is not None:
                current[i] = taint
                if not self._recording:
                    self._changed = True

    def _tuple_return_of(self, value) -> Optional[List[Optional[Taint]]]:
        """Per-element taint when ``value`` is a (possibly awaited)
        call to an in-model function returning a tuple literal."""
        if isinstance(value, ast.Await):
            value = value.value
        if not isinstance(value, ast.Call):
            return None
        callee = self._callee_of(value)
        if callee is None:
            return None
        return self._return_tuple.get(callee)

    def _callee_of(self, node: ast.Call) -> Optional[FunctionInfo]:
        site = self._sites.get(id(node))
        if site is None:
            return None
        res = self.graph.resolve(site)
        if res is not None and res[0] == "func":
            return res[1]
        if res is None and site.shape[0] == "dotted":
            return self._flow._duck_resolve(site)
        return None

    # -- expressions ------------------------------------------------------

    def _expr(self, node, env: Dict[str, Taint]) -> Optional[Taint]:
        if node is None or isinstance(node, ast.Constant):
            return None
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Await):
            return self._expr(node.value, env)
        if isinstance(node, ast.Call):
            return self._call(node, env)
        if isinstance(node, ast.BinOp):
            return self._binop(node, env)
        if isinstance(node, ast.UnaryOp):
            return self._expr(node.operand, env)
        if isinstance(node, ast.BoolOp):
            taints = [self._expr(v, env) for v in node.values]
            return next((t for t in taints if t is not None), None)
        if isinstance(node, ast.Compare):
            self._expr(node.left, env)
            for comp in node.comparators:
                self._expr(comp, env)
            return None  # a bool is never size-dangerous
        if isinstance(node, ast.IfExp):
            self._expr(node.test, env)
            a = self._expr(node.body, env)
            b = self._expr(node.orelse, env)
            return a or b
        if isinstance(node, ast.Subscript):
            return self._subscript(node, env)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            taints = [self._expr(e, env) for e in node.elts]
            # A literal mixing kinds (``return op, ctx, body``) smears
            # one taint over every destructured target; prefer ``buf``
            # — copying a peer payload is bounded by its (already
            # capped) size, while treating the payload as a peer
            # INTEGER would turn every byte-copy into an allocation
            # finding.  Indexing the smeared buf still yields tainted
            # nums, so real length fields keep flowing.
            taints = [t for t in taints if t is not None]
            for t in taints:
                if t[0] == "buf":
                    return t
            return taints[0] if taints else None
        if isinstance(node, ast.Starred):
            return self._expr(node.value, env)
        if isinstance(node, ast.NamedExpr):
            val = self._expr(node.value, env)
            self._assign(node.target, val, env)
            return val
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            comp_env = dict(env)
            for gen in node.generators:
                it = self._expr(gen.iter, comp_env)
                if it is not None:
                    self._assign(gen.target, ("num", it[1]), comp_env)
                for cond in gen.ifs:
                    self._expr(cond, comp_env)
                    self._cleanse(cond, comp_env)
            if isinstance(node, ast.DictComp):
                self._expr(node.key, comp_env)
                self._expr(node.value, comp_env)
            else:
                self._expr(node.elt, comp_env)
            return None
        if isinstance(node, (ast.Attribute, ast.Lambda)):
            if isinstance(node, ast.Attribute):
                self._expr(node.value, env)
            return None
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, env)
        return None

    def _binop(self, node: ast.BinOp, env) -> Optional[Taint]:
        left = self._expr(node.left, env)
        right = self._expr(node.right, env)
        if isinstance(node.op, ast.Mult):
            for taint, other, other_taint in (
                (left, node.right, right),
                (right, node.left, left),
            ):
                if taint is None or taint[0] != "num":
                    continue
                sequence_side = (
                    (
                        isinstance(other, ast.Constant)
                        and isinstance(other.value, (str, bytes))
                    )
                    or isinstance(other, (ast.List, ast.Tuple))
                    or (other_taint is not None and other_taint[0] == "buf")
                )
                self._note_sink_site(node)
                if sequence_side:
                    self._sink(
                        "unbounded-peer-allocation",
                        node.lineno,
                        taint,
                        "tainted * sequence",
                        "peer-controlled integer sizes a sequence-repeat "
                        "allocation with no dominating bound check",
                    )
                    return ("buf", taint[1])
                break
        for t in (left, right):
            if t is not None and t[0] == "num":
                return t
        return left or right

    def _subscript(self, node: ast.Subscript, env) -> Optional[Taint]:
        base = self._expr(node.value, env)
        sl = node.slice
        if isinstance(sl, ast.Slice):
            bounds = [b for b in (sl.lower, sl.upper, sl.step) if b is not None]
            tainted = None
            for b in bounds:
                t = self._expr(b, env)
                if tainted is None and t is not None and t[0] == "num":
                    tainted = t
            if any(not isinstance(b, ast.Constant) for b in bounds):
                self._note_sink_site(node)
            if tainted is not None:
                self._sink(
                    "unchecked-peer-read-size",
                    node.lineno,
                    tainted,
                    "slice[tainted]",
                    "peer-controlled offset bounds a slice with no "
                    "dominating bound check",
                )
            return base
        index = self._expr(sl, env)
        if base is not None and base[0] == "buf":
            return ("num", base[1])  # buf[i]: a peer byte/element value
        if index is not None:
            return None  # tainted key into an untainted container
        return None

    def _call(self, node: ast.Call, env) -> Optional[Taint]:
        d = _dotted(node.func)
        if d is None:
            self._expr(node.func, env)
            last: Optional[str] = None
            attrs: Tuple[str, ...] = ()
            base: Optional[str] = None
        else:
            base, attrs = d
            last = attrs[-1] if attrs else base
        starred = any(isinstance(a, ast.Starred) for a in node.args)
        arg_taints = [self._expr(a, env) for a in node.args]
        kw_taints = {
            kw.arg: self._expr(kw.value, env)
            for kw in node.keywords
            if kw.arg is not None
        }
        for kw in node.keywords:
            if kw.arg is None:
                self._expr(kw.value, env)

        callee = self._callee_of(node)

        # -- sinks (checked before the source so readexactly(tainted)
        #    both fires and yields a tainted payload) -----------------
        if last in ("bytes", "bytearray") and not attrs and node.args:
            self._note_sink_site(node)
            first = arg_taints[0]
            if first is not None and first[0] == "num":
                self._sink(
                    "unbounded-peer-allocation",
                    node.lineno,
                    first,
                    f"{last}(tainted)",
                    "peer-controlled integer sizes an allocation with no "
                    "dominating bound check",
                )
                return ("buf", first[1])
            if first is not None and first[0] == "buf":
                return first
            return None
        if last == "range" and not attrs and node.args:
            self._note_sink_site(node)
            tainted = next(
                (t for t in arg_taints if t is not None and t[0] == "num"),
                None,
            )
            if tainted is not None:
                self._sink(
                    "unvalidated-count-loop",
                    node.lineno,
                    tainted,
                    "range(tainted)",
                    "peer-controlled count drives a loop with no "
                    "dominating bound check",
                )
            return None
        if last in _SIZE_READS and attrs and callee is None and node.args:
            self._note_sink_site(node)
            first = arg_taints[0]
            if first is not None and first[0] == "num":
                self._sink(
                    "unchecked-peer-read-size",
                    node.lineno,
                    first,
                    f"{last}(tainted)",
                    "peer-controlled length sizes a stream read with no "
                    "dominating bound check",
                )
        if (
            callee is not None
            and callee is self._func
            and any(t is not None for t in arg_taints)
        ):
            tainted = next(t for t in arg_taints if t is not None)
            self._note_sink_site(node)
            self._sink(
                "unvalidated-count-loop",
                node.lineno,
                tainted,
                "recursion(tainted)",
                "peer-controlled value reaches self-recursion with no "
                "dominating bound check",
            )

        # -- sources ------------------------------------------------------
        vocab = BOUNDARY_SOURCES.get(self._rel)
        if vocab is not None and last is not None and last in vocab:
            if self._recording:
                self.sources += 1
                key = (self._rel, last)
                if key not in self.source_sites:
                    self.source_sites[key] = node.lineno
            return (
                vocab[last],
                [(f"{last} (peer read)", self._rel, node.lineno)],
            )

        # -- builtin transforms -------------------------------------------
        if last in _PASSTHROUGH and node.args:
            return arg_taints[0]
        if last in ("min", "max") and not attrs and len(node.args) > 1:
            if all(t is not None for t in arg_taints):
                return arg_taints[0]
            return None  # clamped against an untainted bound
        if last in ("len", "bool", "sum", "ord") and not attrs:
            return None

        # -- interprocedural propagation ----------------------------------
        if callee is not None and callee.node is not None:
            self._flow_into(
                callee, node, arg_taints, kw_taints, starred,
                dotted=bool(attrs),
            )
            ret = self._return_taint.get(callee)
            if ret is not None:
                return (
                    ret[0],
                    ret[1] + [(self._func.ref, self._rel, node.lineno)],
                )
        return None

    def _flow_into(
        self, callee, node, arg_taints, kw_taints, starred, dotted
    ) -> None:
        args = callee.node.args
        ordered = [a.arg for a in list(args.posonlyargs) + list(args.args)]
        if dotted and ordered and ordered[0] in ("self", "cls"):
            ordered = ordered[1:]
        target = self._param_taint.setdefault(callee, {})
        callee_rel = callee.module.rel_path
        hop = (callee.ref, callee_rel, callee.lineno)

        def contribute(param: str, taint: Taint) -> None:
            if param in target:
                return
            target[param] = (taint[0], taint[1] + [hop])
            if not self._recording:
                self._changed = True

        if not starred:
            for i, taint in enumerate(arg_taints):
                if taint is None or i >= len(ordered):
                    continue
                contribute(ordered[i], taint)
        params = callee.params
        for name, taint in kw_taints.items():
            if taint is not None and name in params:
                contribute(name, taint)

    # -- sanitizers -------------------------------------------------------

    def _cleanse(self, test, env: Dict[str, Taint]) -> None:
        if isinstance(test, ast.BoolOp):
            for value in test.values:
                self._cleanse(value, env)
            return
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            self._cleanse(test.operand, env)
            return
        if not isinstance(test, ast.Compare):
            return
        if not all(isinstance(op, _ORDERED_OPS) for op in test.ops):
            return  # equality tells you nothing about magnitude
        sides = [test.left] + list(test.comparators)
        for i, side in enumerate(sides):
            names = [
                n.id
                for n in ast.walk(side)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and env.get(n.id, (None,))[0] == "num"
            ]
            if not names:
                continue
            others = sides[:i] + sides[i + 1:]
            if others and all(self._boundish(o, env) for o in others):
                for name in names:
                    if name in env:
                        del env[name]
                        if self._recording:
                            self.sanitized += 1

    def _boundish(self, expr, env) -> bool:
        if isinstance(expr, ast.Constant):
            return isinstance(expr.value, (int, float))
        if isinstance(expr, ast.Name):
            if expr.id in env:
                return False
            return expr.id.isupper() or _CAPISH.search(expr.id) is not None
        if isinstance(expr, ast.Attribute):
            return (
                expr.attr == "size"
                or expr.attr.isupper()
                or _CAPISH.search(expr.attr) is not None
            )
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            if d is None:
                return False
            base, attrs = d
            return (attrs[-1] if attrs else base) in _BOUND_CALLS
        if isinstance(expr, ast.BinOp):
            return self._boundish(expr.left, env) and self._boundish(
                expr.right, env
            )
        if isinstance(expr, ast.UnaryOp):
            return self._boundish(expr.operand, env)
        return False

    # -- findings / stats -------------------------------------------------

    def _note_sink_site(self, node) -> None:
        if self._recording and self._rel.startswith(PACKAGE_PREFIX):
            self.sinks += 1

    def _sink(self, rule_name, lineno, taint, symbol, message) -> None:
        if not self._recording:
            return
        if not self._rel.startswith(PACKAGE_PREFIX):
            return  # tests/tools feeding the decoders are not surface
        key = (rule_name, self._rel, lineno, symbol)
        if key in self._seen:
            return
        self._seen.add(key)
        full = taint[1] + [(symbol, self._rel, lineno)]
        self.findings.append(
            Finding(
                rule_name,
                self._rel,
                lineno,
                f"{message} (chain: {chain_names(full)})",
                chain=chain_evidence(full),
            )
        )

    def stats(self) -> dict:
        return {
            "taint_sources": self.sources,
            "taint_sinks": self.sinks,
            "taint_sanitized": self.sanitized,
            "taint_build_s": self.build_seconds,
        }


def taint_for(model: ProgramModel) -> TaintFlow:
    """One TaintFlow per program model, shared by the taint rules (and
    surfaced into ``--stats`` by the engine)."""
    tf = getattr(model, "_taint", None)
    if tf is None:
        tf = TaintFlow(model)
        model._taint = tf
    return tf


@rule(
    "unbounded-peer-allocation",
    "a peer-controlled integer sizes an allocation (bytes(n), seq * n) "
    "without a dominating bound check",
    scope="program",
)
def unbounded_peer_allocation(model: ProgramModel) -> Iterator[Finding]:
    for f in taint_for(model).findings:
        if f.rule == "unbounded-peer-allocation":
            yield f


@rule(
    "unvalidated-count-loop",
    "a peer-controlled count drives a range() loop or recursion without "
    "a dominating bound check",
    scope="program",
)
def unvalidated_count_loop(model: ProgramModel) -> Iterator[Finding]:
    for f in taint_for(model).findings:
        if f.rule == "unvalidated-count-loop":
            yield f


@rule(
    "unchecked-peer-read-size",
    "a peer-controlled length reaches a stream read or slice bound "
    "without a dominating bound check",
    scope="program",
)
def unchecked_peer_read_size(model: ProgramModel) -> Iterator[Finding]:
    for f in taint_for(model).findings:
        if f.rule == "unchecked-peer-read-size":
            yield f


# -- taint-boundary-drift ------------------------------------------------------

#: A trust-boundary table row:
#: ``| `pattern` | source | `module/path.py` | meaning |`` (source rows)
#: ``| `pattern` | sink   | —                | meaning |`` (sink rows)
_BOUNDARY_ROW = re.compile(
    r"^\s*\|\s*`([A-Za-z_][A-Za-z0-9_-]*)`\s*\|\s*(source|sink)\s*\|"
    r"\s*(?:`([^`]+)`|[-—–]+)\s*\|"
)

_DESIGN_DOC = "docs/DESIGN.md"


def _boundary_rows(root: str):
    """[(pattern, role, module-or-None, lineno)] from the DESIGN.md
    trust-boundary table, or None when the doc (or the table) is absent
    — the rule then skips entirely, so scratch fixture trees without
    docs stay clean."""
    lines = read_doc_lines(os.path.join(root, *_DESIGN_DOC.split("/")))
    if lines is None:
        return None
    rows = []
    for i, line in enumerate(lines, start=1):
        m = _BOUNDARY_ROW.match(line)
        if m is not None:
            rows.append((m.group(1), m.group(2), m.group(3), i))
    return rows or None


@rule(
    "taint-boundary-drift",
    "the docs/DESIGN.md trust-boundary table and the actual peer-read "
    "call sites disagree",
    scope="program",
)
def taint_boundary_drift(model: ProgramModel) -> Iterator[Finding]:
    root = model.package_root()
    if root is None:
        return
    rows = _boundary_rows(root)
    if rows is None:
        return
    tf = taint_for(model)
    doc_sources: Dict[Tuple[str, str], int] = {}
    doc_sinks: Dict[str, int] = {}
    for pattern, role, module, lineno in rows:
        if role == "source" and module is not None:
            doc_sources.setdefault((module, pattern), lineno)
        elif role == "sink":
            doc_sinks.setdefault(pattern, lineno)

    # doc -> code: a documented source must have a live call site the
    # analysis actually taints (the vocabulary AND the tree agree).
    for (module, pattern), lineno in sorted(doc_sources.items()):
        if (module, pattern) not in tf.source_sites:
            yield Finding(
                "taint-boundary-drift",
                _DESIGN_DOC,
                lineno,
                f"trust-boundary table declares source '{pattern}' in "
                f"{module} but no such peer-read call site exists "
                f"(stale row)",
            )
    # code -> doc: every peer-read site the analysis taints must be
    # declared in the table.
    for (module, pattern), lineno in sorted(tf.source_sites.items()):
        if (module, pattern) not in doc_sources:
            yield Finding(
                "taint-boundary-drift",
                module,
                lineno,
                f"peer-read call '{pattern}' is a live taint source in "
                f"{module} but is missing from the {_DESIGN_DOC} "
                f"trust-boundary table",
            )
    # sink vocabulary: set equality, both directions.
    for pattern, lineno in sorted(doc_sinks.items()):
        if pattern not in SINK_VOCAB:
            yield Finding(
                "taint-boundary-drift",
                _DESIGN_DOC,
                lineno,
                f"trust-boundary table declares sink '{pattern}' but the "
                f"analysis has no such sink (stale row)",
            )
    anchor = min(doc_sinks.values()) if doc_sinks else rows[0][3]
    for pattern in sorted(SINK_VOCAB - set(doc_sinks)):
        yield Finding(
            "taint-boundary-drift",
            _DESIGN_DOC,
            anchor,
            f"taint sink '{pattern}' is checked by the analysis but "
            f"missing from the trust-boundary table",
        )
