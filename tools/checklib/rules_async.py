"""Asyncio concurrency rules.

These target the bug classes an ~8k-LoC asyncio tree actually grows
(un-awaited coroutines, GC'd fire-and-forget tasks, event-loop stalls,
swallowed cancellation) — the analysis is intentionally local to one
file: a call is only treated as a coroutine when it resolves to an
``async def`` in the same module, which keeps every rule zero-false-
positive on this tree at the cost of missing cross-module cases (the
suppression/baseline machinery is for the opposite error direction).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Tuple

from checklib.context import FileContext, dotted_name
from checklib.registry import finding, rule

#: Known event-loop-blocking callables (dotted as written at call sites).
#: socket.create_connection and the subprocess waiters wedge the whole
#: loop for their full duration; time.sleep for its argument.
BLOCKING_CALLS = frozenset(
    {
        "time.sleep",
        "subprocess.run",
        "subprocess.call",
        "subprocess.check_call",
        "subprocess.check_output",
        "subprocess.getoutput",
        "subprocess.getstatusoutput",
        "socket.create_connection",
        "socket.getaddrinfo",
        "os.system",
        "os.popen",
    }
)

_TASK_SPAWNERS = ("create_task", "ensure_future")


def _walk_state(
    node: ast.AST, in_async: bool = False, cls: Optional[ast.ClassDef] = None
) -> Iterator[Tuple[ast.AST, bool, Optional[ast.ClassDef]]]:
    """Yield every node with (inside-async-def?, enclosing class) state.

    Only a function's BODY takes on that function's context: its
    decorators, argument defaults, and annotations evaluate at
    *definition* time in the enclosing context (a blocking call in an
    async def's decorator runs wherever the def statement runs, not on
    an awaited frame — and conversely, a sync def nested in an async
    body IS defined on the loop).  A nested sync ``def``/``lambda``
    body resets ``in_async`` — it runs whenever it is *called*, which
    need not be on the loop.
    """
    yield node, in_async, cls
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        body = [node.body] if isinstance(node, ast.Lambda) else node.body
        body_ids = {id(stmt) for stmt in body}
        body_async = isinstance(node, ast.AsyncFunctionDef)
        for child in ast.iter_child_nodes(node):
            child_cls = child if isinstance(child, ast.ClassDef) else cls
            if id(child) in body_ids:
                yield from _walk_state(child, body_async, child_cls)
            else:  # decorators, args (defaults/annotations), returns
                yield from _walk_state(child, in_async, child_cls)
        return
    for child in ast.iter_child_nodes(node):
        child_cls = child if isinstance(child, ast.ClassDef) else cls
        yield from _walk_state(child, in_async, child_cls)


def _expr_call(node) -> Optional[ast.Call]:
    """The Call of a bare expression statement (result discarded)."""
    if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
        return node.value
    return None


@rule(
    "unawaited-coroutine",
    "call to a same-module async def whose result is discarded",
)
def unawaited_coroutine(ctx: FileContext):
    for node, _in_async, cls in _walk_state(ctx.tree):
        call = _expr_call(node)
        if call is None:
            continue
        func = call.func
        if (
            isinstance(func, ast.Name)
            and func.id in ctx.async_def_names
            and func.id not in ctx.shadowable_names
        ):
            yield finding(
                ctx,
                "unawaited-coroutine",
                node,
                f"coroutine '{func.id}()' is never awaited",
            )
        elif (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in ("self", "cls")
            and cls is not None
            and func.attr in ctx.async_methods_of(cls)
        ):
            yield finding(
                ctx,
                "unawaited-coroutine",
                node,
                f"coroutine 'self.{func.attr}()' is never awaited",
            )


@rule(
    "dropped-task",
    "create_task/ensure_future result discarded (task can be GC'd mid-run)",
)
def dropped_task(ctx: FileContext):
    # The event loop holds only a weak reference to running tasks: a
    # task whose last strong reference is the discarded return value can
    # be garbage-collected mid-flight.  Keep the handle (a tracked set,
    # an attribute) or add a done-callback that owns it.
    for node in ast.walk(ctx.tree):
        call = _expr_call(node)
        if call is None:
            continue
        func = call.func
        # Any .create_task/.ensure_future attribute counts, whatever the
        # receiver — including chains rooted in a call, the repo's own
        # `asyncio.get_running_loop().create_task(...)` idiom, which
        # dotted_name() alone cannot resolve.
        if isinstance(func, ast.Attribute) and func.attr in _TASK_SPAWNERS:
            if (
                isinstance(func.value, ast.Name)
                and func.value.id in ctx.cm_bound_names
            ):
                # a with-statement capture (asyncio.TaskGroup() as tg)
                # owns its tasks — discarding the handle is correct
                continue
            shown = dotted_name(func) or ast.unparse(func)
        elif isinstance(func, ast.Name) and func.id in _TASK_SPAWNERS:
            shown = func.id
        else:
            continue
        yield finding(
            ctx,
            "dropped-task",
            node,
            f"task handle from '{shown}(...)' is discarded",
        )


@rule(
    "blocking-call-in-async",
    "event-loop-blocking call inside an async def",
    scope="package",
)
def blocking_call_in_async(ctx: FileContext):
    for node, in_async, _cls in _walk_state(ctx.tree):
        if not in_async or not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name in BLOCKING_CALLS:
            yield finding(
                ctx,
                "blocking-call-in-async",
                node,
                f"blocking call '{name}(...)' inside async def",
            )
        elif isinstance(node.func, ast.Name) and node.func.id == "open":
            mode = _open_mode(node)
            if mode is not None and any(c in mode for c in "wax+"):
                yield finding(
                    ctx,
                    "blocking-call-in-async",
                    node,
                    f"blocking call 'open(..., {mode!r})' inside async def",
                )


def _open_mode(call: ast.Call) -> Optional[str]:
    mode = call.args[1] if len(call.args) >= 2 else None
    if mode is None:
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


#: Exception expressions that catch CancelledError.
_CANCEL_CATCHERS = frozenset(
    {
        "BaseException",
        "CancelledError",
        "asyncio.CancelledError",
        "concurrent.futures.CancelledError",
    }
)


def _catches_cancel(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    exprs = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(dotted_name(e) in _CANCEL_CATCHERS for e in exprs)


def _reraises(handler: ast.ExceptHandler) -> bool:
    """A ``raise`` anywhere in the handler body (excluding nested defs)
    counts: bare re-raise propagates the CancelledError, and a converting
    raise still fails the await — the hazard is *silent* swallowing."""
    stack = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Raise):
            return True
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


def _is_cancel_reap(try_node: ast.Try) -> bool:
    """The cancel-and-reap idiom: every statement in the try body is an
    ``await`` of a plain name/attribute (``await task`` after
    ``task.cancel()``) — there the CancelledError is one this code just
    induced, and swallowing it is the point."""
    for stmt in try_node.body:
        value = None
        if isinstance(stmt, ast.Expr):
            value = stmt.value
        elif isinstance(stmt, ast.Assign):
            value = stmt.value
        if not isinstance(value, ast.Await):
            return False
        if not isinstance(value.value, (ast.Name, ast.Attribute)):
            return False
    return bool(try_node.body)


@rule(
    "swallowed-cancel",
    "handler catches CancelledError (or broader) without re-raising",
)
def swallowed_cancel(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if not _catches_cancel(handler):
                continue
            if _reraises(handler):
                continue
            if _is_cancel_reap(node):
                continue
            what = (
                "bare except"
                if handler.type is None
                else f"'except {ast.unparse(handler.type)}'"
            )
            yield finding(
                ctx,
                "swallowed-cancel",
                handler,
                f"{what} swallows CancelledError (no re-raise)",
            )
