"""Per-file context shared by every rule.

Parsing, the scope-resolver pass, and the cheap whole-tree fact
collection happen once here; rules then do their own (small) walks
against the shared tree.  Facts collected:

  * ``async_def_names`` — every name bound by an ``async def`` anywhere
    in the file (module level or nested), for the unawaited-coroutine
    rule's "locally resolvable" test;
  * ``local_private_attrs`` — every ``_name`` a class in this module
    defines (``self._x = ...`` in any method, class-body assignments,
    ``__slots__`` entries, ``def _m``) — private access *between* objects
    of this module's own classes is cooperation, not an API poke;
  * ``in_package`` — whether the file ships in the daemon package
    (``registrar_tpu/``), which arms the package-only hygiene rules.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from checklib.scopes import ScopeAnalyzer, iter_all_args

#: Path prefix (posix-relative) of shipped daemon code: package-scoped
#: rules (blocking calls, private-attr pokes, asserts) apply here only —
#: tests and tooling poke privates and assert by design.
PACKAGE_PREFIX = "registrar_tpu/"


def dotted_name(node) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _class_private_attrs(cls: ast.ClassDef) -> Set[str]:
    """Every single-underscore attribute a class body defines."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        # self._x = ... / self._x: T = ... anywhere in a method body
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                for sub in ast.walk(t):
                    if (
                        isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id in ("self", "cls")
                    ):
                        out.add(sub.attr)
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(stmt.name)
        elif isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
                    if t.id == "__slots__":
                        out.update(_slot_names(stmt.value))
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
            stmt.target, ast.Name
        ):
            out.add(stmt.target.id)
    return {a for a in out if a.startswith("_") and not a.startswith("__")}


def _slot_names(value) -> Set[str]:
    if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
        return {
            e.value
            for e in value.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
    return set()


class FileContext:
    """Everything a rule may consult about one file."""

    def __init__(self, path: str, rel_path: str, source: bytes, tree: ast.Module):
        self.path = path
        self.rel_path = rel_path  # posix-relative; used in reports/baseline
        self.tree = tree
        # Split on '\n' ONLY — str.splitlines() also breaks on \f/\v/
        # \x1c/U+2028, which ast and tokenize do NOT treat as newlines,
        # so a form feed above a suppression comment would skew every
        # line number below it and silently unbind the suppressions.
        self.source_text = source.decode("utf-8", errors="replace")
        self.source_lines = self.source_text.split("\n")
        self.in_package = rel_path.startswith(PACKAGE_PREFIX)

        analyzer = ScopeAnalyzer()
        analyzer.visit(tree)
        #: (rule, lineno, message) from the name rules' resolver pass.
        self.scope_problems = analyzer.resolve()

        self.async_def_names: Set[str] = set()
        self.local_private_attrs: Set[str] = set()
        self.classes: List[ast.ClassDef] = []
        #: Names an async-def name may be *shadowed* by somewhere in the
        #: file (parameters, assignments, import aliases).  The
        #: unawaited-coroutine rule does no scope resolution, so a name
        #: in this set is ambiguous — e.g. `def fire(notify): notify()`
        #: beside `async def notify()` — and must not be flagged
        #: (zero-false-positive beats coverage in a build gate).
        self.shadowable_names: Set[str] = set()
        #: Names bound as `with ... as <name>` targets — receivers whose
        #: methods manage their own lifecycles (TaskGroup and friends).
        self.cm_bound_names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self.async_def_names.add(node.name)
            elif isinstance(node, ast.FunctionDef):
                # a sync def of the same name makes the binding ambiguous
                self.shadowable_names.add(node.name)
            elif isinstance(node, ast.ClassDef):
                self.classes.append(node)
                self.local_private_attrs |= _class_private_attrs(node)
                self.shadowable_names.add(node.name)
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                for arg in iter_all_args(node.args):
                    self.shadowable_names.add(arg.arg)
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store
            ):
                self.shadowable_names.add(node.id)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    self.shadowable_names.add(
                        alias.asname or alias.name.split(".")[0]
                    )
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                # `async with asyncio.TaskGroup() as tg:` — a context
                # manager OWNS what it hands out; tg.create_task(...)
                # discarding the handle is the canonical idiom, not the
                # GC hazard the dropped-task rule exists for.
                for item in node.items:
                    if item.optional_vars is not None:
                        for sub in ast.walk(item.optional_vars):
                            if isinstance(sub, ast.Name):
                                self.cm_bound_names.add(sub.id)

    def async_methods_of(self, cls: ast.ClassDef) -> Set[str]:
        return {
            stmt.name
            for stmt in cls.body
            if isinstance(stmt, ast.AsyncFunctionDef)
        }
