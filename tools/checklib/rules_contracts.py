"""Generation-2 contract rules: the program's string-keyed surfaces.

Two contracts in this tree live entirely in string literals — the
EventEmitter event names every subsystem hangs off, and the config keys
shared between the accessors, docs/CONFIG.md, and
etc/config.example.json.  A typo in either compiles, imports, and passes
every unit test that doesn't exercise that exact wiring; these rules
diff the surfaces program-wide instead.
"""

from __future__ import annotations

import os
from typing import Iterator

from checklib.model import Finding
from checklib.program import (
    ProgramModel,
    parse_config_doc,
    parse_config_example,
)
from checklib.registry import rule

#: The modules that translate operator-facing JSON into the package's
#: runtime surface — the "accessors" of the config-key-drift contract
#: (config.py parses the file; records/registration consume the
#: passed-through ``registration`` block verbatim).
CONFIG_ACCESSOR_PATHS = (
    "registrar_tpu/config.py",
    "registrar_tpu/records.py",
    "registrar_tpu/registration.py",
)

CONFIG_DOC = "docs/CONFIG.md"
CONFIG_EXAMPLE = "etc/config.example.json"


@rule(
    "dead-event-name",
    "event emitted with no .on/.once/.wait_for listener in the program",
    scope="program",
)
def dead_event_name(model: ProgramModel) -> Iterator[Finding]:
    # emit("hearbeat") [sic] compiles and runs: the event silently never
    # reaches anyone, which is exactly how the session_reborn /
    # watch_rearm_failed / resume_refused wiring would fail.  Constant
    # event names only — dynamic emits (the client's per-path watch
    # emitter) are not modeled, and listeners anywhere in the checked
    # program (tests observing an event keep it alive) count.
    listened = {
        s.event
        for mod in model.modules.values()
        for s in mod.event_sites
        if s.kind == "listen"
    }
    for mod in model.modules.values():
        for site in mod.event_sites:
            if site.kind == "emit" and site.event not in listened:
                yield Finding(
                    "dead-event-name",
                    site.rel_path,
                    site.lineno,
                    f"event '{site.event}' is emitted but nothing in the "
                    "program listens for it (.on/.once/.wait_for)",
                )


@rule(
    "unknown-event-name",
    "listener registered for an event nothing in the program emits",
    scope="program",
)
def unknown_event_name(model: ProgramModel) -> Iterator[Finding]:
    # The mirror image: .on("hearbeat") registers happily and fires
    # never — a monitoring hook or a test waiting on a typo'd name.
    emitted = {
        s.event
        for mod in model.modules.values()
        for s in mod.event_sites
        if s.kind == "emit"
    }
    for mod in model.modules.values():
        for site in mod.event_sites:
            if site.kind == "listen" and site.event not in emitted:
                yield Finding(
                    "unknown-event-name",
                    site.rel_path,
                    site.lineno,
                    f"listener for '{site.event}' matches no .emit() in "
                    "the program (typo'd or removed event name?)",
                )


@rule(
    "config-key-drift",
    "config keys drift between accessors, docs/CONFIG.md, and the "
    "example config",
    scope="program",
)
def config_key_drift(model: ProgramModel) -> Iterator[Finding]:
    # Three sources of truth for the same key set, each consumed by a
    # different audience (the daemon, operators, deploy templating); a
    # key present in one and missing in another is a distinct failure
    # mode per direction, so each direction is its own finding message.
    if CONFIG_ACCESSOR_PATHS[0] not in model.by_path:
        return  # no config accessor in this program: nothing to diff
    root = model.package_root()
    if root is None:
        return

    code: dict = {}
    for rel in CONFIG_ACCESSOR_PATHS:
        mod = model.by_path.get(rel)
        if mod is None:
            continue
        for key, lineno in sorted(mod.key_reads.items()):
            code.setdefault(key, (rel, lineno))

    doc_path = os.path.join(root, *CONFIG_DOC.split("/"))
    example_path = os.path.join(root, *CONFIG_EXAMPLE.split("/"))
    doc = parse_config_doc(doc_path)
    example = parse_config_example(example_path)

    if doc is not None:
        table_keys, mentions = doc
        for key, (rel, lineno) in sorted(code.items()):
            if key not in mentions:
                yield Finding(
                    "config-key-drift",
                    rel,
                    lineno,
                    f"config key '{key}' is read by the accessors but "
                    f"never documented in {CONFIG_DOC}",
                )
        for key, lineno in sorted(table_keys.items()):
            if key not in code:
                yield Finding(
                    "config-key-drift",
                    CONFIG_DOC,
                    lineno,
                    f"config key '{key}' is documented but no accessor "
                    "reads it (dead documentation or a missing feature)",
                )
        if example is not None:
            for key, lineno in sorted(table_keys.items()):
                if key not in example:
                    yield Finding(
                        "config-key-drift",
                        CONFIG_DOC,
                        lineno,
                        f"config key '{key}' is documented but missing "
                        f"from {CONFIG_EXAMPLE} (which claims to "
                        "exercise every documented key)",
                    )
    if example is not None:
        for key, (rel, lineno) in sorted(code.items()):
            if key not in example:
                yield Finding(
                    "config-key-drift",
                    rel,
                    lineno,
                    f"config key '{key}' is read by the accessors but "
                    f"not exercised by {CONFIG_EXAMPLE}",
                )
        for key in sorted(example - set(code)):
            yield Finding(
                "config-key-drift",
                CONFIG_EXAMPLE,
                0,
                f"config key '{key}' is present in the example config "
                "but no accessor reads it (typo'd or removed key?)",
            )
        if doc is not None:
            _, mentions = doc
            for key in sorted(example - mentions):
                yield Finding(
                    "config-key-drift",
                    CONFIG_EXAMPLE,
                    0,
                    f"config key '{key}' is present in the example "
                    f"config but never documented in {CONFIG_DOC}",
                )
