"""Generation-2 contract rules: the program's string-keyed surfaces.

Two contracts in this tree live entirely in string literals — the
EventEmitter event names every subsystem hangs off, and the config keys
shared between the accessors, docs/CONFIG.md, and
etc/config.example.json.  A typo in either compiles, imports, and passes
every unit test that doesn't exercise that exact wiring; these rules
diff the surfaces program-wide instead.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator, Set

from checklib.model import Finding
from checklib.program import (
    ProgramModel,
    parse_config_doc,
    parse_config_example,
)
from checklib.registry import rule

#: The modules that translate operator-facing JSON into the package's
#: runtime surface — the "accessors" of the config-key-drift contract
#: (config.py parses the file; records/registration consume the
#: passed-through ``registration`` block verbatim).
CONFIG_ACCESSOR_PATHS = (
    "registrar_tpu/config.py",
    "registrar_tpu/records.py",
    "registrar_tpu/registration.py",
)

CONFIG_DOC = "docs/CONFIG.md"
CONFIG_EXAMPLE = "etc/config.example.json"


@rule(
    "dead-event-name",
    "event emitted with no .on/.once/.wait_for listener in the program",
    scope="program",
)
def dead_event_name(model: ProgramModel) -> Iterator[Finding]:
    # emit("hearbeat") [sic] compiles and runs: the event silently never
    # reaches anyone, which is exactly how the session_reborn /
    # watch_rearm_failed / resume_refused wiring would fail.  Constant
    # event names only — dynamic emits (the client's per-path watch
    # emitter) are not modeled, and listeners anywhere in the checked
    # program (tests observing an event keep it alive) count.
    listened = {
        s.event
        for mod in model.modules.values()
        for s in mod.event_sites
        if s.kind == "listen"
    }
    for mod in model.modules.values():
        for site in mod.event_sites:
            if site.kind == "emit" and site.event not in listened:
                yield Finding(
                    "dead-event-name",
                    site.rel_path,
                    site.lineno,
                    f"event '{site.event}' is emitted but nothing in the "
                    "program listens for it (.on/.once/.wait_for)",
                )


@rule(
    "unknown-event-name",
    "listener registered for an event nothing in the program emits",
    scope="program",
)
def unknown_event_name(model: ProgramModel) -> Iterator[Finding]:
    # The mirror image: .on("hearbeat") registers happily and fires
    # never — a monitoring hook or a test waiting on a typo'd name.
    emitted = {
        s.event
        for mod in model.modules.values()
        for s in mod.event_sites
        if s.kind == "emit"
    }
    for mod in model.modules.values():
        for site in mod.event_sites:
            if site.kind == "listen" and site.event not in emitted:
                yield Finding(
                    "unknown-event-name",
                    site.rel_path,
                    site.lineno,
                    f"listener for '{site.event}' matches no .emit() in "
                    "the program (typo'd or removed event name?)",
                )


@rule(
    "config-key-drift",
    "config keys drift between accessors, docs/CONFIG.md, and the "
    "example config",
    scope="program",
)
def config_key_drift(model: ProgramModel) -> Iterator[Finding]:
    # Three sources of truth for the same key set, each consumed by a
    # different audience (the daemon, operators, deploy templating); a
    # key present in one and missing in another is a distinct failure
    # mode per direction, so each direction is its own finding message.
    if CONFIG_ACCESSOR_PATHS[0] not in model.by_path:
        return  # no config accessor in this program: nothing to diff
    root = model.package_root()
    if root is None:
        return

    code: dict = {}
    for rel in CONFIG_ACCESSOR_PATHS:
        mod = model.by_path.get(rel)
        if mod is None:
            continue
        for key, lineno in sorted(mod.key_reads.items()):
            code.setdefault(key, (rel, lineno))

    doc_path = os.path.join(root, *CONFIG_DOC.split("/"))
    example_path = os.path.join(root, *CONFIG_EXAMPLE.split("/"))
    doc = parse_config_doc(doc_path)
    example = parse_config_example(example_path)

    if doc is not None:
        table_keys, mentions = doc
        for key, (rel, lineno) in sorted(code.items()):
            if key not in mentions:
                yield Finding(
                    "config-key-drift",
                    rel,
                    lineno,
                    f"config key '{key}' is read by the accessors but "
                    f"never documented in {CONFIG_DOC}",
                )
        for key, lineno in sorted(table_keys.items()):
            if key not in code:
                yield Finding(
                    "config-key-drift",
                    CONFIG_DOC,
                    lineno,
                    f"config key '{key}' is documented but no accessor "
                    "reads it (dead documentation or a missing feature)",
                )
        if example is not None:
            for key, lineno in sorted(table_keys.items()):
                if key not in example:
                    yield Finding(
                        "config-key-drift",
                        CONFIG_DOC,
                        lineno,
                        f"config key '{key}' is documented but missing "
                        f"from {CONFIG_EXAMPLE} (which claims to "
                        "exercise every documented key)",
                    )
    if example is not None:
        for key, (rel, lineno) in sorted(code.items()):
            if key not in example:
                yield Finding(
                    "config-key-drift",
                    rel,
                    lineno,
                    f"config key '{key}' is read by the accessors but "
                    f"not exercised by {CONFIG_EXAMPLE}",
                )
        for key in sorted(example - set(code)):
            yield Finding(
                "config-key-drift",
                CONFIG_EXAMPLE,
                0,
                f"config key '{key}' is present in the example config "
                "but no accessor reads it (typo'd or removed key?)",
            )
        if doc is not None:
            _, mentions = doc
            for key in sorted(example - mentions):
                yield Finding(
                    "config-key-drift",
                    CONFIG_EXAMPLE,
                    0,
                    f"config key '{key}' is present in the example "
                    f"config but never documented in {CONFIG_DOC}",
                )


# -- doc scanning shared by the drift rules -----------------------------------


def read_doc_lines(path: str):
    """Lines of a documentation file, or None when it is absent or
    unreadable — the ONE copy of the read-or-skip pattern every
    doc-drift rule (config keys, metric names, the fault matrix in
    rules_errors.py) shares, so a rule skips a missing doc's leg
    instead of condemning everything against an empty mention set."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return fh.read().split("\n")
    except OSError:
        return None


# -- metric-name-drift ---------------------------------------------------------

METRICS_PATH = "registrar_tpu/metrics.py"
OPERATIONS_DOC = "docs/OPERATIONS.md"

#: ``registrar_*`` tokens in doc prose/alert expressions.  Greedy over
#: the name alphabet; a token ending in ``_`` is a prefix/wildcard
#: mention (``registrar_cache_*``, ``grep registrar_``) and is skipped.
_METRIC_REF = re.compile(r"registrar_[a-z0-9_]*")


#: rendered-series suffixes a histogram FAMILY name implies: the bare
#: family never renders, so a runbook legitimately references only
#: these (`rate(registrar_zk_op_seconds_count[5m])`,
#: `histogram_quantile(0.99, ...registrar_zk_op_seconds_bucket...)`)
HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


def _defined_metric_names(tree) -> Set[str]:
    """String literals passed as CALL arguments in metrics.py — the
    ``Counter("registrar_x_total", ...)`` constructor surface.  The
    module docstring also lists every name, but a docstring can go
    stale exactly like the runbook; only real constructor args count.

    A ``Histogram`` (``reg.histogram(...)`` / ``Histogram(...)``)
    constructor additionally defines its rendered ``_bucket``/``_sum``/
    ``_count`` series — the bare family name never appears in the
    exposition, so those suffixed forms are what runbooks reference."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        func_name = (
            func.attr
            if isinstance(func, ast.Attribute)
            else getattr(func, "id", "")
        )
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and _METRIC_REF.fullmatch(arg.value)
                and not arg.value.endswith("_")
            ):
                out.add(arg.value)
                if func_name in ("histogram", "Histogram"):
                    for suffix in HISTOGRAM_SUFFIXES:
                        out.add(arg.value + suffix)
    return out


@rule(
    "metric-name-drift",
    "docs/OPERATIONS.md references a metric metrics.py no longer "
    "pre-seeds",
    scope="program",
)
def metric_name_drift(model: ProgramModel) -> Iterator[Finding]:
    # Every registrar_* series is pre-seeded at instrument() time so
    # alerts never silently match an absent series — which makes the
    # runbook's metric NAMES part of the contract: renaming a counter
    # in metrics.py kills every alert built on the old name without a
    # single test failing.  Diff direction: a name the alerts/runbooks
    # reference must exist in metrics.py.  (The reverse — a metric the
    # runbook doesn't mention — is fine: docs highlight, they don't
    # enumerate.)
    mod = model.by_path.get(METRICS_PATH)
    if mod is None:
        return
    root = model.package_root()
    if root is None:
        return
    defined = _defined_metric_names(mod.ctx.tree)
    if not defined:
        return
    lines = read_doc_lines(os.path.join(root, *OPERATIONS_DOC.split("/")))
    if lines is None:
        return
    seen: Set[str] = set()
    for i, line in enumerate(lines, start=1):
        for m in _METRIC_REF.finditer(line):
            name = m.group(0)
            if name.endswith("_") or name in seen:
                continue  # prefix/wildcard mention, or already reported
            if name.startswith("registrar_tpu"):
                continue  # the package import path, not a metric name
            seen.add(name)
            if name not in defined:
                yield Finding(
                    "metric-name-drift",
                    OPERATIONS_DOC,
                    i,
                    f"metric '{name}' is referenced by the alerts/"
                    f"runbooks but {METRICS_PATH} pre-seeds no such "
                    "series (a renamed counter silently kills this "
                    "alert)",
                )


# -- fault-id-drift ------------------------------------------------------------

FAULTS_CATALOG_DOC = "docs/FAULTS.md"

#: fault-class ids are kebab-case tokens with at least one dash
#: (``crash-loop``, ``netem-episode``) — the dash requirement keeps
#: ordinary single-word string call-args out of the diff
_FAULT_ID = re.compile(r"^[a-z][a-z0-9]*(-[a-z0-9]+)+$")

#: a catalog row's machine-readable marker: ``id: crash-loop`` (bare or
#: backticked) in docs/FAULTS.md
_DOC_FAULT_ID = re.compile(r"\bid:\s*`?([a-z][a-z0-9-]*)`?")


def _code_fault_ids(model: ProgramModel):
    """Constant fault-class ids at harness injection sites in the
    package — ``<harness>.inject("crash-loop", ...)`` — as
    ``{id: (rel_path, lineno)}`` (first site wins)."""
    out: dict = {}
    for mod in model.modules.values():
        if not mod.rel_path.startswith("registrar_tpu/"):
            continue
        for node in ast.walk(mod.ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            func_name = (
                func.attr
                if isinstance(func, ast.Attribute)
                else getattr(func, "id", "")
            )
            if func_name != "inject":
                continue
            arg = node.args[0]
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and _FAULT_ID.match(arg.value)
            ):
                out.setdefault(arg.value, (mod.rel_path, node.lineno))
    return out


@rule(
    "fault-id-drift",
    "fault-class ids drift between the SLO harness injection sites "
    "and the docs/FAULTS.md catalog",
    scope="program",
)
def fault_id_drift(model: ProgramModel) -> Iterator[Finding]:
    # Fault-class ids are a contract exactly like span names: the SLO
    # report keys MTTD/MTTR by them, the outage-seconds metric labels
    # by them, and operators grep docs/FAULTS.md's catalog for the
    # recovery path behind a bad number.  A scenario renamed in the
    # harness silently orphans its catalog row (and dashboard filters)
    # without failing a single test — so both directions are diffed,
    # the same shape as span-name-drift.
    root = model.package_root()
    if root is None:
        return
    code = _code_fault_ids(model)
    lines = read_doc_lines(
        os.path.join(root, *FAULTS_CATALOG_DOC.split("/"))
    )
    doc_ids: dict = {}
    if lines is not None:
        for i, line in enumerate(lines, start=1):
            for m in _DOC_FAULT_ID.finditer(line):
                if _FAULT_ID.match(m.group(1)):
                    doc_ids.setdefault(m.group(1), i)
    if not code and not doc_ids:
        return  # no SLO harness and no catalog: nothing to diff
    if lines is None:
        # the harness injects but the catalog doc is missing entirely:
        # anchor ONE finding per id at its injection site
        for fid, (rel, lineno) in sorted(code.items()):
            yield Finding(
                "fault-id-drift",
                rel,
                lineno,
                f"fault id '{fid}' is injected by the harness but "
                f"{FAULTS_CATALOG_DOC} (the fault-class catalog) does "
                "not exist",
            )
        return
    for fid, (rel, lineno) in sorted(code.items()):
        if fid not in doc_ids:
            yield Finding(
                "fault-id-drift",
                rel,
                lineno,
                f"fault id '{fid}' is injected by the harness but has "
                f"no `id:` row in {FAULTS_CATALOG_DOC}",
            )
    for fid, lineno in sorted(doc_ids.items()):
        if fid not in code:
            yield Finding(
                "fault-id-drift",
                FAULTS_CATALOG_DOC,
                lineno,
                f"fault id '{fid}' is cataloged but no harness "
                "injection site uses it (renamed or removed scenario?)",
            )


# -- bench-metric-drift --------------------------------------------------------

BENCH_PATH = "bench.py"
BENCH_HISTORY = "BENCH_HISTORY.json"
PERF_DOC = "docs/PERF.md"

#: bench metric names are snake_case tokens with at least one underscore
#: (``heartbeat_ms_1000_znodes``, ``live_resolve_qps``) — the underscore
#: requirement keeps single-word table cells out of the diff
_BENCH_METRIC = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)+$")

#: gate directions a BENCH_METRICS entry may carry (None = unpinned)
_BENCH_DIRECTIONS = ("lower", "higher")


def _bench_declared(path: str):
    """bench.py's module-level ``BENCH_METRICS`` dict literal as
    ``{name: (direction-or-None, lineno)}``; None when the file is
    missing/unparseable, ``{}`` when the declaration is absent."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "BENCH_METRICS"
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        out = {}
        for key, val in zip(node.value.keys, node.value.values):
            if (
                isinstance(key, ast.Constant)
                and isinstance(key.value, str)
                and isinstance(val, ast.Constant)
                and (val.value is None or val.value in _BENCH_DIRECTIONS)
            ):
                out[key.value] = (val.value, key.lineno)
        return out
    return {}


def _perf_doc_metric_cells(lines):
    """Metric-name tokens from docs/PERF.md's metric tables, as
    ``{name: lineno}``.  Only tables whose header's first cell contains
    the word "metric" count — prose and code-identifier tables stay out
    of the diff.  A first cell that IS a metric-shaped token is always a
    data row, never a header — ``phantom_metric_ms`` contains the
    substring "metric" but must be scanned, not skipped."""
    out: dict = {}
    in_metric_table = False
    for i, line in enumerate(lines, start=1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_metric_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        first = cells[0].strip("`").strip()
        if _BENCH_METRIC.match(first):
            if in_metric_table:
                out.setdefault(first, i)
            continue
        if re.search(r"\bmetric\b", first.lower()):
            in_metric_table = True  # a header labels, it never cites
        # separator rows and prose-labeled data rows change nothing
    return out


@rule(
    "bench-metric-drift",
    "bench metric names drift between bench.py's BENCH_METRICS, "
    "BENCH_HISTORY.json's directions, and docs/PERF.md's tables",
    scope="program",
)
def bench_metric_drift(model: ProgramModel) -> Iterator[Finding]:
    # Bench metric names are a contract exactly like fault ids and
    # metric names: BENCH_HISTORY.json keys every round by them, the
    # generated baseline gates by them, and docs/PERF.md's tables cite
    # them.  A metric renamed in bench.py silently orphans its history
    # pin (the gate's "missing from bench output" only fires at bench
    # runtime, on the driver box) and its doc rows — so the three
    # surfaces are diffed here, statically, on every `make check`.
    # bench.py's declared map is the code-side truth (gate() enforces at
    # runtime that every emitted metric is declared in it).
    root = model.package_root()
    if root is None:
        return
    import json as _json

    bench_path = os.path.join(root, BENCH_PATH)
    if not os.path.exists(bench_path):
        return  # no bench in this program: nothing to diff
    declared = _bench_declared(bench_path)
    if declared is None:
        return  # unparseable: the syntax-error finding owns this
    history_path = os.path.join(root, BENCH_HISTORY)
    directions: dict = {}
    have_history = os.path.exists(history_path)
    if have_history:
        try:
            with open(history_path, "r", encoding="utf-8") as fh:
                directions = _json.load(fh).get("directions", {})
        except (OSError, ValueError):
            yield Finding(
                "bench-metric-drift",
                BENCH_HISTORY,
                0,
                f"{BENCH_HISTORY} exists but is not readable JSON — the "
                "baseline gate is generated from it",
            )
            return
    if not declared and (directions or have_history):
        yield Finding(
            "bench-metric-drift",
            BENCH_PATH,
            0,
            f"{BENCH_PATH} declares no BENCH_METRICS literal map — the "
            "metric-name contract cannot be checked",
        )
        return
    for name, direction in sorted(directions.items()):
        spec = declared.get(name)
        if spec is None:
            yield Finding(
                "bench-metric-drift",
                BENCH_HISTORY,
                0,
                f"metric '{name}' is pinned in {BENCH_HISTORY} but "
                f"{BENCH_PATH}'s BENCH_METRICS does not declare it "
                "(renamed or removed measurement? the gate would report "
                "it missing on every run)",
            )
        elif spec[0] != direction:
            yield Finding(
                "bench-metric-drift",
                BENCH_PATH,
                spec[1],
                f"metric '{name}' is declared '{spec[0]}' in "
                f"BENCH_METRICS but {BENCH_HISTORY} pins direction "
                f"'{direction}'",
            )
    for name, (direction, lineno) in sorted(declared.items()):
        if direction is not None and name not in directions:
            yield Finding(
                "bench-metric-drift",
                BENCH_PATH,
                lineno,
                f"metric '{name}' is declared gate-direction "
                f"'{direction}' but {BENCH_HISTORY} has no directions "
                "entry for it (record a round and repin, or declare it "
                "None/unpinned)",
            )
    lines = read_doc_lines(os.path.join(root, *PERF_DOC.split("/")))
    if lines is None:
        return  # no perf doc: its leg just doesn't apply
    known = set(declared) | set(directions)
    if not known:
        return
    for name, lineno in sorted(_perf_doc_metric_cells(lines).items()):
        if name not in known:
            yield Finding(
                "bench-metric-drift",
                PERF_DOC,
                lineno,
                f"{PERF_DOC} metric table cites '{name}', which neither "
                f"{BENCH_PATH}'s BENCH_METRICS nor {BENCH_HISTORY} "
                "knows (renamed metric orphaning its doc row?)",
            )


# -- span-name-drift -----------------------------------------------------------

OBSERVABILITY_DOC = "docs/OBSERVABILITY.md"

#: the tracer call surface whose first string argument is a span/event
#: name (registrar_tpu/trace.py: Tracer.span/start_span/event)
_TRACE_CALL_NAMES = frozenset({"span", "start_span", "event"})

#: span/event names are dotted lowercase tokens (``zk.op``,
#: ``cache.invalidated``) — the dot requirement keeps unrelated
#: single-word string call-args out of the diff entirely
_SPAN_NAME = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")


def _code_span_names(model: ProgramModel):
    """Constant span/event names at tracer call sites in the package:
    ``{name: (rel_path, lineno)}`` (first site wins)."""
    out: dict = {}
    for mod in model.modules.values():
        if not mod.rel_path.startswith("registrar_tpu/"):
            continue
        for node in ast.walk(mod.ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            func_name = (
                func.attr
                if isinstance(func, ast.Attribute)
                else getattr(func, "id", "")
            )
            if func_name not in _TRACE_CALL_NAMES:
                continue
            arg = node.args[0]
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and _SPAN_NAME.match(arg.value)
            ):
                out.setdefault(arg.value, (mod.rel_path, node.lineno))
    return out


@rule(
    "span-name-drift",
    "span/event names drift between tracer call sites and the "
    "docs/OBSERVABILITY.md catalog",
    scope="program",
)
def span_name_drift(model: ProgramModel) -> Iterator[Finding]:
    # Span names are a contract exactly like metric names: dashboards
    # filter the flight recorder by them, the slow-span runbook greps
    # for them, and instrument_tracing routes them into histograms by
    # string equality — a renamed span silently empties a histogram
    # without failing a single test.  Both directions are diffed: a
    # code name the catalog misses is undocumented surface; a cataloged
    # name no code emits is a dead runbook entry.
    root = model.package_root()
    if root is None:
        return
    code = _code_span_names(model)
    if not code:
        return  # no tracing layer in this program: nothing to diff
    doc_path = os.path.join(root, *OBSERVABILITY_DOC.split("/"))
    lines = read_doc_lines(doc_path)
    if lines is None:
        # The catalog doc is missing entirely but the code traces:
        # anchor ONE finding per name at its call site.
        for name, (rel, lineno) in sorted(code.items()):
            yield Finding(
                "span-name-drift",
                rel,
                lineno,
                f"span/event name '{name}' is used in code but "
                f"{OBSERVABILITY_DOC} (the span catalog) does not exist",
            )
        return
    mentions: Set[str] = set()
    table_names: dict = {}
    for i, line in enumerate(lines, start=1):
        for m in re.finditer(r"`([^`]+)`", line):
            token = m.group(1)
            if _SPAN_NAME.match(token):
                mentions.add(token)
        stripped = line.strip()
        if not stripped.startswith("|"):
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        m = re.fullmatch(r"`([^`]+)`", cells[0])
        if m and _SPAN_NAME.match(m.group(1)):
            table_names.setdefault(m.group(1), i)
    for name, (rel, lineno) in sorted(code.items()):
        if name not in mentions:
            yield Finding(
                "span-name-drift",
                rel,
                lineno,
                f"span/event name '{name}' is used in code but never "
                f"cataloged in {OBSERVABILITY_DOC}",
            )
    for name, lineno in sorted(table_names.items()):
        if name not in code:
            yield Finding(
                "span-name-drift",
                OBSERVABILITY_DOC,
                lineno,
                f"span/event name '{name}' is cataloged but no tracer "
                "call site in the package uses it (renamed or removed "
                "span?)",
            )
