#!/usr/bin/env python3
"""Benchmark: full registration lifecycle through the real wire stack.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

What is measured — the complete reference-default register operation
(SURVEY.md §3.1) end to end over a real TCP socket: the five-stage
pipeline (cleanup, 1 s settle delay, mkdirp, ephemeral creates, service
put) against the in-process ZooKeeper server, until the znodes are
readable by an independent observer session.

Baseline semantics: the reference publishes no benchmark numbers
(BASELINE.md) — its registration latency is floor-bounded by the
hard-coded 1,000 ms settle delay (reference lib/register.js:232-235) plus
ZooKeeper RPC time.  ``vs_baseline`` is therefore baseline_floor_ms /
measured_ms: ~1.0 means the rebuild hits the contract-mandated floor with
negligible overhead (it cannot exceed 1.0 without changing observable
behavior the survey pins).  The settle-free pipeline cost is reported in
``extra`` for visibility into the actual implementation overhead.
"""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from registrar_tpu import binderview  # noqa: E402
from registrar_tpu.registration import register, unregister  # noqa: E402
from registrar_tpu.testing.server import ZKServer  # noqa: E402
from registrar_tpu.zk.client import ZKClient  # noqa: E402

REGISTRATION = {
    "domain": "bench.emy-10.joyent.us",
    "type": "load_balancer",
    "aliases": ["alias-1.bench.emy-10.joyent.us"],
    "service": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
    },
}

BASELINE_FLOOR_MS = 1000.0  # reference lib/register.js:232-235 settle delay


async def _bench() -> dict:
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    observer = await ZKClient([server.address]).connect()
    try:
        # Warm-up (connection + first-op costs out of the measurement).
        nodes = await register(
            client, REGISTRATION, admin_ip="10.0.0.1",
            hostname="benchhost", settle_delay=0,
        )
        await unregister(client, nodes)

        # Measured: reference-default register (1 s settle included),
        # until visible to an independent session.
        t0 = time.perf_counter()
        nodes = await register(
            client, REGISTRATION, admin_ip="10.0.0.1", hostname="benchhost",
        )
        for n in nodes:
            await observer.stat(n)
        register_ms = (time.perf_counter() - t0) * 1000.0

        # Settle-free pipeline cost over many iterations (implementation
        # overhead: 4 ephemeral nodes + service record + cleanup, ~13 RPCs).
        iters = 50
        t0 = time.perf_counter()
        for _ in range(iters):
            nodes = await register(
                client, REGISTRATION, admin_ip="10.0.0.1",
                hostname="benchhost", settle_delay=0,
            )
        pipeline_ms = (time.perf_counter() - t0) * 1000.0 / iters

        # Heartbeat probe latency (hot loop #1, SURVEY.md §3.2).
        t0 = time.perf_counter()
        for _ in range(iters):
            await client.heartbeat(nodes)
        heartbeat_ms = (time.perf_counter() - t0) * 1000.0 / iters

        # Binder-view resolution latency (what a DNS answer costs to
        # assemble from the znodes; registrar_tpu/binderview.py).
        t0 = time.perf_counter()
        for _ in range(iters):
            res = await binderview.resolve(
                observer, REGISTRATION["domain"], "A"
            )
        resolve_ms = (time.perf_counter() - t0) * 1000.0 / iters
        if res.empty:
            raise RuntimeError(
                "resolve benchmark measured an empty result — the timed "
                "path was not the real answer-assembly path"
            )

        # Concurrent-registrar throughput: N independent sessions (the
        # real deployment shape — one registrar per zone) registering
        # distinct domains at once, settle-free.
        n_conc = 20
        conc_clients = [
            await ZKClient([server.address]).connect() for _ in range(n_conc)
        ]
        try:
            t0 = time.perf_counter()
            await asyncio.gather(
                *(
                    register(
                        c,
                        {"domain": f"c{i}.bench.emy-10.joyent.us",
                         "type": "host"},
                        admin_ip="10.0.0.2",
                        hostname=f"host{i}",
                        settle_delay=0,
                    )
                    for i, c in enumerate(conc_clients)
                )
            )
            conc_s = time.perf_counter() - t0
        finally:
            for c in conc_clients:
                await c.close()
        throughput = n_conc / conc_s

        return {
            "metric": "register_to_visible_ms",
            "value": round(register_ms, 2),
            "unit": "ms",
            "vs_baseline": round(BASELINE_FLOOR_MS / register_ms, 4),
            "extra": {
                "baseline": "reference floor: 1000ms mandated settle delay "
                "(lib/register.js:232-235) + ZK RPC time; reference "
                "publishes no benchmark numbers (BASELINE.md)",
                "pipeline_ms_no_settle": round(pipeline_ms, 3),
                "heartbeat_ms": round(heartbeat_ms, 3),
                "resolve_a_query_ms": round(resolve_ms, 3),
                "concurrent_registrations_per_s": round(throughput, 1),
                "znodes_per_registration": len(nodes),
            },
        }
    finally:
        await observer.close()
        await client.close()
        await server.stop()


if __name__ == "__main__":
    print(json.dumps(asyncio.run(_bench())))
