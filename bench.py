#!/usr/bin/env python3
"""Benchmark: full registration lifecycle through the real wire stack.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Regression gate: when ``BENCH_BASELINE.json`` is present (checked in),
every numeric metric is compared against its pinned cross-round
baseline after the line is printed; any metric regressing more than the
tolerance — 10% by default, ``BENCH_TOLERANCE_PCT`` to widen on slower
hardware, ``BENCH_GATE=0`` to disable — fails the run with exit 1 and a
per-metric report on stderr.  One automatic retry absorbs scheduler
noise: a genuine slowdown fails both runs, a one-off blip does not.

What is measured — the complete reference-default register operation
(SURVEY.md §3.1) end to end over a real TCP socket: the five-stage
pipeline (cleanup, 1 s settle delay, mkdirp, ephemeral creates, service
put) against the in-process ZooKeeper server, until the znodes are
readable by an independent observer session.

Baseline semantics: the reference publishes no benchmark numbers
(BASELINE.md) — its registration latency is floor-bounded by the
hard-coded 1,000 ms settle delay (reference lib/register.js:232-235) plus
ZooKeeper RPC time.  ``vs_baseline`` is therefore baseline_floor_ms /
measured_ms: ~1.0 means the rebuild hits the contract-mandated floor with
negligible overhead (it cannot exceed 1.0 without changing observable
behavior the survey pins).  The settle-free pipeline cost is reported in
``extra`` for visibility into the actual implementation overhead.
"""

import asyncio
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from registrar_tpu import binderview  # noqa: E402
from registrar_tpu import metrics as metrics_mod  # noqa: E402
from registrar_tpu import trace as trace_mod  # noqa: E402
from registrar_tpu.records import (  # noqa: E402
    domain_to_path,
    host_record,
    payload_bytes,
)
from registrar_tpu.registration import register, unregister  # noqa: E402
from registrar_tpu.testing.server import ZKServer  # noqa: E402
from registrar_tpu.zk.client import ZKClient  # noqa: E402
from registrar_tpu.zk.protocol import CreateFlag  # noqa: E402
from registrar_tpu.zkcache import ZKCache  # noqa: E402

REGISTRATION = {
    "domain": "bench.emy-10.joyent.us",
    "type": "load_balancer",
    "aliases": ["alias-1.bench.emy-10.joyent.us"],
    "service": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
    },
}

BASELINE_FLOOR_MS = 1000.0  # reference lib/register.js:232-235 settle delay

#: BENCH_SMOKE=1 (the CI bench leg): run the 1k-scale variants but skip
#: the 10k-znode sweep — its metric is emitted as null so the gate reads
#: it as "unmeasurable in this environment", exactly like daemon_rss_mb
#: off-Linux.  The full matrix stays driver-box-only (r06-dev precedent).
SMOKE = os.environ.get("BENCH_SMOKE") == "1"

#: Every metric name this bench can emit, mapped to its gate direction —
#: "lower"/"higher" for metrics pinned in BENCH_HISTORY.json, None for
#: deliberately-unpinned extras (scheduler-noise-dominated deltas whose
#: real gate is an in-process assert).  THE machine-checked contract:
#: checklib's bench-metric-drift rule diffs this literal map against
#: BENCH_HISTORY.json's directions and docs/PERF.md's metric tables, and
#: gate() fails on any emitted metric missing from it — so a renamed
#: metric cannot silently orphan its history pin or its doc row.
BENCH_METRICS = {
    "register_to_visible_ms": "lower",
    "pipeline_ms_no_settle": "lower",
    "heartbeat_ms": "lower",
    "resolve_a_query_ms": "lower",
    "concurrent_registrations_per_s": "higher",
    "heartbeat_ms_100_znodes": "lower",
    "heartbeat_ms_1000_znodes": "lower",
    "heartbeat_ms_10000_znodes": "lower",
    "heartbeat_ms_1000_znodes_coalesced_100_services": "lower",
    "live_resolve_qps": "higher",
    "concurrent_agents_100": "higher",
    "resolve_a_ms_50_instances": "lower",
    "resolve_srv_ms_50_instances": "lower",
    "watch_fanout_ms_50_watchers": "lower",
    "daemon_rss_mb": "lower",
    "resolve_a_cached_ms_50_instances": "lower",
    "resolve_srv_cached_ms_50_instances": "lower",
    "cached_resolve_qps_50_instances": "higher",
    "cache_coherence_lag_ms": "lower",
    "resolve_cached_hist_p50_ms": "lower",
    "resolve_cached_hist_p95_ms": "lower",
    "resolve_cached_hist_p99_ms": "lower",
    "resolve_a_cached_traced_ms": None,
    "resolve_srv_cached_traced_ms": None,
    "trace_overhead_pct": None,
    "znodes_per_registration": None,
    "sharded_resolve_qps_1_shards": "higher",
    "sharded_resolve_qps_2_shards": "higher",
    "sharded_resolve_qps_4_shards": "higher",
    "sharded_live_resolve_qps_4_shards": "higher",
    "sharded_resolve_qps_4_shards_traced": "higher",
    "sharded_trace_overhead_pct": None,
    "reshard_warm_handoff_ms": "lower",
    "overload_admitted_warm_p99_ms": "lower",
    "overload_shed_fastfail_p99_ms": "lower",
    "overload_capacity_qps": None,
    "overload_offered_x_capacity": None,
    "overload_sheds_total": None,
    "overload_storm_seed": None,
    "dns_udp_qps_4_shards": "higher",
    "dns_a_p99_us": "lower",
    "dns_nxdomain_p99_us": "lower",
    "dns_encode_cache_hit_ratio": "higher",
    "dns_storm_seed": None,
}

#: histogram-quantile metric names as literals (consumed from
#: BENCH_METRICS-checkable constants, not built by f-string)
HIST_QUANTILE_METRICS = (
    (0.50, "resolve_cached_hist_p50_ms"),
    (0.95, "resolve_cached_hist_p95_ms"),
    (0.99, "resolve_cached_hist_p99_ms"),
)

FLEET_DOMAIN = "fleet.bench.emy-10.joyent.us"
FLEET_REG = {
    "domain": FLEET_DOMAIN,
    "type": "load_balancer",
    "service": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
    },
}


async def _register_fleet(client, n: int = 50) -> None:
    """The biggest realistic Binder answer: a large stateless fleet
    behind one domain (shared by the live and cached resolve benches)."""
    for i in range(n):
        await register(
            client, FLEET_REG, admin_ip=f"10.1.{i // 256}.{i % 256}",
            hostname=f"inst{i}", settle_delay=0,
        )


async def _cached_metrics(
    client, observer, live_a_ms: float, live_srv_ms: float, iters: int = 1000
) -> dict:
    """Measure the ISSUE-4 watch-coherent cache over the 50-instance
    fleet: warm resolve latency (A + SRV), sustained cached QPS, and the
    write→cache-visible coherence lag.

    Enforces the acceptance bound inline: a warm cached resolve must be
    ≥10× faster than the live-read path measured in the same run — if
    the cache ever quietly falls back to RPCs, the run fails loudly
    rather than letting the gate's tolerance absorb it.
    """
    srv_name = f"_http._tcp.{FLEET_DOMAIN}"
    cache = ZKCache(observer)
    try:
        # Cold fills (and the correctness gate on what we time below).
        res_a = await binderview.resolve(cache, FLEET_DOMAIN, "A")
        res_srv = await binderview.resolve(cache, srv_name, "SRV")
        if len(res_a.answers) != 50 or len(res_srv.answers) != 50:
            raise RuntimeError(
                "cached resolve did not see all 50 instances "
                f"(A={len(res_a.answers)} SRV={len(res_srv.answers)})"
            )

        # Median of bursts, like the concurrency metric (docs/PERF.md
        # round-4 post-mortem): a single burst of sub-100µs resolves is
        # scheduler-noise-dominated; the median across bursts tracks the
        # code.
        burst = max(iters // 5, 1)

        async def med_burst(name: str, qtype: str) -> float:
            rates = []
            for _ in range(5):
                t0 = time.perf_counter()
                for _ in range(burst):
                    res = await binderview.resolve(cache, name, qtype)
                rates.append((time.perf_counter() - t0) * 1000.0 / burst)
                if len(res.answers) != 50:
                    raise RuntimeError("cached resolve lost instances")
            return sorted(rates)[len(rates) // 2]

        cached_a_ms = await med_burst(FLEET_DOMAIN, "A")
        cached_srv_ms = await med_burst(srv_name, "SRV")
        if not cache.authoritative or cache.stats["bypasses"]:
            raise RuntimeError(
                "cached bench ran degraded — the timed path was not the "
                "in-memory hot path"
            )
        if cached_a_ms * 10 > live_a_ms or cached_srv_ms * 10 > live_srv_ms:
            raise RuntimeError(
                "cached resolve is not >=10x faster than live "
                f"(A {cached_a_ms:.4f} vs {live_a_ms:.4f} ms, "
                f"SRV {cached_srv_ms:.4f} vs {live_srv_ms:.4f} ms)"
            )

        # ---- ISSUE 8: the same hot path under 100% tracing ------------
        # Acceptance bound: with spans on at sample_rate=1.0 feeding the
        # registrar_resolve_seconds histogram, a warm cached resolve may
        # cost at most 10% over the untraced path (BENCH_TRACE_OVERHEAD_PCT
        # to widen on noisy boxes).  Sub-100µs medians are noise-prone, so
        # each attempt re-measures the untraced base back-to-back with the
        # traced pass and the verdict is the best of 3 attempts — a real
        # per-resolve tracing cost shows up in every attempt, a scheduler
        # blip does not.
        tracer = trace_mod.Tracer(sample_rate=1.0)
        treg = metrics_mod.instrument_tracing(tracer)
        limit_pct = float(os.environ.get("BENCH_TRACE_OVERHEAD_PCT", "10"))
        overhead_pct = traced_a_ms = traced_srv_ms = None
        for _attempt in range(3):
            base_a = await med_burst(FLEET_DOMAIN, "A")
            base_srv = await med_burst(srv_name, "SRV")
            cache.tracer = tracer
            try:
                t_a = await med_burst(FLEET_DOMAIN, "A")
                t_srv = await med_burst(srv_name, "SRV")
            finally:
                cache.tracer = None
            attempt_pct = (
                max(t_a / base_a, t_srv / base_srv) - 1.0
            ) * 100.0
            if overhead_pct is None or attempt_pct < overhead_pct:
                overhead_pct = attempt_pct
                traced_a_ms, traced_srv_ms = t_a, t_srv
            if overhead_pct <= limit_pct:
                break
        if overhead_pct > limit_pct:
            raise RuntimeError(
                "tracing overhead on the warm cached resolve exceeds "
                f"{limit_pct}%: best attempt {overhead_pct:.1f}% "
                f"(traced A {traced_a_ms:.4f} ms, SRV {traced_srv_ms:.4f} ms)"
            )
        hist = treg.get("registrar_resolve_seconds")
        if not hist.count({"source": "cached"}):
            raise RuntimeError(
                "traced bench recorded no cached resolve spans — the "
                "timed path was not the instrumented hot path"
            )
        if hist.count({"source": "live"}):
            raise RuntimeError(
                "traced bench recorded live-labeled resolves — the cache "
                "degraded mid-measurement"
            )
        # The p50/p95/p99 a production scrape would compute from the new
        # histogram (bucket-interpolated, like histogram_quantile()) —
        # recorded into the bench round so the distribution, not just the
        # burst median, is regression-gated.
        hist_quantiles = {
            name: round(hist.quantile(q, {"source": "cached"}) * 1000.0, 4)
            for q, name in HIST_QUANTILE_METRICS
        }

        # Sustained throughput, mixed A+SRV (the cached-QPS headline);
        # median of bursts for the same noise-rejection reason.
        qps_rounds = []
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(burst):
                await binderview.resolve(cache, FLEET_DOMAIN, "A")
                await binderview.resolve(cache, srv_name, "SRV")
            qps_rounds.append((2 * burst) / (time.perf_counter() - t0))
        qps = sorted(qps_rounds)[len(qps_rounds) // 2]

        # Coherence lag: write an instance record, poll the CACHED view
        # until the new address is served.  The clock covers the whole
        # pipeline under test — commit, watch delivery, invalidation,
        # live refill — i.e. how long a DNS answer can lag the truth.
        inst_path = f"{domain_to_path(FLEET_DOMAIN)}/inst0"
        lags = []
        for rnd in range(11):
            new_addr = f"10.3.{rnd}.9"
            payload = payload_bytes(host_record("load_balancer", new_addr))
            t0 = time.perf_counter()
            await client.set_data(inst_path, payload)
            deadline = t0 + 5.0
            while True:
                res = await binderview.resolve(cache, FLEET_DOMAIN, "A")
                if any(a.data == new_addr for a in res.answers):
                    break
                if time.perf_counter() > deadline:
                    raise RuntimeError(
                        f"cache never converged on write round {rnd} — "
                        "coherence is broken, not just slow"
                    )
                await asyncio.sleep(0)
            lags.append((time.perf_counter() - t0) * 1000.0)
        lags.sort()
        coherence_ms = lags[len(lags) // 2]
        # restore inst0 for any later consumer of the fleet tree
        await client.set_data(
            inst_path, payload_bytes(host_record("load_balancer", "10.1.0.0"))
        )
        return {
            "resolve_a_cached_ms_50_instances": round(cached_a_ms, 4),
            "resolve_srv_cached_ms_50_instances": round(cached_srv_ms, 4),
            "cached_resolve_qps_50_instances": round(qps, 1),
            "cache_coherence_lag_ms": round(coherence_ms, 3),
            "resolve_a_cached_traced_ms": round(traced_a_ms, 4),
            "resolve_srv_cached_traced_ms": round(traced_srv_ms, 4),
            "trace_overhead_pct": round(overhead_pct, 2),
            **hist_quantiles,
        }
    finally:
        cache.close()


async def _create_ephemerals(client, paths) -> None:
    """Create many ephemerals fast: chunked multi transactions (500 ops
    per txn) instead of one awaited round trip — or task — per node; a
    10k-node fixture stands up in tens of txns."""
    from registrar_tpu.zk.client import Op

    chunk = 500
    for i in range(0, len(paths), chunk):
        await client.multi(
            [
                Op.create(p, b"", CreateFlag.EPHEMERAL)
                for p in paths[i : i + chunk]
            ]
        )


LIVE_QPS_DOMAIN = "liveqps.emy-10.joyent.us"


async def _live_resolve_qps(client, server, conns: int = 4,
                            workers: int = 100, per_worker: int = 30) -> float:
    """Aggregate live-read resolve throughput (ISSUE 11 matrix).

    ``workers`` concurrent resolver coroutines spread over ``conns``
    observer sessions, each resolving a dedicated single-host domain's A
    record ``per_worker`` times; median wall-clock QPS of 3 rounds.
    Uncached by construction (plain ZKClient source), so every resolve
    pays the full wire path — read_node + instance get_many.  Its own
    domain because the concurrency bench nests its throwaway domains as
    CHILDREN of the shared bench domain, which would silently turn this
    into a 100-way fan-out measurement.
    """
    await register(
        client,
        {
            "domain": LIVE_QPS_DOMAIN,
            "type": "load_balancer",
            # The service record is what makes the domain node resolve
            # (a bare host child answers nothing at the domain name).
            "service": {
                "type": "service",
                "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
            },
        },
        admin_ip="10.4.0.1", hostname="livehost", settle_delay=0,
    )
    clients = []
    try:
        for _ in range(conns):
            clients.append(await ZKClient([server.address]).connect())

        async def worker(cl, count):
            for _ in range(count):
                res = await binderview.resolve(cl, LIVE_QPS_DOMAIN, "A")
            return res

        rates = []
        for rnd in range(-1, 3):  # round -1 warms up, unmeasured
            t0 = time.perf_counter()
            results = await asyncio.gather(
                *(
                    worker(clients[i % conns], per_worker)
                    for i in range(workers)
                )
            )
            if rnd >= 0:
                rates.append(
                    workers * per_worker / (time.perf_counter() - t0)
                )
        if any(r.empty for r in results):
            raise RuntimeError(
                "live resolve QPS measured empty answers — the timed "
                "path was not the real answer-assembly path"
            )
        return sorted(rates)[len(rates) // 2]
    finally:
        for cl in clients:
            await cl.close()


# ---- sharded serve tier (ISSUE 12) -----------------------------------------

SHARD_DOMAIN_SUFFIX = "shardbench.emy-10.joyent.us"

#: shard counts the scaling matrix measures; names are BENCH_METRICS
#: literals so the drift rule can see them
SHARD_QPS_METRICS = {
    1: "sharded_resolve_qps_1_shards",
    2: "sharded_resolve_qps_2_shards",
    4: "sharded_resolve_qps_4_shards",
}


def _pick_shard_domains(n_domains: int) -> list:
    """Choose bench domain names that COVER every slice of the widest
    measured ring (4 shards).  The ring is deterministic, so this is a
    pure function — and it matters: a domain set that happens to miss a
    shard would quietly turn the '4-shard' figure into a 3-worker
    measurement and skew the scaling ratio."""
    from registrar_tpu.shard import HashRing

    ring = HashRing(range(max(SHARD_QPS_METRICS)))
    by_owner, fillers = {}, []
    for i in range(256):
        dom = f"d{i}.{SHARD_DOMAIN_SUFFIX}"
        owner = ring.owner(dom)
        if owner not in by_owner:
            by_owner[owner] = dom  # coverage before quota, always
        else:
            fillers.append(dom)
        if (
            len(by_owner) == len(ring.shard_ids)
            and len(by_owner) + len(fillers) >= n_domains
        ):
            break
    chosen = list(by_owner.values()) + fillers
    return chosen[:max(n_domains, len(by_owner))]


async def _register_shard_domains(
    client, n_domains: int = 8, instances: int = 10
) -> list:
    """The sharded tier's workload: several independent service domains
    (NOT children of the fleet domain — nesting them would pollute its
    answers), each with a small instance fleet, chosen so load covers
    every shard's slice (:func:`_pick_shard_domains`)."""
    domains = []
    for i, dom in enumerate(_pick_shard_domains(n_domains)):
        for j in range(instances):
            await register(
                client,
                {
                    "domain": dom,
                    "type": "load_balancer",
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp", "port": 80,
                        },
                    },
                },
                admin_ip=f"10.5.{i}.{j}", hostname=f"i{j}", settle_delay=0,
            )
        domains.append(dom)
    return domains


async def _sharded_qps(
    server, sock_dir: str, domains: list, shards: int,
    *, live: bool = False, per_shard: int = 1200, rounds: int = 3,
) -> float:
    """Aggregate resolve QPS through a ``shards``-worker tier, measured
    over the direct (SO_REUSEPORT-shaped) data plane: the bench fetches
    the ring once and drives every worker concurrently with pipelined
    request batches — the router is control plane only, exactly the
    future DNS frontend's shape.  Median wall-clock QPS of ``rounds``
    rounds (one unmeasured warmup)."""
    from registrar_tpu.shard import (
        OP_RESOLVE, STATUS_OK, ShardDirectClient, ShardRouter,
        decode_resolution, pack_resolve,
    )

    router = ShardRouter(
        [server.address], shards,
        os.path.join(sock_dir, f"bench{shards}{'l' if live else ''}.sock"),
        attach_spread="any", poll_interval_s=30.0,
    )
    await router.start()
    direct = None
    try:
        direct = await ShardDirectClient(router.socket_path).connect()
        by_owner = {}
        for dom in domains:
            by_owner.setdefault(direct.owner(dom), []).append(dom)
        # Warm every domain (and pin correctness: full answer sets).
        for dom in domains:
            res = await direct.resolve(dom, "A")
            if not res.answers:
                raise RuntimeError(f"sharded warm resolve empty for {dom}")

        async def drive(shard_id: int, doms: list, count: int) -> None:
            chan = await direct.channel(shard_id)
            reqs = [pack_resolve(d, "A", live) for d in doms]
            batch = 64
            done = 0
            while done < count:
                n = min(batch, count - done)
                replies = await asyncio.gather(
                    *(
                        chan.request(OP_RESOLVE, reqs[(done + k) % len(reqs)])
                        for k in range(n)
                    )
                )
                done += n
                # EVERY reply's status is checked: error frames return
                # faster than real resolves, so a partially-failing
                # batch would otherwise read as a SPEEDUP and the
                # higher-is-better gate would reward the outage.
                for status, body in replies:
                    if status != STATUS_OK:
                        raise RuntimeError(
                            f"sharded resolve errored: {bytes(body)!r}"
                        )
            # Decode one reply per driver per round — the timed path
            # must be producing real answers, not error frames.
            if not decode_resolution(body).answers:
                raise RuntimeError("sharded resolve lost its answers")

        rates = []
        for rnd in range(-1, rounds):
            t0 = time.perf_counter()
            await asyncio.gather(
                *(
                    drive(sid, doms, per_shard)
                    for sid, doms in by_owner.items()
                )
            )
            if rnd >= 0:
                rates.append(
                    per_shard * len(by_owner)
                    / (time.perf_counter() - t0)
                )
        return sorted(rates)[len(rates) // 2]
    finally:
        if direct is not None:
            await direct.close()
        await router.stop()


#: synthetic root context stamped on every traced-bench request: the
#: cost under test is the wire block + the worker's adopt + span work,
#: not the bench's own id minting
TRACE_BENCH_CTX = (0x13C0FFEE00000001, 0x13C0FFEE00000002, 1)


async def _sharded_trace_overhead(
    server, sock_dir: str, domains: list,
    *, shards: int = 4, per_shard: int = 1200, attempts: int = 6,
    assert_bound: bool = True,
) -> tuple:
    """The PR-8 <10% tracing-overhead bound extended to the sharded
    wire path (ISSUE 13): ``sharded_resolve_qps_4_shards`` measured
    traced-at-100%-sampling vs off.  Traced means the FULL cross-
    process cost per request: trace-context block on the wire, the
    worker's adopt + resolve.query span at sample_rate=1.0, and the
    worker_us reply block back.

    Noise discipline: two long-lived tiers (workers spawned ONCE each),
    driven in alternating base/traced rounds so scheduler drift hits
    both sides of a pair, and the verdict is the best pair of
    ``attempts`` — a real per-request cost shows up in every pair, a
    frequency-scaling episode does not (the PR-8 gate's policy, paired
    tighter because multi-process runs drift more than in-process
    bursts).  Returns ``(overhead_pct, traced_qps)`` or raises when the
    best pair still exceeds the bound (BENCH_TRACE_OVERHEAD_PCT to
    widen on noisy boxes).
    """
    from registrar_tpu.shard import (
        OP_RESOLVE, OP_TRACE, STATUS_OK, ShardDirectClient, ShardRouter,
        pack_resolve,
    )

    limit_pct = float(os.environ.get("BENCH_TRACE_OVERHEAD_PCT", "10"))
    routers = []
    directs = {}
    try:
        for kind, worker_trace in (
            ("off", None), ("on", {"sampleRate": 1.0}),
        ):
            router = ShardRouter(
                [server.address], shards,
                os.path.join(sock_dir, f"benchtrace-{kind}.sock"),
                attach_spread="any", poll_interval_s=30.0,
                worker_trace=worker_trace,
            )
            await router.start()
            # Tracked the moment it has worker subprocesses to reap —
            # a failed client connect below must not orphan them.
            routers.append(router)
            direct = await ShardDirectClient(router.socket_path).connect()
            directs[kind] = direct
            for dom in domains:
                if not (await direct.resolve(dom, "A")).answers:
                    raise RuntimeError(
                        f"trace-overhead warm resolve empty for {dom}"
                    )

        async def one_round(direct, ctx) -> float:
            by_owner = {}
            for dom in domains:
                by_owner.setdefault(direct.owner(dom), []).append(dom)

            async def drive(shard_id: int, doms: list) -> None:
                chan = await direct.channel(shard_id)
                reqs = [pack_resolve(d, "A") for d in doms]
                batch = 64
                done = 0
                while done < per_shard:
                    n = min(batch, per_shard - done)
                    replies = await asyncio.gather(
                        *(
                            chan.request(
                                OP_RESOLVE,
                                reqs[(done + k) % len(reqs)],
                                trace_ctx=ctx,
                            )
                            for k in range(n)
                        )
                    )
                    done += n
                    for status, body in replies:
                        if status != STATUS_OK:
                            raise RuntimeError(
                                "trace-overhead resolve errored: "
                                f"{bytes(body)!r}"
                            )

            t0 = time.perf_counter()
            await asyncio.gather(
                *(drive(sid, doms) for sid, doms in by_owner.items())
            )
            return per_shard * len(by_owner) / (time.perf_counter() - t0)

        # warmup both tiers (unmeasured)
        await one_round(directs["off"], None)
        await one_round(directs["on"], TRACE_BENCH_CTX)
        overhead_pct = traced_qps = None
        for _attempt in range(attempts):
            base = await one_round(directs["off"], None)
            traced = await one_round(directs["on"], TRACE_BENCH_CTX)
            pct = (base / traced - 1.0) * 100.0
            if overhead_pct is None or pct < overhead_pct:
                overhead_pct = pct
                traced_qps = traced
            if overhead_pct <= limit_pct * 0.7:
                break  # comfortably under the bound; stop burning rounds
        # Like the >=3x scaling bound: never asserted under SMOKE —
        # contended CI vCPUs would gate scheduler luck, not code (the
        # values are still reported).
        if assert_bound and overhead_pct > limit_pct:
            raise RuntimeError(
                "cross-process tracing overhead on the sharded resolve "
                f"path exceeds {limit_pct}%: best of {attempts} pairs "
                f"{overhead_pct:.1f}% (traced {traced_qps:.1f} qps)"
            )
        # Same honesty check as the PR-8 gate: the traced figure is only
        # a tracing figure if the workers actually recorded the spans (a
        # silently-disabled tracer would "win" by doing nothing).
        direct_on = directs["on"]
        probe = json.dumps(
            {"trace_id": f"{TRACE_BENCH_CTX[0]:016x}"}
        ).encode()
        for sid in range(shards):
            chan = await direct_on.channel(sid)
            status, body = await chan.request(OP_TRACE, probe)
            if status != STATUS_OK or not json.loads(
                bytes(body).decode()
            ).get("entries"):
                raise RuntimeError(
                    f"traced sharded bench: worker {sid} recorded no "
                    "spans — the timed path was not the traced path"
                )
        return overhead_pct, traced_qps
    finally:
        for direct in directs.values():
            await direct.close()
        for router in routers:
            await router.stop()


async def _reshard_handoff(
    server, sock_dir: str, domains: list, shards: int = 4,
) -> float:
    """``reshard_warm_handoff_ms``: wall time of a live reshard
    (``shards`` → ``shards + 1``) — worker spawn, warm-set dump, new-
    owner pre-warm, ring flip, departure drain — while a resolver polls
    the router relay the whole time.  ANY polled error fails the run:
    zero-error resharding is the acceptance bound, not a best effort."""
    from registrar_tpu.shard import ShardClient, ShardRouter

    router = ShardRouter(
        [server.address], shards,
        os.path.join(sock_dir, "benchreshard.sock"),
        attach_spread="any", poll_interval_s=30.0,
    )
    await router.start()
    client = None
    try:
        client = await ShardClient(router.socket_path).connect()
        for dom in domains:
            if not (await client.resolve(dom, "A")).answers:
                raise RuntimeError(f"reshard warm resolve empty for {dom}")
        polling = True
        errors = []

        async def poll() -> int:
            count = 0
            while polling:
                for dom in domains:
                    try:
                        res = await client.resolve(dom, "A")
                        if not res.answers:
                            errors.append(f"{dom}: empty")
                    except Exception as err:  # noqa: BLE001 - the count IS the result
                        errors.append(f"{dom}: {err!r}")
                    count += 1
                await asyncio.sleep(0.002)
            return count

        poller = asyncio.ensure_future(poll())
        outcome = await router.reshard(shards + 1)
        await asyncio.sleep(0.05)  # a few post-flip polls on the new ring
        polling = False
        polled = await poller
        if errors:
            raise RuntimeError(
                f"reshard was not zero-error: {errors[:5]!r} "
                f"({len(errors)} of {polled} polls)"
            )
        if not polled:
            raise RuntimeError("reshard poller never ran")
        return outcome["duration_ms"]
    finally:
        if client is not None:
            await client.close()
        await router.stop()


async def _sharded_metrics(server, client, sock_dir: str,
                           smoke: bool = False) -> dict:
    """The ISSUE-12 scaling matrix: cached QPS at 1/2/4 shards, live QPS
    at 4 shards, and the warm-handoff reshard cost.  On a >=4-core box
    the 4-shard cached figure must be >=3x the 1-shard figure (the
    acceptance bound); on fewer cores the workers time-slice one core
    and the ratio is reported but not asserted."""
    domains = await _register_shard_domains(
        client, n_domains=4 if smoke else 8,
        instances=5 if smoke else 10,
    )
    per_shard = 300 if smoke else 1200
    qps = {}
    for shards, metric in SHARD_QPS_METRICS.items():
        qps[metric] = await _sharded_qps(
            server, sock_dir, domains, shards, per_shard=per_shard,
        )
    live_qps = await _sharded_qps(
        server, sock_dir, domains, 4, live=True,
        per_shard=per_shard // 4,
    )
    overhead_pct, traced_qps = await _sharded_trace_overhead(
        server, sock_dir, domains, per_shard=per_shard,
        attempts=3 if smoke else 6, assert_bound=not smoke,
    )
    handoff_ms = await _reshard_handoff(server, sock_dir, domains)
    overload = await _overload_metrics(
        server, sock_dir, domains, _overload_seed(), smoke=smoke,
    )
    dns = await _dns_metrics(
        server, sock_dir, domains, _dns_seed(), smoke=smoke,
        compare_qps=qps["sharded_resolve_qps_4_shards"],
    )
    cores = os.cpu_count() or 1
    ratio = (
        qps["sharded_resolve_qps_4_shards"]
        / qps["sharded_resolve_qps_1_shards"]
    )
    # The acceptance bound asserts on >=4-core boxes only (its own
    # condition), and never under SMOKE: shared CI "cores" are
    # contended vCPUs, and a scaling ratio measured on them gates
    # scheduler luck, not code.
    if cores >= 4 and not smoke and ratio < 3.0:
        raise RuntimeError(
            f"4-shard cached QPS is only {ratio:.2f}x the 1-shard figure "
            f"on a {cores}-core box (acceptance bound: >=3x)"
        )
    return {
        **{name: round(value, 1) for name, value in qps.items()},
        "sharded_live_resolve_qps_4_shards": round(live_qps, 1),
        "sharded_resolve_qps_4_shards_traced": round(traced_qps, 1),
        "sharded_trace_overhead_pct": round(overhead_pct, 2),
        "reshard_warm_handoff_ms": round(handoff_ms, 1),
        **overload,
        **dns,
    }


#: the bench tier's overload armor (ISSUE 17): per-connection inflight
#: does the shedding, the global depth is the backstop, cold fills are
#: bounded, and a non-reading client is cut loose — mirrors the SLO
#: harness's armored tier so the gated p99 measures the same defenses
#: the nines envelope prices
OVERLOAD_BENCH_ARMOR = {
    "maxQueueDepth": 96,
    "maxInflightPerConn": 6,
    "coldFillConcurrency": 4,
    "writeDeadlineS": 0.4,
}


async def _overload_metrics(
    server, sock_dir: str, domains: list, seed: int,
    shards: int = 2, capacity_x: float = 5.0, storm_s: float = 1.5,
    smoke: bool = False,
) -> dict:
    """p99-under-overload (ISSUE 17): stand up an ARMORED tier, measure
    its warm capacity closed-loop, then drive the seeded heavy-tailed
    storm paced at ``capacity_x`` the measured figure.  The gated
    metrics are the p99 of ADMITTED warm resolves (the armor's promise:
    accepted work stays fast) and the p99 of an explicit shed reply
    (the refusals must be fast too — fail-fast, never silence).  A
    storm request that times out fails the run outright: under armor a
    timeout is a bug, not a data point."""
    from registrar_tpu.shard import ShardRouter
    from registrar_tpu.testing import workload

    router = ShardRouter(
        [server.address], shards,
        os.path.join(sock_dir, "benchoverload.sock"),
        attach_spread="any", poll_interval_s=30.0,
        overload=OVERLOAD_BENCH_ARMOR,
    )
    await router.start()
    try:
        capacity = await workload.measure_capacity(
            router.socket_path, domains,
            seconds=0.25 if smoke else 0.5,
        )
        storm = workload.StormWorkload(
            router.socket_path, domains, seed=seed,
            duration_s=storm_s / 2 if smoke else storm_s,
            clients=8, pipeline=32,
            offered_rps=capacity * capacity_x,
            loris_frames=4000 if smoke else 12000,
        )
        report = await storm.run()
        summary = report.summary()
        if report.timeouts_total:
            raise RuntimeError(
                f"overload storm: {report.timeouts_total} requests timed "
                "out under armor — every refusal must be an explicit "
                f"fast shed (summary: {summary})"
            )
        if report.sheds_total == 0:
            raise RuntimeError(
                "overload storm never shed: offered load "
                f"{summary['offered_rps']} qps did not exceed the tier's "
                f"admission bounds (capacity {capacity:.1f} qps)"
            )
        return {
            "overload_admitted_warm_p99_ms": summary[
                "admitted_warm_p99_ms"
            ],
            "overload_shed_fastfail_p99_ms": summary[
                "shed_fastfail_p99_ms"
            ],
            "overload_capacity_qps": round(capacity, 1),
            "overload_offered_x_capacity": round(
                summary["offered_rps"] / capacity, 2
            ) if capacity else None,
            "overload_sheds_total": summary["sheds_total"],
            "overload_storm_seed": seed,
        }
    finally:
        await router.stop()


def _overload_seed() -> int:
    """The storm seed: pinned via BENCH_OVERLOAD_SEED for replay,
    drawn fresh otherwise — always echoed in the output line."""
    raw = os.environ.get("BENCH_OVERLOAD_SEED")
    if raw is not None:
        return int(raw)
    import random

    return random.randrange(2**32)


def _dns_seed() -> int:
    """The DNS workload seed: pinned via BENCH_DNS_SEED for replay,
    drawn fresh otherwise — always echoed in the output line."""
    raw = os.environ.get("BENCH_DNS_SEED")
    if raw is not None:
        return int(raw)
    import random

    return random.randrange(2**32)


class _DnsLoadProtocol(asyncio.DatagramProtocol):
    """One pipelined UDP load endpoint: outstanding queries matched
    back to their waiter by message id.  One connected endpoint is one
    kernel flow, and SO_REUSEPORT hashes the 4-tuple — so each client
    sticks to exactly one shard worker for its whole life.  The bench
    spreads several clients to cover the tier the way a resolver fleet
    does, and warms each client's own flow (see _dns_metrics)."""

    def __init__(self):
        self.futures = {}
        self.transport = None

    def connection_made(self, transport):
        self.transport = transport

    def datagram_received(self, data, addr):
        fut = self.futures.pop(int.from_bytes(data[:2], "big"), None)
        if fut is not None and not fut.done():
            fut.set_result(data)

    def error_received(self, exc):
        for fut in self.futures.values():
            if not fut.done():
                fut.set_exception(exc)
        self.futures.clear()


async def _dns_metrics(
    server, sock_dir: str, domains: list, seed: int,
    shards: int = 4, smoke: bool = False,
    compare_qps: "float | None" = None,
) -> dict:
    """The ISSUE-19 DNS slice: real UDP packets against the
    SO_REUSEPORT tier under a seeded Zipf-popular workload (~75% warm
    A, ~15% NXDOMAIN, ~10% SRV), pipelined 32-deep per client.

    Two in-process acceptance bounds live here, next to the data:

      * the per-worker encode cache must serve >0.9 of renders under
        the Zipf mix — below that the answer path is re-encoding, not
        patching, and the line-rate claim is fiction;
      * on the full (non-SMOKE) run the DNS tier must deliver >=75% of
        the raw sharded resolve QPS measured on the same box in the
        same run — the wire codec and UDP hop may cost, but not a
        protocol translation's worth.

    Every reply's rcode and answer count are checked inline: an error
    answer returns faster than a real one, and folding it into the QPS
    figure would read as a speedup.
    """
    import random as _random

    from registrar_tpu import dnsfront
    from registrar_tpu.shard import ShardRouter

    rng = _random.Random(seed)
    router = ShardRouter(
        [server.address], shards,
        os.path.join(sock_dir, "benchdns.sock"),
        attach_spread="any", poll_interval_s=30.0,
        dns={"host": "127.0.0.1", "port": 0},
    )
    await router.start()
    loop = asyncio.get_running_loop()
    transports = []
    try:
        host, port = "127.0.0.1", router.dns["port"]
        missing = [f"nx{i}.{SHARD_DOMAIN_SUFFIX}" for i in range(4)]
        n_clients = 4 if smoke else 8
        pipeline = 32
        total = 1000 if smoke else 6000
        clients = []
        for _ in range(n_clients):
            transport, proto = await loop.create_datagram_endpoint(
                _DnsLoadProtocol, remote_addr=(host, port),
            )
            transports.append(transport)
            clients.append(proto)

        qid_counter = [0]

        async def ask(proto, name, qtype):
            qid_counter[0] = (qid_counter[0] + 1) & 0xFFFF
            qid = qid_counter[0]
            while qid in proto.futures:  # outstanding-id collision
                qid = (qid + 1) & 0xFFFF
            fut = loop.create_future()
            proto.futures[qid] = fut
            t0 = time.perf_counter()
            # EDNS 4096 like a real resolver: without it the 512-byte
            # classic limit truncates the 10-instance SRV answers to
            # empty TC replies and the reply check below (rightly)
            # fails the run.
            proto.transport.sendto(
                dnsfront.build_query(
                    qid, name, qtype, rd=True, edns_size=4096,
                )
            )
            data = await asyncio.wait_for(fut, timeout=5.0)
            return data, (time.perf_counter() - t0) * 1e6

        # Zipf popularity over the registered domains (weight 1/rank);
        # a bounded pool of never-registered names rides the negative
        # templates the same way a resolver's junk tail does.
        weights = [1.0 / rank for rank in range(1, len(domains) + 1)]

        def pick():
            r = rng.random()
            if r < 0.15:
                return rng.choice(missing), dnsfront.QTYPE_A, "nx"
            dom = rng.choices(domains, weights=weights)[0]
            if r < 0.25:
                return f"_http._tcp.{dom}", dnsfront.QTYPE_SRV, "srv"
            return dom, dnsfront.QTYPE_A, "a"

        schedule = [pick() for _ in range(total)]

        # Warm pass (unmeasured): every client asks every pool name
        # once.  Clients pin to workers by 4-tuple hash, so warming
        # through one client leaves the others' workers cold — each
        # flow warms itself.  Worst case this costs pool_size x shards
        # cache misses total; the measured phase is then all template
        # patches, which is what the hit-ratio bound certifies.
        warm_names = (
            [(d, dnsfront.QTYPE_A) for d in domains]
            + [(f"_http._tcp.{d}", dnsfront.QTYPE_SRV) for d in domains]
            + [(m, dnsfront.QTYPE_A) for m in missing]
        )
        for proto in clients:
            for name, qtype in warm_names:
                await ask(proto, name, qtype)

        latencies = {"a": [], "nx": [], "srv": []}

        async def drive(proto, part):
            for i in range(0, len(part), pipeline):
                chunk = part[i:i + pipeline]
                replies = await asyncio.gather(
                    *(ask(proto, name, qtype) for name, qtype, _ in chunk)
                )
                for (data, us), (name, _q, kind) in zip(replies, chunk):
                    rcode = data[3] & 0x0F
                    ancount = int.from_bytes(data[6:8], "big")
                    if kind == "nx":
                        if rcode != dnsfront.RCODE_NXDOMAIN:
                            raise RuntimeError(
                                "dns bench: expected NXDOMAIN for "
                                f"{name}, got rcode {rcode}"
                            )
                    elif rcode != dnsfront.RCODE_NOERROR or not ancount:
                        raise RuntimeError(
                            f"dns bench: {name} answered rcode {rcode} "
                            f"with {ancount} answers"
                        )
                    latencies[kind].append(us)

        per = (len(schedule) + n_clients - 1) // n_clients
        parts = [
            schedule[i * per:(i + 1) * per] for i in range(n_clients)
        ]
        t0 = time.perf_counter()
        await asyncio.gather(
            *(drive(p, part) for p, part in zip(clients, parts))
        )
        qps = len(schedule) / (time.perf_counter() - t0)

        def p99(vals):
            vals = sorted(vals)
            return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

        # status() forces a fresh worker poll; dns_rollup() alone folds
        # whatever the last periodic poll saw (30 s stale here).
        await router.status()
        rollup = router.dns_rollup() or {}
        cache = rollup.get("encode_cache") or {}
        hits = int(cache.get("hits", 0))
        misses = int(cache.get("misses", 0))
        ratio = hits / (hits + misses) if (hits + misses) else 0.0
        if ratio <= 0.9:
            raise RuntimeError(
                "dns bench: encode-cache hit ratio under the Zipf "
                f"workload is {ratio:.3f} ({hits} hits / {misses} "
                "misses; acceptance bound: >0.9)"
            )
        if compare_qps and not smoke and qps < 0.75 * compare_qps:
            raise RuntimeError(
                f"dns bench: {qps:.0f} qps over UDP is under 75% of "
                f"the raw sharded figure ({compare_qps:.0f} qps) on "
                "this box — the wire path is costing a protocol "
                "translation, not an encode"
            )
        return {
            "dns_udp_qps_4_shards": round(qps, 1),
            "dns_a_p99_us": round(p99(latencies["a"]), 1),
            "dns_nxdomain_p99_us": round(p99(latencies["nx"]), 1),
            "dns_encode_cache_hit_ratio": round(ratio, 4),
            "dns_storm_seed": seed,
        }
    finally:
        for transport in transports:
            transport.close()
        await router.stop()


async def _concurrent_agents(server, n_agents: int, znodes_each: int) -> float:
    """Full heartbeat sweeps per second across ``n_agents`` concurrent
    sessions, each owning ``znodes_each`` ephemerals (the 1k-instance
    fleet shape when 100 × 10).  Median of 5 concurrent rounds."""
    agents = []
    try:
        for i in range(n_agents):
            cl = await ZKClient([server.address]).connect()
            base = f"/agents/a{i}"
            await cl.mkdirp(base)
            paths = [f"{base}/e{j}" for j in range(znodes_each)]
            await _create_ephemerals(cl, paths)
            agents.append((cl, paths))
        rates = []
        for rnd in range(-1, 5):  # warmup round unmeasured
            t0 = time.perf_counter()
            await asyncio.gather(
                *(cl.heartbeat(paths) for cl, paths in agents)
            )
            if rnd >= 0:
                rates.append(n_agents / (time.perf_counter() - t0))
        return sorted(rates)[len(rates) // 2]
    finally:
        for cl, _ in agents:
            await cl.close()


async def _daemon_rss_mb(server) -> "float | None":
    """Resident memory of a real daemon process once registered, in MiB.

    Returns None where /proc isn't available (non-Linux)."""
    import tempfile

    if not os.path.isdir("/proc"):
        return None
    with tempfile.TemporaryDirectory() as td:
        cfg_path = os.path.join(td, "cfg.json")
        with open(cfg_path, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "registration": {
                        "domain": "rss.bench.emy-10.joyent.us",
                        "type": "host",
                    },
                    "adminIp": "10.2.0.1",
                    "zookeeper": {
                        "servers": [
                            {"host": server.host, "port": server.port}
                        ],
                        "timeout": 5000,
                    },
                },
                f,
            )
        env = dict(os.environ)
        env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
        proc = subprocess.Popen(
            [sys.executable, "-m", "registrar_tpu", "-f", cfg_path],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        try:
            probe = await ZKClient([server.address]).connect()
            try:
                deadline = time.monotonic() + 20
                while (
                    await probe.exists("/us/joyent/emy-10/bench/rss")
                ) is None:
                    if time.monotonic() > deadline:
                        raise RuntimeError("daemon never registered")
                    await asyncio.sleep(0.1)
            finally:
                await probe.close()
            with open(f"/proc/{proc.pid}/status", encoding="utf-8") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return round(int(line.split()[1]) / 1024.0, 1)
            return None
        finally:
            proc.terminate()
            try:
                await asyncio.to_thread(proc.wait, 15)
            except subprocess.TimeoutExpired:
                proc.kill()  # metrics are already in hand; don't leak
                await asyncio.to_thread(proc.wait)


async def _bench() -> dict:
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    observer = await ZKClient([server.address]).connect()
    try:
        # Warm-up (connection + first-op costs out of the measurement).
        nodes = await register(
            client, REGISTRATION, admin_ip="10.0.0.1",
            hostname="benchhost", settle_delay=0,
        )
        await unregister(client, nodes)

        # Measured: reference-default register (1 s settle included),
        # until visible to an independent session.
        t0 = time.perf_counter()
        nodes = await register(
            client, REGISTRATION, admin_ip="10.0.0.1", hostname="benchhost",
        )
        for n in nodes:
            await observer.stat(n)
        register_ms = (time.perf_counter() - t0) * 1000.0

        # Settle-free pipeline cost over many iterations (implementation
        # overhead: 4 ephemeral nodes + service record + cleanup, ~13 RPCs).
        # Enough iterations to ride out scheduler noise — the driver
        # records a single run.
        iters = 200
        t0 = time.perf_counter()
        for _ in range(iters):
            nodes = await register(
                client, REGISTRATION, admin_ip="10.0.0.1",
                hostname="benchhost", settle_delay=0,
            )
        pipeline_ms = (time.perf_counter() - t0) * 1000.0 / iters

        # Heartbeat probe latency (hot loop #1, SURVEY.md §3.2).
        t0 = time.perf_counter()
        for _ in range(iters):
            await client.heartbeat(nodes)
        heartbeat_ms = (time.perf_counter() - t0) * 1000.0 / iters

        # Binder-view resolution latency (what a DNS answer costs to
        # assemble from the znodes; registrar_tpu/binderview.py).
        t0 = time.perf_counter()
        for _ in range(iters):
            res = await binderview.resolve(
                observer, REGISTRATION["domain"], "A"
            )
        resolve_ms = (time.perf_counter() - t0) * 1000.0 / iters
        if res.empty:
            raise RuntimeError(
                "resolve benchmark measured an empty result — the timed "
                "path was not the real answer-assembly path"
            )

        # Concurrent-registrar throughput: N independent sessions (the
        # real deployment shape — one registrar per zone) registering
        # distinct domains at once, settle-free.  Median of several
        # bursts: a single ~9 ms burst is dominated by scheduler noise
        # (r4 post-mortem, docs/PERF.md — round-to-round swings of ±20%
        # with no code change on this path), while the median tracks the
        # code.  Median, not best-of: robust to noise without optimism.
        n_conc = 20
        conc_rounds = 5
        conc_clients = [
            await ZKClient([server.address]).connect() for _ in range(n_conc)
        ]
        try:
            rates = []
            for rnd in range(-1, conc_rounds):
                # rnd -1 is an unmeasured warmup: first-touch costs (code
                # paths, the shared /us/joyent/emy-10/bench prefix) land
                # there, not in the measurement.
                t0 = time.perf_counter()
                await asyncio.gather(
                    *(
                        register(
                            c,
                            {"domain":
                             f"c{i}r{rnd}.bench.emy-10.joyent.us",
                             "type": "host"},
                            admin_ip="10.0.0.2",
                            hostname=f"host{i}",
                            settle_delay=0,
                        )
                        for i, c in enumerate(conc_clients)
                    )
                )
                if rnd >= 0:
                    rates.append(n_conc / (time.perf_counter() - t0))
        finally:
            for c in conc_clients:
                await c.close()
        throughput = sorted(rates)[len(rates) // 2]

        # ---- scale extras (round-2: prove the O(N) paths stay flat;
        # round-8: the 1k–10k-instance matrix, ISSUE 11) ----

        # Heartbeat over many owned znodes: one session, N ephemerals,
        # the agent's hot loop #1 stat fan-out.  The 10k sweep is the
        # matrix's deep end — skipped under BENCH_SMOKE (CI), where its
        # metric reports null ("unmeasurable in this environment").
        heartbeat_scale = {}
        scale_paths = {}
        for n in (100, 1000) if SMOKE else (100, 1000, 10000):
            base = f"/hbscale{n}"
            await client.mkdirp(base)
            paths = [f"{base}/e{i}" for i in range(n)]
            await _create_ephemerals(client, paths)
            scale_paths[n] = paths
            hb_iters = 5
            t0 = time.perf_counter()
            for _ in range(hb_iters):
                await client.heartbeat(paths)
            heartbeat_scale[n] = round(
                (time.perf_counter() - t0) * 1000.0 / hb_iters, 3
            )

        # Coalesced multi-service sweep (ISSUE 11 tentpole): the same
        # 1000 znodes probed as 100 services × 10 znodes through ONE
        # heartbeat_many flush — the wire shape the agent coalescer
        # produces for a multi-service host.
        svc_groups = [
            scale_paths[1000][i * 10 : (i + 1) * 10] for i in range(100)
        ]
        co_iters = 5
        t0 = time.perf_counter()
        for _ in range(co_iters):
            outcomes = await client.heartbeat_many(svc_groups)
            if any(outcomes):
                # Checked EVERY iteration: a failing sweep returns on a
                # different (typically faster) path, and folding it into
                # the timing would record a broken run as an improvement.
                raise RuntimeError(
                    "coalesced heartbeat sweep reported per-service "
                    f"errors: {[e for e in outcomes if e]!r}"
                )
        coalesced_ms = (time.perf_counter() - t0) * 1000.0 / co_iters

        # Live (uncached) resolve throughput: 100 concurrent resolver
        # coroutines over 4 observer sessions hammering a dedicated
        # single-host domain — the aggregate QPS ceiling of the live
        # read path (the cached path's QPS is measured separately).
        live_qps = await _live_resolve_qps(client, server)

        # 100 concurrent agents (the 1k-instance fleet shape: 100
        # sessions × 10 owned znodes), all heartbeating at once; value
        # is full agent sweeps per second.
        agents_qps = await _concurrent_agents(server, n_agents=100,
                                              znodes_each=10)

        # Resolution over a 50-instance service (the biggest realistic
        # Binder answer: a large stateless fleet behind one domain).
        await _register_fleet(client)
        t0 = time.perf_counter()
        for _ in range(iters):
            res_a = await binderview.resolve(observer, FLEET_DOMAIN, "A")
        fleet_a_ms = (time.perf_counter() - t0) * 1000.0 / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            res_srv = await binderview.resolve(
                observer, f"_http._tcp.{FLEET_DOMAIN}", "SRV"
            )
        fleet_srv_ms = (time.perf_counter() - t0) * 1000.0 / iters
        if len(res_a.answers) != 50 or len(res_srv.answers) != 50:
            raise RuntimeError(
                "fleet resolve did not see all 50 instances "
                f"(A={len(res_a.answers)} SRV={len(res_srv.answers)})"
            )

        # Cached resolves + coherence lag (ISSUE 4): the same fleet
        # served from the watch-coherent in-memory cache.
        cached = await _cached_metrics(client, observer, fleet_a_ms,
                                       fleet_srv_ms)

        # Watch fan-out: 50 sessions watching one node; time from a
        # write to the last notification arriving.  Median of 5 rounds —
        # a single ~1.5 ms shot is scheduler-noise-dominated the same way
        # the concurrency burst was (docs/PERF.md), and the gate pins
        # this metric.
        watchers = [
            await ZKClient([server.address]).connect() for _ in range(50)
        ]
        try:
            await client.put("/fanout", b"v0")
            # One persistent listener per watcher (client listeners are
            # not one-shot); each round re-arms the server-side watch and
            # resets the shared countdown.
            state = {"pending": 0, "notified": None}

            def on_event(_ev):
                state["pending"] -= 1
                if state["pending"] == 0:
                    state["notified"].set()

            for wcl in watchers:
                wcl.watch("/fanout", on_event)
            fanout_rounds = []
            for rnd in range(5):
                state["pending"] = len(watchers)
                state["notified"] = asyncio.Event()
                for wcl in watchers:
                    await wcl.get("/fanout", watch=True)
                t0 = time.perf_counter()
                await client.set_data("/fanout", f"v{rnd + 1}".encode())
                await asyncio.wait_for(state["notified"].wait(), timeout=10)
                fanout_rounds.append((time.perf_counter() - t0) * 1000.0)
            fanout_ms = sorted(fanout_rounds)[len(fanout_rounds) // 2]
        finally:
            for wcl in watchers:
                await wcl.close()

        # Sharded serve tier (ISSUE 12): the multi-process scaling
        # matrix.  Skipped under BENCH_SMOKE exactly like the 10k-znode
        # sweep — multi-process scaling numbers are meaningless on a
        # shared CI core, so the metrics report null ("unmeasurable in
        # this environment") and `make bench-sharded` exercises the
        # machinery separately.
        if SMOKE:
            sharded = {
                "sharded_resolve_qps_1_shards": None,
                "sharded_resolve_qps_2_shards": None,
                "sharded_resolve_qps_4_shards": None,
                "sharded_live_resolve_qps_4_shards": None,
                "sharded_resolve_qps_4_shards_traced": None,
                "sharded_trace_overhead_pct": None,
                "reshard_warm_handoff_ms": None,
                "overload_admitted_warm_p99_ms": None,
                "overload_shed_fastfail_p99_ms": None,
                "overload_capacity_qps": None,
                "overload_offered_x_capacity": None,
                "overload_sheds_total": None,
                "overload_storm_seed": None,
                "dns_udp_qps_4_shards": None,
                "dns_a_p99_us": None,
                "dns_nxdomain_p99_us": None,
                "dns_encode_cache_hit_ratio": None,
                "dns_storm_seed": None,
            }
        else:
            import tempfile

            with tempfile.TemporaryDirectory(prefix="shbench") as td:
                sharded = await _sharded_metrics(server, client, td)

        # Daemon RSS: the real deployed process (register + heartbeat
        # loop) measured from /proc after it finishes registering.
        daemon_rss_mb = await _daemon_rss_mb(server)

        return {
            "metric": "register_to_visible_ms",
            "value": round(register_ms, 2),
            "unit": "ms",
            "vs_baseline": round(BASELINE_FLOOR_MS / register_ms, 4),
            "extra": {
                "baseline": "reference floor: 1000ms mandated settle delay "
                "(lib/register.js:232-235) + ZK RPC time; reference "
                "publishes no benchmark numbers (BASELINE.md)",
                "pipeline_ms_no_settle": round(pipeline_ms, 3),
                "heartbeat_ms": round(heartbeat_ms, 3),
                "resolve_a_query_ms": round(resolve_ms, 3),
                "concurrent_registrations_per_s": round(throughput, 1),
                "znodes_per_registration": len(nodes),
                "heartbeat_ms_100_znodes": heartbeat_scale[100],
                "heartbeat_ms_1000_znodes": heartbeat_scale[1000],
                "heartbeat_ms_10000_znodes": heartbeat_scale.get(10000),
                "heartbeat_ms_1000_znodes_coalesced_100_services": round(
                    coalesced_ms, 3
                ),
                "live_resolve_qps": round(live_qps, 1),
                "concurrent_agents_100": round(agents_qps, 1),
                "resolve_a_ms_50_instances": round(fleet_a_ms, 3),
                "resolve_srv_ms_50_instances": round(fleet_srv_ms, 3),
                "watch_fanout_ms_50_watchers": round(fanout_ms, 3),
                "daemon_rss_mb": daemon_rss_mb,
                **cached,
                **sharded,
            },
        }
    finally:
        await observer.close()
        await client.close()
        await server.stop()


async def _bench_cached() -> dict:
    """``--cached-only``: the cached-resolve + coherence-lag slice.

    The hook behind ``make bench-cached`` (and the CI chaos job): stand
    up the 50-instance fleet, measure the live path briefly (the 10×
    comparison base), then run the full cached/coherence measurement.
    Prints the same one-JSON-line shape; never gated (the full-run
    metrics are absent by design — the cross-round gate belongs to
    ``python bench.py``).
    """
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    observer = await ZKClient([server.address]).connect()
    try:
        await _register_fleet(client)
        live_iters = 50
        t0 = time.perf_counter()
        for _ in range(live_iters):
            await binderview.resolve(observer, FLEET_DOMAIN, "A")
        live_a_ms = (time.perf_counter() - t0) * 1000.0 / live_iters
        t0 = time.perf_counter()
        for _ in range(live_iters):
            await binderview.resolve(
                observer, f"_http._tcp.{FLEET_DOMAIN}", "SRV"
            )
        live_srv_ms = (time.perf_counter() - t0) * 1000.0 / live_iters
        cached = await _cached_metrics(client, observer, live_a_ms,
                                       live_srv_ms)
        return {
            "metric": "resolve_a_cached_ms_50_instances",
            "value": cached["resolve_a_cached_ms_50_instances"],
            "unit": "ms",
            "extra": {
                "baseline": "live-read path measured in the same run; "
                "the cached path must be >=10x faster or this run fails",
                "resolve_a_ms_50_instances": round(live_a_ms, 3),
                "resolve_srv_ms_50_instances": round(live_srv_ms, 3),
                **cached,
            },
        }
    finally:
        await observer.close()
        await client.close()
        await server.stop()


async def _bench_overload() -> dict:
    """``--overload-only``: the ISSUE-17 p99-under-overload slice.

    The hook behind ``make overload-quick`` (and the CI chaos job):
    register the shard-bench domains, stand up the ARMORED 2-shard
    tier, measure capacity, and drive the seeded storm at ~5x it.
    Prints the one-JSON-line shape with the storm seed echoed (replay
    with BENCH_OVERLOAD_SEED=<seed>); never gated here — the
    cross-round gate on the p99 metrics belongs to ``python bench.py``.
    A timeout under armor fails the run inside _overload_metrics.
    """
    import tempfile

    seed = _overload_seed()
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    try:
        domains = await _register_shard_domains(
            client, n_domains=4 if SMOKE else 8,
            instances=5 if SMOKE else 10,
        )
        with tempfile.TemporaryDirectory(prefix="ovbench") as td:
            overload = await _overload_metrics(
                server, td, domains, seed, smoke=SMOKE,
            )
        print(
            f"bench: overload storm seed {seed} "
            f"(replay: BENCH_OVERLOAD_SEED={seed}) — "
            f"admitted warm p99 {overload['overload_admitted_warm_p99_ms']}"
            f"ms, shed fast-fail p99 "
            f"{overload['overload_shed_fastfail_p99_ms']}ms, "
            f"{overload['overload_sheds_total']} sheds at "
            f"{overload['overload_offered_x_capacity']}x capacity",
            file=sys.stderr,
        )
        return {
            "metric": "overload_admitted_warm_p99_ms",
            "value": overload["overload_admitted_warm_p99_ms"],
            "unit": "ms",
            "seed": seed,
            "extra": {
                "baseline": "armored tier under the seeded storm; the "
                "admitted-warm p99 and shed fast-fail p99 are gated "
                "cross-round by the full bench, and a timeout under "
                "armor fails this run outright",
                **overload,
            },
        }
    finally:
        await client.close()
        await server.stop()


async def _bench_dns() -> dict:
    """``--dns-only``: the ISSUE-19 DNS-frontend slice.

    The hook behind ``make dns-quick`` (and the CI chaos job): register
    the shard-bench domains, stand up the 4-shard SO_REUSEPORT DNS
    tier, and drive the seeded Zipf workload over real UDP packets.
    Prints the one-JSON-line shape with the seed echoed (replay with
    BENCH_DNS_SEED=<seed>); never gated here — the cross-round gate on
    the DNS metrics belongs to ``python bench.py``.  The encode-cache
    hit-ratio bound (>0.9) asserts inside _dns_metrics regardless; the
    within-25%-of-raw-sharded bound asserts only on the full
    (non-SMOKE) run, where both figures come off the same box in the
    full bench.
    """
    import tempfile

    seed = _dns_seed()
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    try:
        domains = await _register_shard_domains(
            client, n_domains=4 if SMOKE else 8,
            instances=5 if SMOKE else 10,
        )
        with tempfile.TemporaryDirectory(prefix="dnsbench") as td:
            dns = await _dns_metrics(server, td, domains, seed,
                                     smoke=SMOKE)
        print(
            f"bench: dns storm seed {seed} "
            f"(replay: BENCH_DNS_SEED={seed}) — "
            f"{dns['dns_udp_qps_4_shards']} qps over UDP, warm A p99 "
            f"{dns['dns_a_p99_us']}us, NXDOMAIN p99 "
            f"{dns['dns_nxdomain_p99_us']}us, encode-cache hit ratio "
            f"{dns['dns_encode_cache_hit_ratio']}",
            file=sys.stderr,
        )
        return {
            "metric": "dns_udp_qps_4_shards",
            "value": dns["dns_udp_qps_4_shards"],
            "unit": "qps",
            "seed": seed,
            "extra": {
                "baseline": "real-packet DNS over the SO_REUSEPORT "
                "4-shard tier under the seeded Zipf workload; the "
                "encode-cache hit ratio must exceed 0.9 or this run "
                "fails outright",
                **dns,
            },
        }
    finally:
        await client.close()
        await server.stop()


async def _bench_sharded() -> dict:
    """``--sharded-only``: the ISSUE-12 sharded-tier slice.

    The hook behind ``make bench-sharded`` (and the CI bench smoke leg,
    where BENCH_SMOKE=1 shrinks the workload): stand up the shard-bench
    domains and run the full scaling matrix + reshard measurement —
    including the in-process zero-error reshard check and (on >=4
    cores) the >=3x scaling bound.  Prints the one-JSON-line shape;
    never gated (the cross-round gate belongs to ``python bench.py``).
    """
    import tempfile

    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    try:
        with tempfile.TemporaryDirectory(prefix="shbench") as td:
            sharded = await _sharded_metrics(server, client, td,
                                             smoke=SMOKE)
        return {
            "metric": "sharded_resolve_qps_4_shards",
            "value": sharded["sharded_resolve_qps_4_shards"],
            "unit": "qps",
            "extra": {
                "baseline": "1-shard figure measured in the same run; "
                "on a >=4-core box 4 shards must deliver >=3x it "
                f"(this box: {os.cpu_count()} cores)",
                **sharded,
            },
        }
    finally:
        await client.close()
        await server.stop()


# ---- profiling (make profile) ----------------------------------------------

PROFILE_REPORT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "profile-report.txt"
)


async def _profile_loops() -> None:
    """The two hot loops the perf rounds attack, run long enough to
    profile: the warm cached resolve and the 1000-znode heartbeat sweep
    (solo + coalesced).  Stood up exactly like the bench proper."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    observer = await ZKClient([server.address]).connect()
    cache = None
    try:
        await _register_fleet(client)
        cache = ZKCache(observer)
        srv_name = f"_http._tcp.{FLEET_DOMAIN}"
        for _ in range(2000):
            await binderview.resolve(cache, FLEET_DOMAIN, "A")
            await binderview.resolve(cache, srv_name, "SRV")
        base = "/profile-hb"
        await client.mkdirp(base)
        paths = [f"{base}/e{i}" for i in range(1000)]
        await _create_ephemerals(client, paths)
        for _ in range(25):
            await client.heartbeat(paths)
        groups = [paths[i * 10 : (i + 1) * 10] for i in range(100)]
        for _ in range(25):
            await client.heartbeat_many(groups)
    finally:
        if cache is not None:
            cache.close()
        await observer.close()
        await client.close()
        await server.stop()


def run_profile(report_path: str = None) -> int:
    """``--profile`` (make profile): cProfile the cached-resolve and
    heartbeat bench loops, dump the top-25 cumulative report to
    profile-report.txt — so the next perf round starts from data, not
    guesses (ISSUE 11 satellite; uploaded as a CI artifact)."""
    import cProfile
    import io
    import pstats

    path = report_path or PROFILE_REPORT
    prof = cProfile.Profile()
    prof.runcall(asyncio.run, _profile_loops())
    out = io.StringIO()
    stats = pstats.Stats(prof, stream=out)
    stats.sort_stats("cumulative").print_stats(25)
    with open(path, "w", encoding="utf-8") as f:
        f.write(
            "# bench.py --profile: cached-resolve + heartbeat hot loops "
            "under cProfile\n# top 25 by cumulative time\n"
        )
        f.write(out.getvalue())
    print(f"bench: wrote {path}", file=sys.stderr)
    return 0


# ---- cross-round regression gate -------------------------------------------

BASELINE_PATH = os.environ.get(
    "BENCH_BASELINE_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "BENCH_BASELINE.json"),
)

HISTORY_PATH = os.environ.get(
    "BENCH_HISTORY_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "BENCH_HISTORY.json"),
)


def baseline_from_history(history: dict) -> dict:
    """Apply the governance rule: baseline = per-metric best across all
    recorded rounds, with ``headroom_pct`` of slack away from the best.

    Round-4 verdict #6: the baseline must be *generated* from the
    append-only history by rule, never hand-edited — `--repin` writes
    it, `--check-baseline` (run by `make check`) and the gate tests
    fail on any divergence.  Best-of (not worst-of) so a best-to-worst
    slide moves the measured value toward the floor instead of being
    absorbed by a floor pinned at the historical worst.
    """
    h = float(history["headroom_pct"]) / 100.0
    directions = history["directions"]
    metrics = {}
    for name, direction in directions.items():
        values = [
            r["metrics"][name]
            for r in history["rounds"]
            if name in r.get("metrics", {})
        ]
        if not values:
            raise ValueError(f"history has no values for metric {name!r}")
        best = min(values) if direction == "lower" else max(values)
        pinned = best * (1 + h) if direction == "lower" else best * (1 - h)
        metrics[name] = {"value": round(pinned, 4), "direction": direction}
    return {
        "comment": "GENERATED from BENCH_HISTORY.json by `python bench.py "
        "--repin` — do not hand-edit (make check verifies this file "
        "matches the history rule; record new results in the history "
        "instead). Rule: per-metric best across recorded rounds with "
        f"{history['headroom_pct']}% headroom away from the best; the "
        "gate then allows tolerance_pct beyond these values at runtime "
        "(BENCH_TOLERANCE_PCT to widen on slower hardware, BENCH_GATE=0 "
        "to disable, BENCH_BASELINE_PATH / BENCH_HISTORY_PATH to "
        "relocate).",
        "tolerance_pct": history["tolerance_pct"],
        "metrics": metrics,
    }


def load_history(path: str = None) -> dict:
    with open(path or HISTORY_PATH, encoding="utf-8") as f:
        return json.load(f)


def repin(history_path: str = None, baseline_path: str = None) -> None:
    baseline = baseline_from_history(load_history(history_path))
    with open(baseline_path or BASELINE_PATH, "w", encoding="utf-8") as f:
        json.dump(baseline, f, indent=2)
        f.write("\n")


def check_baseline(history_path: str = None, baseline_path: str = None) -> list:
    """Divergences between the checked-in baseline and rule(history)."""
    expected = baseline_from_history(load_history(history_path))
    actual = load_baseline(baseline_path)
    if actual is None:
        return ["BENCH_BASELINE.json is missing; run `python bench.py --repin`"]
    problems = []
    if actual.get("tolerance_pct") != expected["tolerance_pct"]:
        problems.append(
            f"tolerance_pct {actual.get('tolerance_pct')} != history's "
            f"{expected['tolerance_pct']}"
        )
    for name, spec in expected["metrics"].items():
        got = actual.get("metrics", {}).get(name)
        if got != spec:
            problems.append(f"{name}: baseline {got} != rule(history) {spec}")
    for name in actual.get("metrics", {}):
        if name not in expected["metrics"]:
            problems.append(f"{name}: in baseline but not in history")
    return problems


def flat_metrics(result: dict) -> dict:
    """Headline value + every numeric extra, as one {name: value} map."""
    flat = {result["metric"]: result["value"]}
    for key, val in result.get("extra", {}).items():
        if isinstance(val, (int, float)) and not isinstance(val, bool):
            flat[key] = val
    return flat


def load_baseline(path: str = None) -> "dict | None":
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def gate(
    result: dict,
    baseline: dict,
    tolerance_pct: "float | None" = None,
    declared_metrics: "dict | None" = BENCH_METRICS,
) -> list:
    """Compare a bench result against the pinned baseline.

    Returns a list of human-readable regression strings (empty = pass).
    A metric missing from the result counts as a regression — losing a
    measurement silently is how coverage rots.  Metrics whose measured
    value is None (e.g. daemon_rss_mb off-Linux) are skipped.

    ``declared_metrics`` is the runtime half of the bench-metric-drift
    contract (every emitted metric must be declared); it defaults to
    this bench's own map and MUST be passed as None by reusers with
    their own metric namespace — tools/slo.py's gate rides this
    function with SLO metric names that bench.py rightly never
    declares.
    """
    if tolerance_pct is None:
        raw = os.environ.get(
            "BENCH_TOLERANCE_PCT", baseline.get("tolerance_pct", 10)
        )
        try:
            tolerance_pct = float(raw)
        except (TypeError, ValueError):
            # A typo'd CI env value must read as a config error, not a
            # traceback (round-4 advisor finding).
            print(
                f"bench: invalid BENCH_TOLERANCE_PCT {raw!r}; "
                "expected a number",
                file=sys.stderr,
            )
            raise SystemExit(2)
    flat = flat_metrics(result)
    failures = []
    if declared_metrics is not None:
        for name in sorted(flat):
            if name not in declared_metrics:
                # The runtime half of the bench-metric-drift contract:
                # an emitted metric absent from the declared map means
                # the static diff (checklib) is checking a stale name
                # set.
                failures.append(
                    f"{name}: emitted but not declared in "
                    "bench.BENCH_METRICS"
                )
    for name, spec in baseline["metrics"].items():
        expected, direction = spec["value"], spec["direction"]
        measured = flat.get(name)
        if name in result.get("extra", {}) and result["extra"][name] is None:
            continue  # unmeasurable in this environment
        if measured is None:
            failures.append(f"{name}: missing from bench output")
            continue
        # Ratio-symmetric bounds: "X% worse" means the same factor in both
        # directions (lower-is-better may grow by 1+t, higher-is-better may
        # shrink by 1/(1+t)).  A subtractive bound for higher-is-better
        # would go non-positive at tolerance >= 100% and gate nothing.
        factor = 1 + tolerance_pct / 100.0
        if direction == "lower":
            limit = expected * factor
            if measured > limit:
                failures.append(
                    f"{name}: {measured} > {round(limit, 4)} "
                    f"(baseline {expected} +{tolerance_pct}%)"
                )
        else:
            limit = expected / factor
            if measured < limit:
                failures.append(
                    f"{name}: {measured} < {round(limit, 4)} "
                    f"(baseline {expected} /{factor})"
                )
    return failures


def best_of(a: dict, b: dict, baseline: dict) -> dict:
    """Per-metric best of two runs (direction-aware), for the retry pass."""
    fa, fb = flat_metrics(a), flat_metrics(b)
    best = {}
    for name, spec in baseline["metrics"].items():
        va, vb = fa.get(name), fb.get(name)
        if va is None or vb is None:
            best[name] = va if vb is None else vb
        elif spec["direction"] == "lower":
            best[name] = min(va, vb)
        else:
            best[name] = max(va, vb)
    return best


def main() -> int:
    if "--repin" in sys.argv[1:]:
        repin()
        print(f"bench: wrote {BASELINE_PATH} from {HISTORY_PATH}",
              file=sys.stderr)
        return 0
    if "--cached-only" in sys.argv[1:]:
        print(json.dumps(asyncio.run(_bench_cached())))
        return 0
    if "--sharded-only" in sys.argv[1:]:
        print(json.dumps(asyncio.run(_bench_sharded())))
        return 0
    if "--overload-only" in sys.argv[1:]:
        print(json.dumps(asyncio.run(_bench_overload())))
        return 0
    if "--dns-only" in sys.argv[1:]:
        print(json.dumps(asyncio.run(_bench_dns())))
        return 0
    if "--profile" in sys.argv[1:]:
        return run_profile()
    if "--check-baseline" in sys.argv[1:]:
        problems = check_baseline()
        for p in problems:
            print(f"bench: baseline drift: {p}", file=sys.stderr)
        if problems:
            print(
                "bench: BENCH_BASELINE.json does not match the history "
                "rule — record results in BENCH_HISTORY.json and run "
                "`python bench.py --repin` (never hand-edit the baseline)",
                file=sys.stderr,
            )
        return 1 if problems else 0
    result = asyncio.run(_bench())
    baseline = load_baseline()
    gate_on = os.environ.get("BENCH_GATE", "1") != "0" and baseline is not None
    failures = gate(result, baseline) if gate_on else []
    # Up to two retries: a contended box shows whole-run degradation
    # episodes (observed: 6 metrics 20-40% worse at once, clean a minute
    # later); the gate judges the per-metric best across runs, so noise
    # cannot fail a round while a real regression fails every run.  The
    # printed line stays one honest (the latest) run.
    best_view = result
    for attempt in range(2):
        if not failures:
            break
        print(
            f"bench: possible regression (attempt {attempt + 1}), "
            "retrying: " + "; ".join(failures),
            file=sys.stderr,
        )
        result = asyncio.run(_bench())
        merged = best_of(best_view, result, baseline)
        # Union of keys: a metric measured in an earlier run must stay in
        # the merged view even if the latest run's output omitted it —
        # best_of kept its value; dropping the key would turn it into a
        # spurious "missing from bench output" failure.
        extra = dict(result.get("extra", {}))
        for name, val in merged.items():
            if name != result["metric"] and val is not None:
                extra[name] = val
        best_view = {
            "metric": result["metric"],
            "value": merged.get(result["metric"], result["value"]),
            "extra": extra,
        }
        failures = gate(best_view, baseline)
    print(json.dumps(result))
    if failures:
        print("bench: REGRESSION vs BENCH_BASELINE.json:", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
