"""Transaction (multi) and sync support: client + in-process server.

The reference's zkplus stack predates ZooKeeper multi and never exposed
sync; the rebuild's transport covers the full 3.4 surface (zk/protocol.py
"multi" section).  These tests pin the atomicity contract end to end over
a real socket: all-or-nothing apply, per-op error codes on abort
(failing op's real code, RUNTIME_INCONSISTENCY for the rest), watch
delivery for applied ops, and ephemeral ownership of nodes created inside
a transaction.
"""

import asyncio

import pytest

from registrar_tpu.registration import register, unregister
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import MultiError, Op, ZKClient
from registrar_tpu.zk.jute import Reader, Writer
from registrar_tpu.zk import protocol as proto
from registrar_tpu.zk.protocol import CreateFlag, Err, Stat, ZKError


async def _pair():
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    return server, client


class TestMultiWire:
    """Round-trip of the multi records through jute (no server)."""

    def test_request_roundtrip(self):
        ops = [
            Op.create("/a", b"x"),
            Op.delete("/b", version=3),
            Op.set_data("/c", b"y", version=7),
            Op.check("/d", 2),
        ]
        w = Writer()
        proto.MultiRequest(ops=ops).write(w)
        parsed = proto.MultiRequest.read(Reader(w.to_bytes()))
        assert [(t, r) for t, r in parsed.ops] == ops

    def test_response_roundtrip(self):
        stat = Stat(*([0] * 11))
        results = [
            proto.CreateResponse(path="/a"),
            proto.DeleteResult(),
            proto.SetDataResponse(stat=stat),
            proto.CheckResult(),
        ]
        w = Writer()
        proto.MultiResponse(results=results).write(w)
        parsed = proto.MultiResponse.read(Reader(w.to_bytes()))
        assert parsed.results == results

    def test_error_response_roundtrip(self):
        results = [
            proto.ErrorResult(err=Err.NO_NODE),
            proto.ErrorResult(err=Err.RUNTIME_INCONSISTENCY),
        ]
        w = Writer()
        proto.MultiResponse(results=results).write(w)
        assert proto.MultiResponse.read(Reader(w.to_bytes())).results == results

    def test_disallowed_op_type_rejected(self):
        w = Writer()
        proto.MultiHeader(type=proto.OpCode.GET_DATA, done=False, err=-1).write(w)
        with pytest.raises(ValueError):
            proto.MultiRequest.read(Reader(w.to_bytes()))


class TestMultiApply:
    async def test_atomic_create_batch(self):
        server, client = await _pair()
        try:
            results = await client.multi(
                [
                    Op.create("/com", b""),
                    Op.create("/com/a", b"one"),
                    Op.create("/com/b", b"two", flags=CreateFlag.EPHEMERAL),
                ]
            )
            assert results == ["/com", "/com/a", "/com/b"]
            data, _ = await client.get("/com/a")
            assert data == b"one"
            assert (await client.stat("/com/b")).ephemeral_owner == client.session_id
        finally:
            await client.close()
            await server.stop()

    async def test_abort_applies_nothing(self):
        server, client = await _pair()
        try:
            await client.create("/exists", b"")
            with pytest.raises(MultiError) as excinfo:
                await client.multi(
                    [
                        Op.create("/fresh", b""),
                        Op.create("/exists", b""),  # NODE_EXISTS -> abort
                        Op.delete("/exists"),
                    ]
                )
            err = excinfo.value
            assert err.code == Err.NODE_EXISTS
            assert err.results == [
                Err.RUNTIME_INCONSISTENCY,
                Err.NODE_EXISTS,
                Err.RUNTIME_INCONSISTENCY,
            ]
            # nothing applied: /fresh absent, /exists still present
            assert await client.exists("/fresh") is None
            assert await client.exists("/exists") is not None
        finally:
            await client.close()
            await server.stop()

    async def test_check_guards_transaction(self):
        server, client = await _pair()
        try:
            await client.create("/guard", b"v0")
            stat = await client.stat("/guard")
            ok = await client.multi(
                [Op.check("/guard", stat.version), Op.set_data("/guard", b"v1")]
            )
            assert ok[0] is None and ok[1].version == stat.version + 1
            # stale check now aborts, and the write is not applied
            with pytest.raises(MultiError) as excinfo:
                await client.multi(
                    [
                        Op.check("/guard", stat.version),
                        Op.set_data("/guard", b"v2"),
                    ]
                )
            assert excinfo.value.code == Err.BAD_VERSION
            assert (await client.get("/guard"))[0] == b"v1"
        finally:
            await client.close()
            await server.stop()

    async def test_delete_then_recreate_same_path(self):
        # ops within one txn observe each other's effects
        server, client = await _pair()
        try:
            await client.create("/swap", b"old")
            results = await client.multi(
                [Op.delete("/swap"), Op.create("/swap", b"new")]
            )
            assert results == [None, "/swap"]
            assert (await client.get("/swap"))[0] == b"new"
        finally:
            await client.close()
            await server.stop()

    async def test_sequential_name_collision_aborts_atomically(self):
        # Regression: a sequential create whose derived name collides with
        # an existing node must abort the whole transaction at validation
        # time — earlier ops in the txn must not leak through.
        server, client = await _pair()
        try:
            await client.create("/p", b"")
            # Creating this node bumps /p's cversion 0 -> 1, so the next
            # sequential "b" create derives exactly this name.
            await client.create("/p/b0000000001", b"")
            assert (await client.stat("/p")).cversion == 1
            with pytest.raises(MultiError) as excinfo:
                await client.multi(
                    [
                        Op.create("/q", b""),
                        Op.create(
                            "/p/b", b"",
                            flags=CreateFlag.PERSISTENT_SEQUENTIAL,
                        ),
                    ]
                )
            assert excinfo.value.code == Err.NODE_EXISTS
            assert await client.exists("/q") is None  # nothing applied
        finally:
            await client.close()
            await server.stop()

    async def test_recreate_resets_sequential_counter(self):
        # Regression: delete+recreate of a parent inside one txn must
        # predict sequential children from the *fresh* node's cversion=0,
        # both for naming and for collision detection.
        server, client = await _pair()
        try:
            await client.create("/a", b"")
            await client.create("/a/pad", b"")  # cversion 1
            await client.unlink("/a/pad")  # cversion 2 (stale if inherited)
            results = await client.multi(
                [
                    Op.delete("/a"),
                    Op.create("/a", b""),
                    Op.create(
                        "/a/s", b"", flags=CreateFlag.PERSISTENT_SEQUENTIAL
                    ),
                ]
            )
            assert results[2] == "/a/s0000000000"
            # and the collision case: occupying the name the fresh counter
            # will derive next (explicit create bumps cversion 0 -> 1, so
            # the sequential op derives s0000000001) must abort cleanly,
            # applying nothing
            with pytest.raises(MultiError) as excinfo:
                await client.multi(
                    [
                        Op.delete("/a/s0000000000"),
                        Op.delete("/a"),
                        Op.create("/a", b""),
                        Op.create("/a/s0000000001", b""),
                        Op.create(
                            "/a/s", b"",
                            flags=CreateFlag.PERSISTENT_SEQUENTIAL,
                        ),
                    ]
                )
            assert excinfo.value.code == Err.NODE_EXISTS
            assert await client.exists("/a/s0000000000") is not None
        finally:
            await client.close()
            await server.stop()

    async def test_sequential_create_in_multi(self):
        server, client = await _pair()
        try:
            await client.create("/seq", b"")
            results = await client.multi(
                [
                    Op.create(
                        "/seq/n-", b"a", flags=CreateFlag.PERSISTENT_SEQUENTIAL
                    ),
                    Op.create(
                        "/seq/n-", b"b", flags=CreateFlag.PERSISTENT_SEQUENTIAL
                    ),
                ]
            )
            assert results == ["/seq/n-0000000000", "/seq/n-0000000001"]
            assert (await client.get(results[1]))[0] == b"b"
        finally:
            await client.close()
            await server.stop()

    async def test_version_checked_delete(self):
        server, client = await _pair()
        try:
            await client.create("/v", b"")
            with pytest.raises(MultiError) as excinfo:
                await client.multi([Op.delete("/v", version=9)])
            assert excinfo.value.code == Err.BAD_VERSION
            assert await client.exists("/v") is not None
        finally:
            await client.close()
            await server.stop()

    async def test_ephemeral_in_multi_dies_with_session(self):
        server, client = await _pair()
        try:
            await client.multi(
                [
                    Op.create("/e", b"", flags=CreateFlag.EPHEMERAL),
                ]
            )
            observer = await ZKClient([server.address]).connect()
            try:
                assert await observer.exists("/e") is not None
                await server.expire_session(client.session_id)
                await asyncio.sleep(0.05)
                assert await observer.exists("/e") is None
            finally:
                await observer.close()
        finally:
            await client.close()
            await server.stop()

    async def test_watches_fire_only_on_commit(self):
        server, client = await _pair()
        try:
            await client.create("/w", b"")
            events = []
            await client.get("/w", watch=True)
            client.watch("/w", events.append)

            # aborted txn -> no watch event
            with pytest.raises(MultiError):
                await client.multi(
                    [Op.set_data("/w", b"x"), Op.check("/w", 99)]
                )
            await asyncio.sleep(0.05)
            assert events == []

            # committed txn -> data watch fires
            await client.multi([Op.set_data("/w", b"x")])
            await asyncio.sleep(0.05)
            assert [e.path for e in events] == ["/w"]
        finally:
            await client.close()
            await server.stop()

    async def test_empty_multi_is_noop(self):
        server, client = await _pair()
        try:
            assert await client.multi([]) == []
        finally:
            await client.close()
            await server.stop()


class TestSync:
    async def test_sync_returns_path(self):
        server, client = await _pair()
        try:
            assert await client.sync("/") == "/"
            await client.create("/s", b"")
            assert await client.sync("/s") == "/s"
        finally:
            await client.close()
            await server.stop()


class TestAtomicUnregister:
    async def test_unregister_atomic_deletes_all(self):
        server, client = await _pair()
        try:
            nodes = await register(
                client,
                {
                    "domain": "1.moray.emy-10.joyent.us",
                    "type": "moray_host",
                    "aliases": ["alias.moray.emy-10.joyent.us"],
                },
                admin_ip="10.0.0.7",
                hostname="atomhost",
                settle_delay=0,
            )
            assert len(nodes) == 2
            await unregister(client, nodes, atomic=True)
            for n in nodes:
                assert await client.exists(n) is None
        finally:
            await client.close()
            await server.stop()

    async def test_unregister_atomic_all_or_nothing(self):
        server, client = await _pair()
        try:
            await client.mkdirp("/us/joyent")
            await client.create("/us/joyent/h1", b"")
            with pytest.raises(ZKError):
                await unregister(
                    client, ["/us/joyent/h1", "/us/joyent/missing"], atomic=True
                )
            # sequential mode would have deleted h1 before failing;
            # atomic mode must leave it untouched.
            assert await client.exists("/us/joyent/h1") is not None
        finally:
            await client.close()
            await server.stop()
