"""Registration pipeline integration tests.

Python rebuild of reference test/register.test.js — but hermetic: each test
registers against the in-process ZK server, then *reads back from ZooKeeper*
and asserts on the stored payloads, exactly like the reference's read-back
helper (reference test/register.test.js:26-66).  Also covers the reference's
known coverage gaps (SURVEY.md §4): multi-node unregister, aliases, ports
arrays.
"""


import pytest

from registrar_tpu.records import parse_payload
from registrar_tpu.registration import register, unregister, znode_paths
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import ZKError

DOMAIN = "unit.test.registrar"  # -> /registrar/test/unit
PATH = "/registrar/test/unit"


async def _pair():
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    return server, client


async def _register(client, registration, **kw):
    kw.setdefault("settle_delay", 0.01)
    kw.setdefault("hostname", "testhost")
    return await register(client, registration, **kw)


class TestRegister:
    async def test_host_only(self):
        # reference test/register.test.js:76-86
        server, client = await _pair()
        try:
            nodes = await _register(
                client, {"domain": DOMAIN, "type": "host"}, admin_ip="10.0.0.1"
            )
            assert nodes == [f"{PATH}/testhost"]
            st = await client.stat(nodes[0])
            assert st.ephemeral_owner == client.session_id  # really ephemeral
            data, _ = await client.get(nodes[0])
            assert parse_payload(data)["type"] == "host"
        finally:
            await client.close()
            await server.stop()

    async def test_admin_ip_payload_exact(self):
        # reference test/register.test.js:112-131 (deepEqual on payload)
        server, client = await _pair()
        try:
            nodes = await _register(
                client,
                {"domain": DOMAIN, "type": "host"},
                admin_ip="192.168.0.5",
            )
            data, _ = await client.get(nodes[0])
            assert data == (
                b'{"type":"host","address":"192.168.0.5",'
                b'"host":{"address":"192.168.0.5"}}'
            )
        finally:
            await client.close()
            await server.stop()

    async def test_admin_ip_and_ttl(self):
        # reference test/register.test.js:134-155
        server, client = await _pair()
        try:
            nodes = await _register(
                client,
                {"domain": DOMAIN, "type": "host", "ttl": 30},
                admin_ip="192.168.0.5",
            )
            data, _ = await client.get(nodes[0])
            assert parse_payload(data) == {
                "type": "host",
                "address": "192.168.0.5",
                "ttl": 30,
                "host": {"address": "192.168.0.5"},
            }
        finally:
            await client.close()
            await server.stop()

    async def test_service_record_written_persistent(self):
        # reference test/register.test.js:158-186
        server, client = await _pair()
        try:
            registration = {
                "domain": DOMAIN,
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            nodes = await _register(client, registration, admin_ip="10.9.9.9")
            # service node appended to the owned list
            assert nodes == [f"{PATH}/testhost", PATH]
            svc_data, svc_stat = await client.get(PATH)
            assert svc_stat.ephemeral_owner == 0  # persistent
            assert parse_payload(svc_data) == {
                "type": "service",
                "service": {
                    "type": "service",
                    "service": {
                        "srvce": "_http", "proto": "_tcp", "port": 80, "ttl": 60,
                    },
                },
            }
            # host record inherits the service port when no ports configured
            host_data, _ = await client.get(f"{PATH}/testhost")
            assert parse_payload(host_data)["load_balancer"]["ports"] == [80]
        finally:
            await client.close()
            await server.stop()

    async def test_aliases_create_additional_host_records(self):
        # coverage gap in the reference suite (SURVEY.md §4)
        server, client = await _pair()
        try:
            registration = {
                "domain": DOMAIN,
                "type": "load_balancer",
                "aliases": [f"a1.{DOMAIN}", f"a2.{DOMAIN}"],
            }
            nodes = await _register(client, registration, admin_ip="10.0.0.2")
            assert nodes == [
                f"{PATH}/testhost",
                f"{PATH}/a1",
                f"{PATH}/a2",
            ]
            for n in nodes:
                data, st = await client.get(n)
                assert st.ephemeral_owner == client.session_id
                assert parse_payload(data)["address"] == "10.0.0.2"
        finally:
            await client.close()
            await server.stop()

    async def test_explicit_ports_override_service_port(self):
        server, client = await _pair()
        try:
            registration = {
                "domain": DOMAIN,
                "type": "moray_host",
                "ports": [2020, 2021],
                "service": {
                    "type": "service",
                    "service": {"srvce": "_moray", "proto": "_tcp", "port": 2020},
                },
            }
            nodes = await _register(client, registration, admin_ip="10.0.0.3")
            data, _ = await client.get(f"{PATH}/testhost")
            assert parse_payload(data)["moray_host"]["ports"] == [2020, 2021]
        finally:
            await client.close()
            await server.stop()

    async def test_reregister_replaces_stale_entries(self):
        # the cleanup stage: re-running the pipeline over stale state works
        server, client = await _pair()
        try:
            registration = {"domain": DOMAIN, "type": "host"}
            await _register(client, registration, admin_ip="10.0.0.4")
            nodes = await _register(client, registration, admin_ip="10.0.0.5")
            data, _ = await client.get(nodes[0])
            assert parse_payload(data)["address"] == "10.0.0.5"
        finally:
            await client.close()
            await server.stop()

    async def test_service_config_not_mutated(self):
        server, client = await _pair()
        try:
            svc = {
                "type": "service",
                "service": {"srvce": "_s", "proto": "_t", "port": 1},
            }
            registration = {"domain": DOMAIN, "type": "load_balancer", "service": svc}
            await _register(client, registration, admin_ip="10.0.0.6")
            assert "ttl" not in svc["service"]  # reference mutates; we must not
        finally:
            await client.close()
            await server.stop()

    @pytest.mark.parametrize(
        "bad",
        [
            {},
            {"domain": DOMAIN},
            {"type": "host"},
            {"domain": DOMAIN, "type": "host", "ttl": "x"},
            {"domain": DOMAIN, "type": "host", "ports": "80"},
            {"domain": DOMAIN, "type": "host", "ports": [True]},
            {"domain": DOMAIN, "type": "host", "aliases": "a.b"},
            {"domain": DOMAIN, "type": "host", "service": {"type": "wrong"}},
        ],
    )
    async def test_validation(self, bad):
        server, client = await _pair()
        try:
            with pytest.raises(ValueError):
                await _register(client, bad, admin_ip="10.0.0.1")
        finally:
            await client.close()
            await server.stop()


class TestSettleDelay:
    async def test_register_waits_the_settle_delay(self):
        # The stage-2 pause is contract (reference lib/register.js:232-235
        # hard-codes 1 s "to be nice to watchers"); a register configured
        # with a 300 ms settle must take at least that long, and a
        # settle-free one must not.
        import time

        server, client = await _pair()
        try:
            reg = {"domain": "settle.test.registrar", "type": "host"}
            t0 = time.perf_counter()
            await register(
                client, reg, admin_ip="10.6.0.1", hostname="s1",
                settle_delay=0.3,
            )
            # Lower bound only: asyncio.sleep never returns early, so this
            # alone kills the settle-skip mutant; an upper bound on the
            # settle-free path would be a latent flake under CI load.
            assert time.perf_counter() - t0 >= 0.3
        finally:
            await client.close()
            await server.stop()

    def test_default_settle_is_the_reference_second(self):
        from registrar_tpu.registration import SETTLE_DELAY_S
        import inspect

        assert SETTLE_DELAY_S == 1.0
        # and it is the default, not an opt-in
        sig = inspect.signature(register)
        assert sig.parameters["settle_delay"].default == SETTLE_DELAY_S


class TestUnregister:
    async def test_unregister_deletes_all_nodes(self):
        # reference test/register.test.js:89-109, plus the multi-node case
        # the reference's early-cb bug (lib/register.js:281) left untested
        server, client = await _pair()
        try:
            registration = {
                "domain": DOMAIN,
                "type": "load_balancer",
                "aliases": [f"x.{DOMAIN}", f"y.{DOMAIN}"],
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            nodes = await _register(client, registration, admin_ip="10.1.1.1")
            assert len(nodes) == 4
            await unregister(client, nodes)
            for n in nodes:
                assert await client.exists(n) is None, n
        finally:
            await client.close()
            await server.stop()

    async def test_unregister_leaves_shared_service_node_for_siblings(self):
        # The production shape: N instances behind one domain.  One
        # instance deregistering owns [its host node, the domain node];
        # the domain node still holds the siblings' ephemerals, so the
        # delete is refused with NOT_EMPTY — that must read as success
        # (host record gone, shared service record intact), for both the
        # sequential walk and the atomic multi path.
        for atomic in (False, True):
            server, client = await _pair()
            sibling = await ZKClient([server.address]).connect()
            try:
                registration = {
                    "domain": DOMAIN,
                    "type": "load_balancer",
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp", "port": 80,
                        },
                    },
                }
                mine = await _register(
                    client, registration, admin_ip="10.1.1.1",
                    hostname="inst-a",
                )
                theirs = await _register(
                    sibling, registration, admin_ip="10.1.1.2",
                    hostname="inst-b",
                )
                await unregister(client, mine, atomic=atomic)
                # my host record is gone …
                assert await client.exists(f"{PATH}/inst-a") is None
                # … the sibling's host record and the service record stay
                assert await client.exists(f"{PATH}/inst-b") is not None
                svc_stat = await client.exists(PATH)
                assert svc_stat is not None and svc_stat.ephemeral_owner == 0
                # the last instance out deletes the service record too
                await unregister(sibling, theirs, atomic=atomic)
                assert await client.exists(PATH) is None
            finally:
                await sibling.close()
                await client.close()
                await server.stop()

    async def test_unregister_missing_node_raises(self):
        # parity: reference unregister does NOT ignore NO_NODE
        server, client = await _pair()
        try:
            with pytest.raises(ZKError) as ei:
                await unregister(client, ["/never/existed"])
            assert ei.value.name == "NO_NODE"
        finally:
            await client.close()
            await server.stop()


class TestZnodePaths:
    def test_paths(self):
        reg = {"domain": "1.moray.us-east.joyent.com", "aliases": ["a.b"]}
        assert znode_paths(reg, hostname="h0") == [
            "/com/joyent/us-east/moray/1/h0",
            "/b/a",
        ]
