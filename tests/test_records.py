"""Golden tests for the ZooKeeper data contract.

Every expected JSON byte-string below is transcribed from the reference
README's worked examples (reference README.md:443-757) or derived from the
reference's record-construction code (reference lib/register.js:132-171,
45-75).  These pin the Binder wire contract: if one of these breaks, the
rebuild no longer interoperates with the reference deployment.
"""

import json

import pytest

from registrar_tpu.records import (
    DEFAULT_SERVICE_TTL,
    HOST_RECORD_TYPES,
    default_address,
    domain_to_path,
    host_record,
    parse_payload,
    path_to_domain,
    payload_bytes,
    service_record,
)


class TestDomainToPath:
    def test_reference_docstring_example(self):
        # reference lib/register.js:36
        assert (
            domain_to_path("1.moray.us-east.joyent.com")
            == "/com/joyent/us-east/moray/1"
        )

    def test_readme_authcache_example(self):
        # reference README.md:466-469
        assert (
            domain_to_path("authcache.emy-10.joyent.us")
            == "/us/joyent/emy-10/authcache"
        )

    def test_lowercases(self):
        assert domain_to_path("FOO.Example.COM") == "/com/example/foo"

    def test_single_label(self):
        assert domain_to_path("localhost") == "/localhost"

    def test_rejects_empty(self):
        with pytest.raises(ValueError):
            domain_to_path("")

    def test_roundtrip(self):
        assert path_to_domain(domain_to_path("a.b.c")) == "a.b.c"


class TestHostRecord:
    def test_readme_redis_host_example(self):
        # reference README.md:536-545 (authcache instance host record)
        rec = host_record("redis_host", "172.27.10.62", ttl=30, ports=[6379])
        assert rec == json.loads(
            """
            {
              "type": "redis_host",
              "address": "172.27.10.62",
              "ttl": 30,
              "redis_host": {
                "address": "172.27.10.62",
                "ports": [ 6379 ]
              }
            }
            """
        )
        # byte-exact: JSON.stringify key order = insertion order
        assert payload_bytes(rec) == (
            b'{"type":"redis_host","address":"172.27.10.62","ttl":30,'
            b'"redis_host":{"address":"172.27.10.62","ports":[6379]}}'
        )

    def test_readme_load_balancer_example_no_ttl(self):
        # reference README.md:624-632: ttl absent entirely when unset
        rec = host_record("load_balancer", "172.27.10.72", ports=[80])
        assert payload_bytes(rec) == (
            b'{"type":"load_balancer","address":"172.27.10.72",'
            b'"load_balancer":{"address":"172.27.10.72","ports":[80]}}'
        )

    def test_no_ports_omits_ports_key(self):
        # JSON.stringify drops undefined members (reference
        # lib/register.js:139-155 leaves ports undefined when neither
        # registration.ports nor a service is configured).
        rec = host_record("host", "10.0.0.1")
        assert payload_bytes(rec) == (
            b'{"type":"host","address":"10.0.0.1",'
            b'"host":{"address":"10.0.0.1"}}'
        )
        assert "ttl" not in rec
        assert "ports" not in rec["host"]

    def test_service_type_rejected(self):
        with pytest.raises(ValueError):
            host_record("service", "10.0.0.1")

    def test_all_documented_types_roundtrip(self):
        for rtype in HOST_RECORD_TYPES:
            rec = host_record(rtype, "192.168.0.5", ports=[1, 2])
            parsed = parse_payload(payload_bytes(rec))
            assert parsed["type"] == rtype
            assert parsed[rtype]["ports"] == [1, 2]


class TestServiceRecord:
    def test_readme_http_example_with_default_ttl(self):
        # reference README.md:663-674 shows the stored record; the inner
        # ttl:60 default is injected at registration time
        # (reference lib/register.js:197).
        cfg = {
            "type": "service",
            "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
        }
        rec = service_record(cfg)
        assert payload_bytes(rec) == (
            b'{"type":"service","service":{"type":"service",'
            b'"service":{"srvce":"_http","proto":"_tcp","port":80,"ttl":60}}}'
        )
        # input config must not be mutated (the reference mutates it;
        # fixed here, SURVEY.md §7 "faithful-vs-fixed")
        assert "ttl" not in cfg["service"]

    def test_readme_redis_example_explicit_ttls(self):
        # reference README.md:509-521 (authcache service record with both
        # inner and outer ttl present)
        cfg = {
            "type": "service",
            "service": {"srvce": "_redis", "proto": "_tcp", "port": 6379, "ttl": 60},
            "ttl": 60,
        }
        rec = service_record(cfg)
        assert payload_bytes(rec) == (
            b'{"type":"service","service":{"type":"service",'
            b'"service":{"srvce":"_redis","proto":"_tcp","port":6379,"ttl":60},'
            b'"ttl":60}}'
        )

    def test_explicit_ttl_preserves_position(self):
        cfg = {
            "type": "service",
            "service": {"srvce": "_s", "ttl": 5, "proto": "_tcp", "port": 1},
        }
        rec = service_record(cfg)
        assert payload_bytes(rec) == (
            b'{"type":"service","service":{"type":"service",'
            b'"service":{"srvce":"_s","ttl":5,"proto":"_tcp","port":1}}}'
        )

    def test_default_ttl_constant(self):
        assert DEFAULT_SERVICE_TTL == 60

    @pytest.mark.parametrize(
        "bad",
        [
            {},
            {"type": "not-service", "service": {"srvce": "_s", "proto": "_t", "port": 1}},
            {"type": "service"},
            {"type": "service", "service": {"proto": "_t", "port": 1}},
            {"type": "service", "service": {"srvce": "_s", "port": 1}},
            {"type": "service", "service": {"srvce": "_s", "proto": "_t"}},
            {"type": "service", "service": {"srvce": "_s", "proto": "_t", "port": True}},
            {"type": "service", "service": {"srvce": "_s", "proto": "_t", "port": 1, "ttl": "x"}},
            {"type": "service", "service": {"srvce": "_s", "proto": "_t", "port": 1, "ttl": None}},
        ],
    )
    def test_validation_rejects(self, bad):
        with pytest.raises(ValueError):
            service_record(bad)


class TestDefaultAddressFallbacks:
    """The route-probe -> gethostbyname -> refuse ladder
    (reference lib/register.js:22-31; the reference crashes where this
    raises)."""

    class _FailingSocket:
        def __init__(self, *a, **kw):
            pass

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

        def connect(self, _addr):
            raise OSError("no route")

    def test_falls_back_to_hostname_resolution(self, monkeypatch):
        import registrar_tpu.records as records

        monkeypatch.setattr(records.socket, "socket", self._FailingSocket)
        monkeypatch.setattr(
            records.socket, "gethostbyname", lambda _h: "198.51.100.7"
        )
        assert default_address() == "198.51.100.7"

    def test_refuses_loopback_everywhere(self, monkeypatch):
        import registrar_tpu.records as records

        monkeypatch.setattr(records.socket, "socket", self._FailingSocket)
        monkeypatch.setattr(
            records.socket, "gethostbyname", lambda _h: "127.0.1.1"
        )
        with pytest.raises(RuntimeError):
            default_address()

    def test_raises_when_resolution_fails_too(self, monkeypatch):
        import registrar_tpu.records as records

        def boom(_h):
            raise OSError("no resolver")

        monkeypatch.setattr(records.socket, "socket", self._FailingSocket)
        monkeypatch.setattr(records.socket, "gethostbyname", boom)
        with pytest.raises(RuntimeError):
            default_address()


class TestInputTypeRejection:
    def test_domain_must_be_str(self):
        with pytest.raises(ValueError):
            domain_to_path(None)

    def test_host_record_type_must_be_nonempty_str(self):
        with pytest.raises(ValueError):
            host_record("", "10.0.0.1")
        with pytest.raises(ValueError):
            host_record(None, "10.0.0.1")


class TestDefaultAddress:
    def test_returns_non_loopback_ipv4_or_raises(self):
        # In an environment with no non-loopback interface this must raise
        # rather than poison DNS with 127.0.0.1.
        try:
            addr = default_address()
        except RuntimeError:
            return
        parts = addr.split(".")
        assert len(parts) == 4
        assert all(0 <= int(p) <= 255 for p in parts)
        assert not addr.startswith("127.")
