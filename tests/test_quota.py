"""Quota tests: zkCli.sh setquota/listquota/delquota parity.

Real ZooKeeper 3.4 stores soft quotas as znodes under /zookeeper/quota
(<path>/zookeeper_limits holds ``count=N,bytes=B``, the server maintains
usage in <path>/zookeeper_stats) and *logs* violations without ever
rejecting writes.  The test server implements the same contract
(registrar_tpu/testing/server.py), and zkcli ships the three commands.
"""

import asyncio
import os
import subprocess
import sys

from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.quota import parse_quota
from registrar_tpu.zk.client import ZKClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(server, *args):
    return subprocess.run(
        [sys.executable, "-m", "registrar_tpu.tools.zkcli",
         "-s", f"{server.host}:{server.port}", *args],
        cwd=REPO, capture_output=True, text=True, timeout=30,
        env={**os.environ, "PYTHONPATH": REPO},
    )


async def test_system_nodes_precreated():
    async with ZKServer() as server:
        client = await ZKClient([server.address]).connect()
        try:
            assert await client.exists("/zookeeper/quota") is not None
        finally:
            await client.close()


async def test_setquota_listquota_roundtrip():
    async with ZKServer() as server:
        client = await ZKClient([server.address]).connect()
        try:
            await client.mkdirp("/app/a")
            await client.put("/app/a/n1", b"12345")

            out = await asyncio.to_thread(
                _run_cli, server, "setquota", "-n", "5", "/app"
            )
            assert out.returncode == 0, out.stderr
            assert "count=5,bytes=-1" in out.stdout

            out = await asyncio.to_thread(_run_cli, server, "listquota", "/app")
            assert out.returncode == 0
            assert "Output quota for /app count=5,bytes=-1" in out.stdout
            # live usage: /app + /app/a + /app/a/n1, 5 data bytes
            assert "Output stat for /app count=3,bytes=5" in out.stdout
        finally:
            await client.close()


async def test_stats_track_writes():
    async with ZKServer() as server:
        client = await ZKClient([server.address]).connect()
        try:
            await client.mkdirp("/app")
            await asyncio.to_thread(
                _run_cli, server, "setquota", "-n", "100", "/app"
            )
            for i in range(3):
                await client.put(f"/app/c{i}", b"xx")
            stats, _ = await client.get("/zookeeper/quota/app/zookeeper_stats")
            usage = parse_quota(stats)
            assert usage == {"count": 4, "bytes": 6}
        finally:
            await client.close()


async def test_exceeding_count_logs_soft_warning():
    async with ZKServer() as server:
        client = await ZKClient([server.address]).connect()
        try:
            await client.mkdirp("/small")
            out = await asyncio.to_thread(
                _run_cli, server, "setquota", "-n", "2", "/small"
            )
            assert out.returncode == 0
            await client.put("/small/one", b"")
            assert server.quota_warnings == 0
            # Third node exceeds count=2 — write SUCCEEDS (soft limit)
            # but the server records the violation.
            await client.put("/small/two", b"")
            assert server.quota_warnings == 1
            assert await client.exists("/small/two") is not None
        finally:
            await client.close()


async def test_exceeding_bytes_logs_soft_warning():
    async with ZKServer() as server:
        client = await ZKClient([server.address]).connect()
        try:
            await client.mkdirp("/fat")
            await asyncio.to_thread(
                _run_cli, server, "setquota", "-b", "10", "/fat"
            )
            await client.put("/fat/blob", b"x" * 8)
            assert server.quota_warnings == 0
            await client.set_data("/fat/blob", b"x" * 11)
            assert server.quota_warnings == 1
        finally:
            await client.close()


async def test_nested_quota_rejected_both_directions():
    async with ZKServer() as server:
        client = await ZKClient([server.address]).connect()
        try:
            await client.mkdirp("/top/mid/leaf")
            assert (await asyncio.to_thread(
                _run_cli, server, "setquota", "-n", "10", "/top/mid"
            )).returncode == 0

            out = await asyncio.to_thread(
                _run_cli, server, "setquota", "-n", "5", "/top/mid/leaf"
            )
            assert out.returncode == 1
            assert "already has a quota" in out.stderr

            out = await asyncio.to_thread(
                _run_cli, server, "setquota", "-n", "50", "/top"
            )
            assert out.returncode == 1
            assert "already has a quota" in out.stderr

            # Updating the SAME path is allowed (not "nesting").
            out = await asyncio.to_thread(
                _run_cli, server, "setquota", "-b", "99", "/top/mid"
            )
            assert out.returncode == 0
            assert "count=10,bytes=99" in out.stdout
        finally:
            await client.close()


async def test_delquota_dimension_and_full():
    async with ZKServer() as server:
        client = await ZKClient([server.address]).connect()
        try:
            await client.mkdirp("/q")
            await asyncio.to_thread(
                _run_cli, server, "setquota", "-n", "7", "-b", "70", "/q"
            )

            out = await asyncio.to_thread(
                _run_cli, server, "delquota", "-n", "/q"
            )
            assert out.returncode == 0
            assert "count=-1,bytes=70" in out.stdout

            out = await asyncio.to_thread(_run_cli, server, "delquota", "/q")
            assert out.returncode == 0
            out = await asyncio.to_thread(_run_cli, server, "listquota", "/q")
            assert out.returncode == 1
            assert "does not exist" in out.stdout
            # and violations no longer tick
            before = server.quota_warnings
            for i in range(10):
                await client.put(f"/q/n{i}", b"data")
            assert server.quota_warnings == before
        finally:
            await client.close()


async def test_registration_traffic_unaffected_by_quota_machinery():
    # The daemon's paths never touch /zookeeper; a quota'd domain subtree
    # still registers fine (soft limits never reject writes).
    from registrar_tpu.registration import register

    async with ZKServer() as server:
        client = await ZKClient([server.address]).connect()
        try:
            await client.mkdirp("/us/test")
            await asyncio.to_thread(
                _run_cli, server, "setquota", "-n", "1", "/us"
            )
            nodes = await register(
                client,
                {"domain": "quotad.test.us", "type": "host"},
                admin_ip="10.9.9.9", hostname="h1", settle_delay=0,
            )
            for n in nodes:
                assert await client.exists(n) is not None
            assert server.quota_warnings > 0  # soft-flagged, not blocked
        finally:
            await client.close()


def test_parse_quota_garbled_fields_read_as_unlimited():
    from registrar_tpu.zk.quota import parse_quota

    assert parse_quota(b"count=abc,bytes=") == {"count": -1, "bytes": -1}
    assert parse_quota(b"") == {"count": -1, "bytes": -1}
    assert parse_quota(b"count=3,junk=9,bytes=7") == {"count": 3, "bytes": 7}
