"""Unit tests for the backoff policy (reference `backoff` library parity).

The delay schedule and both shipped policies mirror the reference
exactly (lib/zk.js:38-42 heartbeat, lib/zk.js:97-101 connect;
BASELINE.md) — pinned directly here rather than only through the
integration suites that ride on them.
"""

import asyncio
import itertools
import math
import random

import pytest

from registrar_tpu.retry import (
    CONNECT_RETRY,
    HEARTBEAT_RETRY,
    RECONNECT_RETRY,
    RetryPolicy,
    call_with_backoff,
    is_transient,
)
from registrar_tpu.zk.protocol import Err, ZKError


class TestDelaySchedule:
    def test_exponential_doubling_capped(self):
        p = RetryPolicy(max_attempts=10, initial_delay=1.0, max_delay=30.0)
        assert [p.delay(a) for a in range(7)] == [1, 2, 4, 8, 16, 30, 30]

    def test_reference_policies(self):
        assert (HEARTBEAT_RETRY.max_attempts,
                HEARTBEAT_RETRY.initial_delay,
                HEARTBEAT_RETRY.max_delay) == (5, 1.0, 30.0)
        assert CONNECT_RETRY.max_attempts == math.inf
        assert (CONNECT_RETRY.initial_delay, CONNECT_RETRY.max_delay) == (1.0, 90.0)


class TestDecorrelatedJitter:
    def test_schedule_stays_inside_the_envelope(self):
        # Every jittered delay must respect the same [initial, max]
        # envelope operators budget for with the plain schedule.
        p = RetryPolicy(
            max_attempts=math.inf, initial_delay=1.0, max_delay=30.0,
            jitter="decorrelated",
        )
        delays = list(itertools.islice(p.schedule(random.Random(42)), 200))
        assert all(1.0 <= d <= 30.0 for d in delays)
        # ... and must actually vary (the whole point): a lockstep fleet
        # would produce one repeated value.
        assert len({round(d, 6) for d in delays}) > 50

    def test_seeded_schedules_are_reproducible(self):
        p = RetryPolicy(jitter="decorrelated")
        a = list(itertools.islice(p.schedule(random.Random(7)), 20))
        b = list(itertools.islice(p.schedule(random.Random(7)), 20))
        assert a == b

    def test_two_clients_decorrelate(self):
        # The thundering-herd property: two workers restarting together
        # must not share a delay schedule.
        p = RetryPolicy(jitter="decorrelated")
        a = list(itertools.islice(p.schedule(random.Random(1)), 20))
        b = list(itertools.islice(p.schedule(random.Random(2)), 20))
        assert a != b

    def test_none_jitter_schedule_matches_delay(self):
        p = RetryPolicy(max_attempts=10, initial_delay=1.0, max_delay=30.0)
        assert list(itertools.islice(p.schedule(), 7)) == [
            p.delay(a) for a in range(7)
        ]

    def test_invalid_jitter_mode_rejected(self):
        with pytest.raises(ValueError):
            RetryPolicy(jitter="full")

    def test_reconnect_policy_adopts_jitter(self):
        # The default reconnect policy keeps the reference's 1-90 s
        # envelope but jitters inside it (ISSUE 2 satellite); the initial
        # connect keeps the reference's exact doubling.
        assert RECONNECT_RETRY.max_attempts == math.inf
        assert (RECONNECT_RETRY.initial_delay, RECONNECT_RETRY.max_delay) == (
            1.0, 90.0,
        )
        assert RECONNECT_RETRY.jitter == "decorrelated"
        assert CONNECT_RETRY.jitter == "none"

    async def test_call_with_backoff_draws_from_jittered_schedule(self):
        attempts = []

        async def fn():
            attempts.append(1)
            if len(attempts) < 3:
                raise RuntimeError("flaky")
            return "ok"

        p = RetryPolicy(
            max_attempts=5, initial_delay=0.001, max_delay=0.005,
            jitter="decorrelated",
        )
        delays = []
        out = await call_with_backoff(
            fn, p,
            on_backoff=lambda a, d, e: delays.append(d),
            rng=random.Random(3),
        )
        assert out == "ok"
        expected = list(itertools.islice(p.schedule(random.Random(3)), 2))
        assert delays == expected


class TestIsTransient:
    def test_connection_loss_and_op_timeout_are_transient(self):
        assert is_transient(ZKError(Err.CONNECTION_LOSS))
        assert is_transient(ZKError(Err.OPERATION_TIMEOUT))
        assert is_transient(ConnectionResetError())
        assert is_transient(asyncio.TimeoutError())
        assert is_transient(OSError(113, "no route to host"))

    def test_session_expiry_and_semantic_errors_are_fatal(self):
        from registrar_tpu.zk.client import SessionExpiredError

        assert not is_transient(SessionExpiredError())
        assert not is_transient(ZKError(Err.SESSION_EXPIRED))
        assert not is_transient(ZKError(Err.NO_NODE))
        assert not is_transient(ZKError(Err.NODE_EXISTS))
        assert not is_transient(ZKError(Err.NO_AUTH))
        assert not is_transient(ValueError("bad config"))


class TestCallWithBackoff:
    async def test_succeeds_first_try_without_sleeping(self):
        calls = []

        async def fn():
            calls.append(1)
            return "ok"

        assert await call_with_backoff(fn, HEARTBEAT_RETRY) == "ok"
        assert len(calls) == 1

    async def test_retries_then_succeeds(self):
        attempts = []

        async def fn():
            attempts.append(1)
            if len(attempts) < 3:
                raise RuntimeError("flaky")
            return "recovered"

        fast = RetryPolicy(max_attempts=5, initial_delay=0.001, max_delay=0.002)
        backoffs = []
        out = await call_with_backoff(
            fn, fast, on_backoff=lambda a, d, e: backoffs.append((a, d))
        )
        assert out == "recovered"
        assert len(attempts) == 3
        # on_backoff fired before each sleep, with the schedule's delays
        assert backoffs == [(0, 0.001), (1, 0.002)]

    async def test_exhausts_attempts_and_raises_last_error(self):
        attempts = []

        async def fn():
            attempts.append(1)
            raise RuntimeError(f"boom {len(attempts)}")

        fast = RetryPolicy(max_attempts=3, initial_delay=0.001, max_delay=0.002)
        with pytest.raises(RuntimeError) as exc:
            await call_with_backoff(fn, fast)
        assert len(attempts) == 3  # max_attempts total calls, not retries
        assert "boom 3" in str(exc.value)  # the LAST error propagates

    async def test_non_retryable_error_is_fatal_immediately(self):
        attempts = []

        async def fn():
            attempts.append(1)
            raise ValueError("fatal")

        with pytest.raises(ValueError):
            await call_with_backoff(
                fn,
                RetryPolicy(max_attempts=5, initial_delay=0.001),
                retryable=lambda e: not isinstance(e, ValueError),
            )
        assert len(attempts) == 1

    async def test_cancellation_aborts_the_loop(self):
        started = asyncio.Event()

        async def fn():
            started.set()
            raise RuntimeError("always failing")

        task = asyncio.ensure_future(
            call_with_backoff(
                fn, RetryPolicy(max_attempts=math.inf, initial_delay=30.0)
            )
        )
        await started.wait()
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
