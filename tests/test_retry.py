"""Unit tests for the backoff policy (reference `backoff` library parity).

The delay schedule and both shipped policies mirror the reference
exactly (lib/zk.js:38-42 heartbeat, lib/zk.js:97-101 connect;
BASELINE.md) — pinned directly here rather than only through the
integration suites that ride on them.
"""

import asyncio
import math

import pytest

from registrar_tpu.retry import (
    CONNECT_RETRY,
    HEARTBEAT_RETRY,
    RetryPolicy,
    call_with_backoff,
)


class TestDelaySchedule:
    def test_exponential_doubling_capped(self):
        p = RetryPolicy(max_attempts=10, initial_delay=1.0, max_delay=30.0)
        assert [p.delay(a) for a in range(7)] == [1, 2, 4, 8, 16, 30, 30]

    def test_reference_policies(self):
        assert (HEARTBEAT_RETRY.max_attempts,
                HEARTBEAT_RETRY.initial_delay,
                HEARTBEAT_RETRY.max_delay) == (5, 1.0, 30.0)
        assert CONNECT_RETRY.max_attempts == math.inf
        assert (CONNECT_RETRY.initial_delay, CONNECT_RETRY.max_delay) == (1.0, 90.0)


class TestCallWithBackoff:
    async def test_succeeds_first_try_without_sleeping(self):
        calls = []

        async def fn():
            calls.append(1)
            return "ok"

        assert await call_with_backoff(fn, HEARTBEAT_RETRY) == "ok"
        assert len(calls) == 1

    async def test_retries_then_succeeds(self):
        attempts = []

        async def fn():
            attempts.append(1)
            if len(attempts) < 3:
                raise RuntimeError("flaky")
            return "recovered"

        fast = RetryPolicy(max_attempts=5, initial_delay=0.001, max_delay=0.002)
        backoffs = []
        out = await call_with_backoff(
            fn, fast, on_backoff=lambda a, d, e: backoffs.append((a, d))
        )
        assert out == "recovered"
        assert len(attempts) == 3
        # on_backoff fired before each sleep, with the schedule's delays
        assert backoffs == [(0, 0.001), (1, 0.002)]

    async def test_exhausts_attempts_and_raises_last_error(self):
        attempts = []

        async def fn():
            attempts.append(1)
            raise RuntimeError(f"boom {len(attempts)}")

        fast = RetryPolicy(max_attempts=3, initial_delay=0.001, max_delay=0.002)
        with pytest.raises(RuntimeError) as exc:
            await call_with_backoff(fn, fast)
        assert len(attempts) == 3  # max_attempts total calls, not retries
        assert "boom 3" in str(exc.value)  # the LAST error propagates

    async def test_non_retryable_error_is_fatal_immediately(self):
        attempts = []

        async def fn():
            attempts.append(1)
            raise ValueError("fatal")

        with pytest.raises(ValueError):
            await call_with_backoff(
                fn,
                RetryPolicy(max_attempts=5, initial_delay=0.001),
                retryable=lambda e: not isinstance(e, ValueError),
            )
        assert len(attempts) == 1

    async def test_cancellation_aborts_the_loop(self):
        started = asyncio.Event()

        async def fn():
            started.set()
            raise RuntimeError("always failing")

        task = asyncio.ensure_future(
            call_with_backoff(
                fn, RetryPolicy(max_attempts=math.inf, initial_delay=30.0)
            )
        )
        await started.wait()
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
