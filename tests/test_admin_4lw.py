"""Four-letter-word admin interface: server responder + zkcli admin command.

Real ZooKeeper answers connection-less admin probes (ruok, srvr, stat,
mntr, cons, dump, wchs, isro) on the client port; operator runbooks use
them as the standard ensemble-health checks alongside zkCli.sh (the
workflow the reference's README "Debugging Notes" documents).  The test
server mirrors that, so ops tooling can be exercised hermetically.
"""

import asyncio
import os
import subprocess
import sys

from registrar_tpu.registration import register
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


async def _probe(server, word: str) -> str:
    reader, writer = await asyncio.open_connection(server.host, server.port)
    writer.write(word.encode())
    await writer.drain()
    out = await asyncio.wait_for(reader.read(1 << 20), timeout=5)
    writer.close()
    return out.decode()


class TestFourLetterWords:
    async def test_ruok_imok(self):
        async with ZKServer() as server:
            assert await _probe(server, "ruok") == "imok"

    async def test_isro_rw(self):
        async with ZKServer() as server:
            assert await _probe(server, "isro") == "rw"

    async def test_srvr_fields(self):
        async with ZKServer() as server:
            client = await ZKClient([server.address]).connect()
            try:
                await client.create("/x", b"abc")
                out = await _probe(server, "srvr")
            finally:
                await client.close()
            assert "Zookeeper version:" in out
            assert "Mode: standalone" in out
            # root + /x + the pre-created /zookeeper + /zookeeper/quota
            # system nodes (real ZK counts them in srvr too)
            assert "Node count: 4" in out
            assert "Zxid: 0x1" in out

    async def test_stat_lists_clients(self):
        async with ZKServer() as server:
            client = await ZKClient([server.address]).connect()
            try:
                out = await _probe(server, "stat")
                assert "Clients:" in out
                assert f"sid=0x{client.session_id:x}" in out
            finally:
                await client.close()

    async def test_mntr_counts_ephemerals_and_watches(self):
        async with ZKServer() as server:
            client = await ZKClient([server.address]).connect()
            try:
                await register(
                    client,
                    {"domain": "mntr.test.us", "type": "host"},
                    admin_ip="10.0.0.9",
                    hostname="mhost",
                    settle_delay=0,
                )
                await client.get("/us/test/mntr/mhost", watch=True)
                out = await _probe(server, "mntr")
                fields = dict(
                    line.split("\t", 1) for line in out.splitlines() if line
                )
                assert fields["zk_server_state"] == "standalone"
                assert fields["zk_ephemerals_count"] == "1"
                assert fields["zk_watch_count"] == "1"
                assert int(fields["zk_znode_count"]) >= 4
                assert int(fields["zk_packets_received"]) > 0
            finally:
                await client.close()

    async def test_dump_lists_sessions_with_ephemerals(self):
        async with ZKServer() as server:
            client = await ZKClient([server.address]).connect()
            try:
                await client.mkdirp("/d")
                from registrar_tpu.zk.protocol import CreateFlag

                await client.create("/d/e", b"", CreateFlag.EPHEMERAL)
                out = await _probe(server, "dump")
                assert f"0x{client.session_id:x}" in out
                assert "\t/d/e" in out
            finally:
                await client.close()

    async def test_wchs_summarizes_watches(self):
        async with ZKServer() as server:
            client = await ZKClient([server.address]).connect()
            try:
                await client.create("/w", b"")
                await client.get("/w", watch=True)
                await client.get_children("/", watch=True)
                out = await _probe(server, "wchs")
                assert "connections watching 2 paths" in out
                assert "Total watches:2" in out
            finally:
                await client.close()

    async def test_wchc_and_wchp_group_watches(self):
        async with ZKServer() as server:
            client = await ZKClient([server.address]).connect()
            try:
                await client.create("/w", b"")
                await client.get("/w", watch=True)
                await client.get_children("/", watch=True)

                by_conn = await _probe(server, "wchc")
                assert f"0x{client.session_id:x}" in by_conn
                assert "\t/w" in by_conn

                by_path = await _probe(server, "wchp")
                assert "/w" in by_path.splitlines()
                assert f"\t0x{client.session_id:x}" in by_path
            finally:
                await client.close()

    async def test_envi_and_conf(self):
        async with ZKServer() as server:
            envi = await _probe(server, "envi")
            assert envi.startswith("Environment:")
            assert "zookeeper.version=" in envi
            assert "os.name=" in envi

            conf = await _probe(server, "conf")
            assert f"clientPort={server.port}" in conf
            assert "maxSessionTimeout=" in conf
            assert "tickTime=" in conf

    async def test_srvr_zxid_exposes_replication_lag(self):
        # Real followers report their own lastProcessedZxid: `admin srvr`
        # against each member is how an operator SEES a lagging follower
        # (docs/OPERATIONS.md) — the zxid must come from the member's
        # read view, and the node count from its applied tree.
        from registrar_tpu.testing.server import ZKEnsemble

        def zxid_of(srvr_text: str) -> int:
            line = next(
                ln for ln in srvr_text.splitlines() if ln.startswith("Zxid:")
            )
            return int(line.split()[1], 16)

        async with ZKEnsemble(2) as ens:
            writer = await ZKClient([ens.addresses[0]]).connect()
            try:
                await writer.create("/lagstat", b"")
                ens.set_lag(1, 60_000)
                await writer.create("/lagstat/extra", b"")  # freezes member 1
                fresh = await _probe(ens.servers[0], "srvr")
                stale = await _probe(ens.servers[1], "srvr")
                assert zxid_of(fresh) > zxid_of(stale)
                # the laggard's node count is its applied view's
                fresh_nodes = next(
                    ln for ln in fresh.splitlines()
                    if ln.startswith("Node count:")
                )
                stale_nodes = next(
                    ln for ln in stale.splitlines()
                    if ln.startswith("Node count:")
                )
                assert fresh_nodes != stale_nodes
                # sync through the laggard catches it up; srvr agrees
                reader = await ZKClient([ens.addresses[1]]).connect()
                try:
                    await reader.sync("/")
                finally:
                    await reader.close()
                caught_up = await _probe(ens.servers[1], "srvr")
                assert zxid_of(caught_up) == zxid_of(fresh)
            finally:
                await writer.close()

    async def test_admin_probe_does_not_disturb_sessions(self):
        # A 4lw probe is a throwaway connection: existing ZK sessions and
        # the protocol path must be unaffected.
        async with ZKServer() as server:
            client = await ZKClient([server.address]).connect()
            try:
                await client.create("/alive", b"")
                await _probe(server, "ruok")
                await _probe(server, "mntr")
                assert await client.exists("/alive") is not None
                assert client.connected
            finally:
                await client.close()


class TestZkCliAdmin:
    async def test_zkcli_admin_ruok(self):
        async with ZKServer() as server:
            out = await asyncio.to_thread(
                subprocess.run,
                [
                    sys.executable, "-m", "registrar_tpu.tools.zkcli",
                    "-s", f"{server.host}:{server.port}", "admin", "ruok",
                ],
                cwd=REPO, capture_output=True, text=True, timeout=30,
                env={**os.environ, "PYTHONPATH": REPO},
            )
            assert out.returncode == 0
            assert out.stdout.strip() == "imok"

    async def test_zkcli_admin_unreachable_server_fails(self):
        out = await asyncio.to_thread(
            subprocess.run,
            [
                sys.executable, "-m", "registrar_tpu.tools.zkcli",
                "-s", "127.0.0.1:1", "admin", "ruok",
            ],
            cwd=REPO, capture_output=True, text=True, timeout=30,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert out.returncode == 1


class TestConsAndDumpTree:
    async def test_cons_lists_connections(self):
        async with ZKServer() as server:
            client = await ZKClient([server.address]).connect()
            try:
                out = await _probe(server, "cons")
                assert f"sid=0x{client.session_id:x}" in out
            finally:
                await client.close()

    async def test_dump_tree_helper_maps_subtree(self):
        async with ZKServer() as server:
            client = await ZKClient([server.address]).connect()
            try:
                await client.mkdirp("/a/b")
                await client.put("/a/b/leaf", b"v")
                tree = server.dump_tree("/a")
                assert tree["/a/b/leaf"] == b"v"
                assert "/a/b" in tree
                assert server.dump_tree("/absent") == {}
            finally:
                await client.close()
