"""Opt-in interop tests against a real ZooKeeper ensemble.

The reference's integration tests target a live ZooKeeper selected via
``ZK_HOST``/``ZK_PORT`` env vars (reference test/helper.js:57-62).  The
rebuild's suite is hermetic by default, but wire-protocol interop with
real ZooKeeper still matters: set ``ZK_HOST`` (and optionally
``ZK_PORT``) to run this module against it, e.g.::

    ZK_HOST=127.0.0.1 ZK_PORT=2181 python -m pytest tests/test_real_zk.py

Skipped automatically when ``ZK_HOST`` is unset.
"""

import os
import uuid

import pytest

from registrar_tpu.records import parse_payload
from registrar_tpu.registration import register, unregister
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zk.protocol import CreateFlag

pytestmark = pytest.mark.skipif(
    not os.environ.get("ZK_HOST"),
    reason="set ZK_HOST (and optionally ZK_PORT) to run real-ZooKeeper interop tests",
)


def _servers():
    return [(os.environ["ZK_HOST"], int(os.environ.get("ZK_PORT", "2181")))]


class TestRealZooKeeper:
    async def test_connect_and_roundtrip(self):
        client = await ZKClient(_servers()).connect()
        try:
            base = f"/registrar-interop-{uuid.uuid4().hex[:8]}"
            await client.mkdirp(base)
            path = await client.create(
                f"{base}/node", b'{"k":"v"}', CreateFlag.EPHEMERAL
            )
            data, stat = await client.get(path)
            assert data == b'{"k":"v"}'
            assert stat.ephemeral_owner == client.session_id
            assert await client.get_children(base) == ["node"]
            await client.unlink(path)
            await client.unlink(base)
        finally:
            await client.close()

    async def test_register_unregister_against_real_zk(self):
        client = await ZKClient(_servers()).connect()
        try:
            domain = f"interop-{uuid.uuid4().hex[:8]}.test.registrar"
            registration = {
                "domain": domain,
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            }
            nodes = await register(
                client, registration, admin_ip="10.250.0.1",
                hostname="interophost", settle_delay=0.05,
            )
            for n in nodes:
                st = await client.stat(n)
                data, _ = await client.get(n)
                assert parse_payload(data)["type"] in ("load_balancer", "service")
            await unregister(client, nodes)
            for n in nodes:
                assert await client.exists(n) is None
            # clean the persistent directory chain we created
            for p in sorted(
                {n.rsplit("/", 1)[0] for n in nodes}, key=len, reverse=True
            ):
                while p and p != "/":
                    try:
                        await client.unlink(p)
                    except Exception:  # noqa: BLE001 - shared parents may remain
                        break
                    p = p.rsplit("/", 1)[0]
        finally:
            await client.close()

    async def test_multi_against_real_zk(self):
        from registrar_tpu.zk.client import Op

        client = await ZKClient(_servers()).connect()
        try:
            base = f"/registrar-interop-multi-{uuid.uuid4().hex[:8]}"
            results = await client.multi(
                [
                    Op.create(base, b""),
                    Op.create(f"{base}/a", b"one"),
                    Op.set_data(f"{base}/a", b"two"),
                ]
            )
            assert results[0] == base and results[1] == f"{base}/a"
            assert (await client.get(f"{base}/a"))[0] == b"two"
            # aborted txn applies nothing (real ZK may report per-op codes
            # in the body — MultiError — or just the header error; both
            # surface as ZKError)
            from registrar_tpu.zk.protocol import ZKError

            with pytest.raises(ZKError):
                await client.multi(
                    [
                        Op.delete(f"{base}/a"),
                        Op.create(f"{base}/a", b""),  # recreate: fine
                        Op.check(f"{base}/a", 99),  # BAD_VERSION -> abort
                    ]
                )
            assert (await client.get(f"{base}/a"))[0] == b"two"
            await client.multi([Op.delete(f"{base}/a"), Op.delete(base)])
            assert await client.exists(base) is None
        finally:
            await client.close()

    async def test_unregister_beside_sibling_against_real_zk(self):
        """The fleet-deregistration semantics depend on real ZooKeeper's
        NOT_EMPTY refusal — including the multi abort reporting the
        failing op's code — so pin them against the real server: one
        instance out, sibling and service record intact; last one out
        cleans up.  Both the sequential walk and the atomic multi path."""
        from registrar_tpu.records import domain_to_path

        for atomic in (False, True):
            mine_client = await ZKClient(_servers()).connect()
            sib_client = await ZKClient(_servers()).connect()
            domain = f"fleet-{uuid.uuid4().hex[:8]}.test.registrar"
            path = domain_to_path(domain)
            try:
                registration = {
                    "domain": domain,
                    "type": "load_balancer",
                    "service": {
                        "type": "service",
                        "service": {
                            "srvce": "_http", "proto": "_tcp", "port": 80,
                        },
                    },
                }
                mine = await register(
                    mine_client, registration, admin_ip="10.250.0.3",
                    hostname="fleet-a", settle_delay=0.05,
                )
                theirs = await register(
                    sib_client, registration, admin_ip="10.250.0.4",
                    hostname="fleet-b", settle_delay=0.05,
                )
                deleted = await unregister(mine_client, mine, atomic=atomic)
                assert "fleet-a" not in await sib_client.get_children(path)
                assert path not in deleted  # shared node not claimed
                svc_stat = await sib_client.stat(path)
                assert svc_stat.ephemeral_owner == 0  # service record stays
                deleted = await unregister(sib_client, theirs, atomic=atomic)
                assert path in deleted  # last one out takes it
                assert await sib_client.exists(path) is None
            finally:
                # clean up even on assertion failure — a long-lived real
                # server must not accumulate this test's persistent nodes
                try:
                    for node in sorted(
                        await sib_client.get_children(path), reverse=True
                    ):
                        await sib_client.unlink(f"{path}/{node}")
                    await sib_client.unlink(path)
                except Exception:  # noqa: BLE001 - already gone on success
                    pass
                for p in ("/registrar/test", "/registrar"):
                    try:
                        await sib_client.unlink(p)
                    except Exception:  # noqa: BLE001 - shared parents remain
                        break
                await sib_client.close()
                await mine_client.close()

    async def test_sync_against_real_zk(self):
        client = await ZKClient(_servers()).connect()
        try:
            assert await client.sync("/") == "/"
        finally:
            await client.close()

    async def test_acl_auth_against_real_zk(self):
        """The digest formula and ACL records must interoperate with real
        ZooKeeper's DigestAuthenticationProvider and fixupACL."""
        from registrar_tpu.zk.protocol import (
            ACL,
            Err,
            OPEN_ACL_UNSAFE,
            Perms,
            ZKError,
            creator_all_acl,
        )

        owner = await ZKClient(_servers()).connect()
        stranger = await ZKClient(_servers()).connect()
        try:
            path = f"/registrar-interop-acl-{uuid.uuid4().hex[:8]}"
            await owner.add_auth("digest", b"interop:pw")
            await owner.create(
                path, b"locked", acls=creator_all_acl("interop", "pw")
            )
            acls, stat = await owner.get_acl(path)
            assert acls == creator_all_acl("interop", "pw")
            assert stat.aversion == 0

            with pytest.raises(ZKError) as exc:
                await stranger.get(path)
            assert exc.value.code == Err.NO_AUTH

            await stranger.add_auth("digest", b"interop:pw")
            assert (await stranger.get(path))[0] == b"locked"

            stat = await owner.set_acl(
                path, list(OPEN_ACL_UNSAFE), version=0
            )
            assert stat.aversion == 1
            with pytest.raises(ZKError) as exc:
                await owner.set_acl(path, [ACL(Perms.READ, "world", "anyone")],
                                    version=0)
            assert exc.value.code == Err.BAD_VERSION
            await owner.unlink(path)
        finally:
            await stranger.close()
            await owner.close()

    async def test_watch_fires_on_real_zk(self):
        import asyncio

        client = await ZKClient(_servers()).connect()
        try:
            path = f"/registrar-interop-watch-{uuid.uuid4().hex[:8]}"
            await client.create(path, b"x")
            fired = asyncio.Event()
            client.watch(path, lambda ev: fired.set())
            await client.stat(path, watch=True)
            await client.put(path, b"y")
            await asyncio.wait_for(fired.wait(), timeout=10)
            await client.unlink(path)
        finally:
            await client.close()

    async def test_daemon_e2e_against_real_zk(self, tmp_path):
        """Short daemon e2e: the real daemon registers into the real
        ZooKeeper, the znode payload matches the contract, and SIGKILL
        (the SMF ':kill' analog) lets the ephemeral vanish via real
        session expiry — the reference's deployment story
        (reference main.js:141-144, smf/manifests/registrar.xml.in)
        against the reference's test dependency (test/helper.js:57-62).
        """
        import asyncio
        import json
        import signal
        import socket
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        token = uuid.uuid4().hex[:8]
        domain = f"{token}.e2e.registrar"  # -> /registrar/e2e/<token>/<host>
        host, port = _servers()[0]
        config = {
            "registration": {
                "domain": domain,
                "type": "load_balancer",
                "heartbeatInterval": 500,
                "service": {
                    "type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
                },
            },
            "adminIp": "10.250.0.2",
            # real ZooKeeper clamps the session timeout to >= 2*tickTime
            # (4 s with the stock 2 s tick), so expiry below takes a few
            # seconds — keep the requested value at the floor.
            "zookeeper": {
                "servers": [{"host": host, "port": port}],
                "timeout": 4000,
            },
        }
        cfg_path = tmp_path / "config.json"
        cfg_path.write_text(json.dumps(config))

        observer = await ZKClient(_servers()).connect()
        proc = subprocess.Popen(
            [sys.executable, "-m", "registrar_tpu", "-f", str(cfg_path)],
            cwd=repo,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env={**os.environ, "PYTHONPATH": repo},
        )
        try:
            hostname = socket.gethostname()
            host_node = f"/registrar/e2e/{token}/{hostname}"
            svc_node = f"/registrar/e2e/{token}"
            # daemon start + 1 s contract settle delay
            for _ in range(150):
                if await observer.exists(host_node):
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError("host znode never appeared in real ZK")
            data, st = await observer.get(host_node)
            assert st.ephemeral_owner != 0
            payload = parse_payload(data)
            assert payload["type"] == "load_balancer"
            assert payload["load_balancer"]["ports"] == [80]
            svc, svc_st = await observer.get(svc_node)
            assert svc_st.ephemeral_owner == 0
            assert parse_payload(svc)["type"] == "service"

            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            # ephemeral vanishes only when the real session expires
            # (>= the 4 s floor after the last heartbeat)
            for _ in range(300):
                if not await observer.exists(host_node):
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError("ephemeral survived real session expiry")
            assert await observer.exists(svc_node) is not None
        finally:
            if proc.poll() is None:
                proc.kill()
            # clean up the persistent chain this test minted
            for p in (f"/registrar/e2e/{token}", "/registrar/e2e", "/registrar"):
                try:
                    await observer.unlink(p)
                except Exception:  # noqa: BLE001 - shared parents may remain
                    break
            await observer.close()
