"""Level-triggered reconciler tests (ISSUE 3).

Each drift class is minted through the test server's state-corruption
controls (``corrupt_node``, ``seize_node``) or plain out-of-band client
ops, then must be *detected* (structured ``drift`` event, right reason)
and — with ``repair`` on — *converged* back to the exact znode contract,
or deliberately left alone (ownership conflicts).  The agent-level tests
also pin the session-rebirth consumer and the down-state desired-absent
path that finishes a failed mid-flight deregistration (the agent.py
``on_fail`` regression).
"""

import asyncio

import pytest

from registrar_tpu import reconcile
from registrar_tpu import registration as register_mod
from registrar_tpu.agent import register_plus
from registrar_tpu.reconcile import (
    R_MISSING,
    R_NOT_EPHEMERAL,
    R_OWNER,
    R_PAYLOAD,
    R_STALE_SERVICE,
    Reconciler,
)
from registrar_tpu.records import parse_payload
from registrar_tpu.registration import register
from registrar_tpu.retry import RetryPolicy
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient

DOMAIN = "rec.test.registrar"
PATH = "/registrar/test/rec"
HOST = "rechost"
ADMIN_IP = "10.8.8.8"

REG = {
    "domain": DOMAIN,
    "type": "load_balancer",
    "service": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
    },
}

FAST_RECONNECT = RetryPolicy(
    max_attempts=float("inf"), initial_delay=0.02, max_delay=0.1
)


async def _pair(**client_kw):
    server = await ZKServer().start()
    client = await ZKClient(
        [server.address], reconnect_policy=FAST_RECONNECT, **client_kw
    ).connect()
    return server, client


def _plus(client, **kw):
    kw.setdefault("settle_delay", 0.01)
    kw.setdefault("hostname", HOST)
    kw.setdefault("admin_ip", ADMIN_IP)
    # keep the heartbeat loop quiet so the reconciler is the only actor
    kw.setdefault("heartbeat_interval", 60)
    kw.setdefault("reconcile", {"interval_seconds": 0.05, "repair": True})
    return register_plus(client, kw.pop("registration", REG), **kw)


class TestDesiredRecords:
    async def test_desired_matches_what_register_writes(self):
        # The sweep compares against desired_records; any formula drift
        # from the live pipeline would mint permanent false diffs — pin
        # them byte-identical.
        server, client = await _pair()
        try:
            nodes = await register(
                client, REG, admin_ip=ADMIN_IP, hostname=HOST,
                settle_delay=0,
            )
            desired = reconcile.desired_records(REG, ADMIN_IP, HOST)
            assert sorted(d.path for d in desired) == sorted(nodes)
            for d in desired:
                data, stat = await client.get(d.path)
                assert data == d.payload, d.path
                assert bool(stat.ephemeral_owner) == d.ephemeral, d.path
            # ... and therefore a fresh registration shows zero drift
            assert await reconcile.audit(
                client, REG, admin_ip=ADMIN_IP, hostname=HOST
            ) == []
        finally:
            await client.close()
            await server.stop()

    async def test_alias_equal_to_domain_collapses_to_one_entry(self):
        # An alias naming the domain itself: the pipeline cannot register
        # this shape at all (stage-3 mkdirp creates the domain node
        # persistent for the host record's parent; stage 4's ephemeral
        # create of the same path dies NODE_EXISTS — pinned below so a
        # future pipeline change revisits desired_records' collapse).
        # desired_records must still not emit the same path twice with
        # conflicting expectations: an audit of the config would report
        # self-contradictory drift forever.
        reg = {**REG, "aliases": [DOMAIN]}
        desired = reconcile.desired_records(reg, ADMIN_IP, HOST)
        paths = [d.path for d in desired]
        assert sorted(paths) == sorted(set(paths)), "duplicate desired paths"
        server, client = await _pair()
        try:
            from registrar_tpu.zk.protocol import Err, ZKError

            with pytest.raises(ZKError) as ei:
                await register(
                    client, reg, admin_ip=ADMIN_IP, hostname=HOST,
                    settle_delay=0,
                )
            assert ei.value.code == Err.NODE_EXISTS
        finally:
            await client.close()
            await server.stop()

    def test_desired_validates_registration(self):
        with pytest.raises(ValueError):
            reconcile.desired_records({"domain": DOMAIN}, ADMIN_IP, HOST)


class TestSweepDetection:
    """Read-only drift detection, one class at a time."""

    async def _registered(self):
        server, client = await _pair()
        await register(
            client, REG, admin_ip=ADMIN_IP, hostname=HOST, settle_delay=0
        )
        return server, client

    async def _sweep(self, client):
        return await reconcile.sweep(
            client,
            reconcile.desired_records(REG, ADMIN_IP, HOST),
            session_id=client.session_id,
        )

    async def test_missing_node(self):
        server, client = await self._registered()
        try:
            await client.unlink(f"{PATH}/{HOST}")
            drifts = await self._sweep(client)
            assert [(d.path, d.reason) for d in drifts] == [
                (f"{PATH}/{HOST}", R_MISSING)
            ]
            assert drifts[0].repairable
        finally:
            await client.close()
            await server.stop()

    async def test_payload_drift(self):
        server, client = await self._registered()
        try:
            await server.corrupt_node(f"{PATH}/{HOST}", b'{"evil":1}')
            drifts = await self._sweep(client)
            assert [(d.path, d.reason) for d in drifts] == [
                (f"{PATH}/{HOST}", R_PAYLOAD)
            ]
        finally:
            await client.close()
            await server.stop()

    async def test_foreign_owner_not_repairable(self):
        server, client = await self._registered()
        try:
            server.seize_node(f"{PATH}/{HOST}", 0xDEAD)
            drifts = await self._sweep(client)
            assert [(d.path, d.reason) for d in drifts] == [
                (f"{PATH}/{HOST}", R_OWNER)
            ]
            assert not drifts[0].repairable
            assert "0xdead" in drifts[0].detail
        finally:
            await client.close()
            await server.stop()

    async def test_host_record_flattened_to_persistent(self):
        server, client = await self._registered()
        try:
            server.seize_node(f"{PATH}/{HOST}", 0)
            drifts = await self._sweep(client)
            assert [(d.path, d.reason) for d in drifts] == [
                (f"{PATH}/{HOST}", R_NOT_EPHEMERAL)
            ]
            assert drifts[0].repairable  # nothing will ever clean it up
        finally:
            await client.close()
            await server.stop()

    async def test_stale_service_record(self):
        server, client = await self._registered()
        try:
            await server.corrupt_node(PATH, b'{"type":"garbage"}')
            drifts = await self._sweep(client)
            assert [(d.path, d.reason) for d in drifts] == [
                (PATH, R_STALE_SERVICE)
            ]
        finally:
            await client.close()
            await server.stop()

    async def test_audit_accepts_any_live_owner(self):
        # An external auditor (zkcli verify) never owns the ephemerals;
        # audit() must not flag a healthy fleet as owner drift.
        server, client = await self._registered()
        auditor = await ZKClient([server.address]).connect()
        try:
            assert await reconcile.audit(
                auditor, REG, admin_ip=ADMIN_IP, hostname=HOST
            ) == []
        finally:
            await auditor.close()
            await client.close()
            await server.stop()


class TestReconcilerRepair:
    """The in-daemon loop end to end, one drift class at a time."""

    async def test_missing_node_repaired_via_pipeline(self):
        server, client = await _pair()
        try:
            ee = _plus(client)
            (znodes,) = await ee.wait_for("register", timeout=10)
            host_node = f"{PATH}/{HOST}"
            await client.unlink(host_node)
            (d,) = await ee.wait_for("drift", timeout=10)
            assert (d.path, d.reason) == (host_node, R_MISSING)
            (repaired,) = await ee.wait_for("driftRepaired", timeout=10)
            assert repaired.reason == R_MISSING
            data, st = await client.get(host_node)
            assert st.ephemeral_owner == client.session_id
            assert parse_payload(data)["type"] == "load_balancer"
            assert ee.znodes == znodes
            # converged: the next sweeps are clean
            summary = (await ee.wait_for("reconcile", timeout=10))[0]
            while summary["drift"]:
                summary = (await ee.wait_for("reconcile", timeout=10))[0]
            assert summary == {"duration": summary["duration"],
                               "drift": 0, "repaired": 0}
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_payload_drift_repaired(self):
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            host_node = f"{PATH}/{HOST}"
            want, _ = await client.get(host_node)
            await server.corrupt_node(host_node, b'{"evil":1}')
            (d,) = await ee.wait_for("drift", timeout=10)
            assert (d.path, d.reason) == (host_node, R_PAYLOAD)
            await ee.wait_for("driftRepaired", timeout=10)
            data, st = await client.get(host_node)
            assert data == want  # byte-exact §2.6 contract restored
            assert st.ephemeral_owner == client.session_id
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_stale_service_repaired_without_ephemeral_blip(self):
        # A drifted service record alone converges via a targeted put:
        # the live host ephemeral must NOT be deleted/recreated (czxid
        # pinned), because that is a real Binder-visible blip.
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            host_node = f"{PATH}/{HOST}"
            want_svc, _ = await client.get(PATH)
            czxid_before = (await client.stat(host_node)).czxid
            await server.corrupt_node(PATH, b'{"type":"garbage"}')
            (d,) = await ee.wait_for("drift", timeout=10)
            assert (d.path, d.reason) == (PATH, R_STALE_SERVICE)
            await ee.wait_for("driftRepaired", timeout=10)
            svc, svc_st = await client.get(PATH)
            assert svc == want_svc
            assert svc_st.ephemeral_owner == 0
            assert (await client.stat(host_node)).czxid == czxid_before
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_own_ephemeral_service_record_converges(self):
        # The realistic "service record became ephemeral" corruption: in
        # real ZooKeeper an ephemeral cannot have children, so this state
        # coexists with the host records being GONE.  A put cannot change
        # ephemeral-ness and the pipeline cannot create children under an
        # ephemeral — the repair must unlink our stray ephemeral first,
        # then the pipeline restores the full contract.
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            want_svc, _ = await client.get(PATH)
            host_node = f"{PATH}/{HOST}"
            await client.unlink(host_node)
            server.seize_node(PATH, client.session_id)
            drifts = None
            for _ in range(100):
                drifts = await ee.reconciler.sweep_once()
                if not drifts:
                    break
                await asyncio.sleep(0.05)
            assert drifts == [], f"never converged: {drifts}"
            # truly converged: service persistent with contract bytes,
            # host record back as OUR ephemeral
            svc, svc_st = await client.get(PATH)
            assert svc_st.ephemeral_owner == 0
            assert svc == want_svc
            st = await client.stat(host_node)
            assert st.ephemeral_owner == client.session_id
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_impossible_ephemeral_service_with_children_is_refused(
        self
    ):
        # Test controls can mint what real ZooKeeper cannot: an ephemeral
        # WITH children.  The pre-clean's unlink hits NOT_EMPTY and must
        # refuse (loudly) rather than crash the loop or falsely report
        # the drift repaired.
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            server.seize_node(PATH, client.session_id)  # host child LIVE
            (d,) = await ee.wait_for("drift", timeout=10)
            assert (d.path, d.reason) == (PATH, R_STALE_SERVICE)
            for _ in range(3):
                await ee.wait_for("reconcile", timeout=10)
            assert ee.reconciler.repaired == 0  # never claimed repaired
            _, st = await client.get(PATH)
            assert st.ephemeral_owner == client.session_id  # untouched
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_foreign_ephemeral_service_record_left_alone(self):
        # The same corruption owned by a FOREIGN session is refused:
        # writing into (or deleting) someone else's ephemeral violates
        # the never-steal rule — detect, count, leave it for the owner's
        # expiry to clean up.
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            before, _ = await client.get(PATH)
            server.seize_node(PATH, 0xDEAD)
            (d,) = await ee.wait_for("drift", timeout=10)
            assert (d.path, d.reason) == (PATH, R_STALE_SERVICE)
            assert not d.repairable
            for _ in range(3):
                await ee.wait_for("reconcile", timeout=10)
            data, st = await client.get(PATH)
            assert st.ephemeral_owner == 0xDEAD  # untouched
            assert data == before
            assert ee.reconciler.repaired == 0
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_ownership_conflict_is_never_stolen(self):
        # Two live claimants for one hostname: detect, count, refuse to
        # repair — the foreign node must survive many sweeps untouched.
        server, client = await _pair()
        hijacker = await ZKClient([server.address]).connect()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            host_node = f"{PATH}/{HOST}"
            registers = []
            ee.on("register", registers.append)
            await client.unlink(host_node)
            await hijacker.create(host_node, b'{"mine":1}')
            # ^ ephemeral create shape does not matter for the guard;
            # make it the worst case: a LIVE foreign ephemeral
            await hijacker.unlink(host_node)
            from registrar_tpu.zk.protocol import CreateFlag

            await hijacker.create(
                host_node, b'{"mine":1}', CreateFlag.EPHEMERAL
            )
            (d,) = await ee.wait_for("drift", timeout=10)
            assert (d.path, d.reason) == (host_node, R_OWNER)
            # several more sweeps: still there, still the hijacker's
            for _ in range(3):
                await ee.wait_for("reconcile", timeout=10)
            data, st = await client.get(host_node)
            assert st.ephemeral_owner == hijacker.session_id
            assert data == b'{"mine":1}'
            assert registers == []  # the pipeline never ran
            assert ee.reconciler.owner_conflicts >= 1
            assert ee.reconciler.repaired == 0
            ee.stop()
        finally:
            await hijacker.close()
            await client.close()
            await server.stop()

    async def test_service_repair_still_runs_beside_owner_conflict(self):
        # An ownership conflict blocks the pipeline (it would steal), but
        # the targeted service-record put touches no ephemeral and must
        # still converge the service record.
        server, client = await _pair()
        hijacker = await ZKClient([server.address]).connect()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            host_node = f"{PATH}/{HOST}"
            want_svc, _ = await client.get(PATH)
            from registrar_tpu.zk.protocol import CreateFlag

            await client.unlink(host_node)
            await hijacker.create(
                host_node, b'{"mine":1}', CreateFlag.EPHEMERAL
            )
            await server.corrupt_node(PATH, b'{"type":"garbage"}')
            (repaired,) = await ee.wait_for("driftRepaired", timeout=10)
            assert repaired.reason == R_STALE_SERVICE
            svc, _ = await client.get(PATH)
            assert svc == want_svc
            # the hijacked node was not touched
            _, st = await client.get(host_node)
            assert st.ephemeral_owner == hijacker.session_id
            ee.stop()
        finally:
            await hijacker.close()
            await client.close()
            await server.stop()

    async def test_repair_off_detects_without_mutating(self):
        server, client = await _pair()
        try:
            ee = _plus(
                client,
                reconcile={"interval_seconds": 0.05, "repair": False},
            )
            await ee.wait_for("register", timeout=10)
            host_node = f"{PATH}/{HOST}"
            repaired = []
            ee.on("driftRepaired", repaired.append)
            await client.unlink(host_node)
            (d,) = await ee.wait_for("drift", timeout=10)
            assert d.reason == R_MISSING
            for _ in range(3):
                await ee.wait_for("reconcile", timeout=10)
            assert await client.exists(host_node) is None
            assert repaired == []
            assert ee.reconciler.drift_seen >= 1
            assert ee.reconciler.repaired == 0
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_concurrent_repairers_do_not_tug_of_war(self):
        # Heartbeat repair AND the reconciler both react to the same
        # missing node.  The loser of the lock race must SKIP (epoch
        # guard), not re-run the pipeline over the winner's fresh
        # registration — pre-fix, the queued repair's cleanup stage
        # deleted the just-repaired node, re-minting the drift in an
        # unbounded delete/recreate loop (caught in the kitchen-sink
        # e2e; this is the fast deterministic pin).
        server, client = await _pair()
        try:
            ee = _plus(
                client,
                heartbeat_interval=0.03,
                heartbeat_retry=RetryPolicy(
                    max_attempts=1, initial_delay=0.01, max_delay=0.01
                ),
                repair_heartbeat_miss=True,
                reconcile={"interval_seconds": 0.03, "repair": True},
            )
            await ee.wait_for("register", timeout=10)
            host_node = f"{PATH}/{HOST}"
            await client.unlink(host_node)
            await ee.wait_for("register", timeout=10)  # repaired by someone
            # Once repaired, the registration must be STABLE: the same
            # znode (czxid pinned) across many sweep+heartbeat cycles.
            deadline = asyncio.get_running_loop().time() + 3
            czxid = None
            while asyncio.get_running_loop().time() < deadline:
                st = await client.exists(host_node)
                if st is None:
                    # mid-pipeline window of the FIRST repair is legal;
                    # a second disappearance after stability is not
                    assert czxid is None, "repaired node was deleted again"
                elif czxid is None:
                    czxid = st.czxid
                else:
                    assert st.czxid == czxid, "node was recreated again"
                await asyncio.sleep(0.02)
            assert czxid is not None
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_sweep_survives_transport_blips(self):
        # A sweep that fails (server gone mid-tick) must not kill the
        # loop: once the ensemble is back the next tick converges.
        server, client = await _pair()
        try:
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            await server.drop_connections()
            # several ticks fire against a reconnecting client; then the
            # reconciler is still alive and sweeping
            await ee.wait_for("reconcile", timeout=10)
            assert ee.reconciler.sweeps >= 1
            ee.stop()
        finally:
            await client.close()
            await server.stop()


class TestDownDesiredAbsent:
    """Health-down flips desired state to absent (ISSUE 3 satellite fix:
    a failed mid-flight unregister is finished by the reconciler)."""

    async def test_failed_unregister_is_finished_by_the_reconciler(
        self, monkeypatch, tmp_path
    ):
        # The regression at agent.py on_fail: health crosses the
        # threshold, the deregistration RPC fails, and the reference-
        # shaped agent left ee.down=True with LIVE znodes forever.  The
        # reconciler's down-sweep must finish the deregistration.
        flag = tmp_path / "healthy"
        flag.write_text("")
        server, client = await _pair()
        try:
            real_unregister = register_mod.unregister
            fail_once = {"armed": True}

            async def flaky_unregister(zk, znodes, **kw):
                if fail_once["armed"]:
                    fail_once["armed"] = False
                    raise RuntimeError("unregister hiccup")
                return await real_unregister(zk, znodes, **kw)

            monkeypatch.setattr(
                register_mod, "unregister", flaky_unregister
            )
            ee = _plus(
                client,
                health_check={
                    "command": f"test -f {flag}",
                    "interval": 0.05,
                    "timeout": 1.0,
                    "threshold": 2,
                },
            )
            await ee.wait_for("register", timeout=10)
            host_node = f"{PATH}/{HOST}"
            errors = []
            ee.on("error", errors.append)
            unreg_fut = asyncio.ensure_future(
                ee.wait_for("unregister", timeout=10)
            )
            flag.unlink()  # health starts failing
            await ee.wait_for("fail", timeout=10)
            # the on_fail unregister hiccuped; znodes are still live and
            # the host is latched down — the pre-fix terminal state
            assert errors and "unregister hiccup" in str(errors[0])
            # ... until the reconciler's down-sweep finishes the job
            err, deleted = await unreg_fut
            assert err is None  # reconciler-driven completion
            assert host_node in deleted
            assert await client.exists(host_node) is None
            assert ee.down
            # the lingering drift was surfaced and counted as repaired
            assert ee.reconciler.repaired >= 1
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_down_shared_service_node_is_not_drift(self):
        # A sibling's ephemeral keeps the shared service node alive: the
        # down-sweep must not report (or try to delete) it forever.
        server, client = await _pair()
        sibling = await ZKClient([server.address]).connect()
        try:
            await register(
                sibling, REG, admin_ip="10.8.8.9", hostname="sibling",
                settle_delay=0,
            )
            ee = _plus(client)
            await ee.wait_for("register", timeout=10)
            # deregister by hand, then latch down with only the shared
            # service node left in the owned list
            ee.down = True
            await register_mod.unregister(client, [f"{PATH}/{HOST}"])
            for _ in range(3):
                await ee.wait_for("reconcile", timeout=10)
            drifts = await ee.reconciler.sweep_once()
            assert drifts == []
            assert await client.exists(PATH) is not None
            assert await client.exists(f"{PATH}/sibling") is not None
            ee.stop()
        finally:
            await sibling.close()
            await client.close()
            await server.stop()


class TestSessionRebirthConsumer:
    """The agent side of surviveSessionExpiry: a reborn session re-runs
    the idempotent pipeline — unless health holds the host down."""

    async def test_rebirth_reregisters_under_new_session(self):
        server, client = await _pair(survive_session_expiry=True)
        try:
            ee = _plus(client, reconcile=None)
            (znodes,) = await ee.wait_for("register", timeout=10)
            old = client.session_id
            rereg = asyncio.ensure_future(ee.wait_for("register", timeout=10))
            await server.expire_session(old)
            (renodes,) = await rereg
            assert renodes == znodes
            st = await client.stat(znodes[0])
            assert st.ephemeral_owner == client.session_id != old
            assert not client.closed
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_rebirth_reregistration_retries_transient_failures(
        self, monkeypatch
    ):
        # Post-rebirth re-registration rides the same turbulence that
        # killed the session, so a single pipeline attempt is not enough
        # — a live session with NO registration is a silent DNS outage,
        # strictly worse than the exit(1) the feature replaces.  The
        # consumer must retry with backoff until it lands, with NO
        # reconciler and NO repairHeartbeatMiss to paper over a give-up.
        import registrar_tpu.agent as agent_mod

        monkeypatch.setattr(
            agent_mod, "REBIRTH_REREGISTER_RETRY",
            RetryPolicy(
                max_attempts=float("inf"), initial_delay=0.02,
                max_delay=0.05,
            ),
        )
        server, client = await _pair(survive_session_expiry=True)
        try:
            ee = _plus(client, reconcile=None)
            (znodes,) = await ee.wait_for("register", timeout=10)

            real_register = register_mod.register
            fail = {"remaining": 2}

            async def flaky_register(*a, **kw):
                if fail["remaining"] > 0:
                    fail["remaining"] -= 1
                    raise RuntimeError("pipeline blip")
                return await real_register(*a, **kw)

            monkeypatch.setattr(register_mod, "register", flaky_register)
            errors = []
            ee.on("error", errors.append)
            rereg = asyncio.ensure_future(ee.wait_for("register", timeout=10))
            await server.expire_session(client.session_id)
            (renodes,) = await rereg
            assert renodes == znodes
            assert errors and "pipeline blip" in str(errors[0])
            st = await client.stat(znodes[0])
            assert st.ephemeral_owner == client.session_id
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_rebirth_respects_health_down(self):
        server, client = await _pair(survive_session_expiry=True)
        try:
            ee = _plus(client, reconcile=None)
            (znodes,) = await ee.wait_for("register", timeout=10)
            ee.down = True  # what on_fail latches before deregistering
            registers = []
            ee.on("register", registers.append)
            reborn = asyncio.ensure_future(
                client.wait_for("session_reborn", timeout=10)
            )
            await server.expire_session(client.session_id)
            await reborn
            await asyncio.sleep(0.3)  # a resurrection would land here
            assert registers == []
            assert await client.exists(znodes[0]) is None
            ee.stop()
        finally:
            await client.close()
            await server.stop()

    async def test_rebirth_with_reconciler_converges_either_way(self):
        # Belt and braces: even if the rebirth consumer's pipeline run
        # raced something and failed, the level-triggered sweep converges
        # the registration — the acceptance criterion's "within one
        # reconcile interval + retry budget".
        server, client = await _pair(survive_session_expiry=True)
        try:
            ee = _plus(client)
            (znodes,) = await ee.wait_for("register", timeout=10)
            old = client.session_id
            await server.expire_session(old)
            from registrar_tpu.zk.protocol import ZKError

            for _ in range(200):
                try:
                    st = await client.exists(znodes[0])
                except ZKError:
                    # the rebirth's reconnect window: ops fail with
                    # CONNECTION_LOSS until the fresh session is up
                    await asyncio.sleep(0.05)
                    continue
                if (
                    st is not None
                    and st.ephemeral_owner == client.session_id
                    and client.session_id != old
                ):
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("never converged after rebirth")
            assert not client.closed
            ee.stop()
        finally:
            await client.close()
            await server.stop()


class TestReconcilerConstruction:
    def test_interval_must_be_positive(self):
        with pytest.raises(ValueError):
            Reconciler(None, None, REG, interval_s=0)

    def test_repair_requires_repair_fn(self):
        with pytest.raises(ValueError):
            Reconciler(None, None, REG, interval_s=1, repair=True)
