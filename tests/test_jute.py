"""Golden-byte tests for jute serialization and protocol records.

The expected byte strings are hand-computed from the Apache ZooKeeper jute
format (4-byte big-endian ints, 8-byte longs, length-prefixed buffers).
They defend against symmetric encode/decode bugs: since both our client and
our test server use this module, a mirrored mistake would otherwise be
invisible.
"""

import pytest

from registrar_tpu.zk.jute import JuteError, Reader, Writer
from registrar_tpu.zk import protocol as proto
from registrar_tpu.zk.protocol import (
    ConnectRequest,
    ConnectResponse,
    CreateRequest,
    Err,
    GetDataResponse,
    OpCode,
    OPEN_ACL_UNSAFE,
    ReplyHeader,
    RequestHeader,
    SetWatches,
    Stat,
    WatcherEvent,
    ZKError,
    check_path,
    encode_reply,
    encode_request,
    frame,
)


class TestPrimitives:
    def test_int_golden(self):
        assert Writer().write_int(1).to_bytes() == b"\x00\x00\x00\x01"
        assert Writer().write_int(-1).to_bytes() == b"\xff\xff\xff\xff"
        assert Writer().write_int(0x0102_0304).to_bytes() == b"\x01\x02\x03\x04"

    def test_long_golden(self):
        assert (
            Writer().write_long(1).to_bytes() == b"\x00\x00\x00\x00\x00\x00\x00\x01"
        )
        assert (
            Writer().write_long(-2).to_bytes() == b"\xff\xff\xff\xff\xff\xff\xff\xfe"
        )

    def test_bool_golden(self):
        assert Writer().write_bool(True).to_bytes() == b"\x01"
        assert Writer().write_bool(False).to_bytes() == b"\x00"

    def test_buffer_golden(self):
        assert Writer().write_buffer(b"ab").to_bytes() == b"\x00\x00\x00\x02ab"
        assert Writer().write_buffer(None).to_bytes() == b"\xff\xff\xff\xff"
        assert Writer().write_buffer(b"").to_bytes() == b"\x00\x00\x00\x00"

    def test_ustring_golden(self):
        assert Writer().write_ustring("/a").to_bytes() == b"\x00\x00\x00\x02/a"
        # UTF-8 length counts bytes, not characters
        assert Writer().write_ustring("é").to_bytes() == b"\x00\x00\x00\x02\xc3\xa9"

    def test_vector_golden(self):
        data = Writer().write_vector(["a", "b"], Writer.write_ustring).to_bytes()
        assert data == b"\x00\x00\x00\x02" b"\x00\x00\x00\x01a" b"\x00\x00\x00\x01b"
        assert Writer().write_vector(None, Writer.write_ustring).to_bytes() == (
            b"\xff\xff\xff\xff"
        )

    def test_int_range_checked(self):
        with pytest.raises(JuteError):
            Writer().write_int(2**31)
        with pytest.raises(JuteError):
            Writer().write_long(2**63)

    def test_reader_roundtrip_all(self):
        w = (
            Writer()
            .write_int(42)
            .write_long(-7)
            .write_bool(True)
            .write_buffer(b"xyz")
            .write_ustring("hello")
            .write_vector([1, 2, 3], Writer.write_int)
        )
        r = Reader(w.to_bytes())
        assert r.read_int() == 42
        assert r.read_long() == -7
        assert r.read_bool() is True
        assert r.read_buffer() == b"xyz"
        assert r.read_ustring() == "hello"
        assert r.read_vector(Reader.read_int) == [1, 2, 3]
        assert r.remaining() == 0

    def test_truncated_raises(self):
        with pytest.raises(JuteError):
            Reader(b"\x00\x00").read_int()
        with pytest.raises(JuteError):
            Reader(b"\x00\x00\x00\x05ab").read_buffer()

    def test_negative_lengths_raise(self):
        with pytest.raises(JuteError):
            Reader(b"\xff\xff\xff\xfe").read_buffer()  # -2
        with pytest.raises(JuteError):
            Reader(b"\xff\xff\xff\xfe").read_vector(Reader.read_int)

    def test_truncation_mid_stream_consumes_nothing(self):
        # The unpack_from fast path must behave exactly like the slicing
        # one at the boundary: a failed primitive read raises without
        # advancing the cursor.
        r = Reader(b"\x00\x00\x00\x01\x00\x00")
        assert r.read_int() == 1
        with pytest.raises(JuteError):
            r.read_int()
        assert r.pos == 4
        with pytest.raises(JuteError):
            Reader(b"\x00" * 7).read_long()

    def test_mutable_buffer_payload_is_pinned(self):
        # bytes are appended without copying; mutable payloads must still
        # be snapshotted at write time.
        buf = bytearray(b"abc")
        w = Writer().write_buffer(buf)
        buf[0] = ord("z")
        assert w.to_bytes() == b"\x00\x00\x00\x03abc"


class TestRecords:
    def test_connect_request_golden(self):
        req = ConnectRequest(timeout_ms=30000, passwd=b"\x00" * 16)
        data = Writer()
        req.write(data)
        b = data.to_bytes()
        assert b == (
            b"\x00\x00\x00\x00"  # protocolVersion 0
            b"\x00\x00\x00\x00\x00\x00\x00\x00"  # lastZxidSeen 0
            b"\x00\x00\x75\x30"  # timeout 30000
            b"\x00\x00\x00\x00\x00\x00\x00\x00"  # sessionId 0
            b"\x00\x00\x00\x10" + b"\x00" * 16  # passwd buffer
            + b"\x00"  # readOnly false
        )
        rt = ConnectRequest.read(Reader(b))
        assert rt == req

    def test_connect_request_tolerates_no_readonly_byte(self):
        req = ConnectRequest()
        w = Writer()
        req.write(w)
        b = w.to_bytes()[:-1]  # drop readOnly byte, as a 3.3-era peer would
        rt = ConnectRequest.read(Reader(b))
        assert rt.read_only is False

    def test_connect_response_roundtrip(self):
        resp = ConnectResponse(timeout_ms=12345, session_id=0xDEAD, passwd=b"p" * 16)
        w = Writer()
        resp.write(w)
        assert ConnectResponse.read(Reader(w.to_bytes())) == resp

    def test_request_header_golden(self):
        w = Writer()
        RequestHeader(xid=proto.XID_PING, type=OpCode.PING).write(w)
        assert w.to_bytes() == b"\xff\xff\xff\xfe\x00\x00\x00\x0b"

    def test_reply_header_golden(self):
        w = Writer()
        ReplyHeader(xid=1, zxid=2, err=Err.NO_NODE).write(w)
        assert w.to_bytes() == (
            b"\x00\x00\x00\x01"
            b"\x00\x00\x00\x00\x00\x00\x00\x02"
            b"\xff\xff\xff\x9b"  # -101
        )

    def test_create_request_golden(self):
        req = CreateRequest(
            path="/a", data=b"hi", acls=list(OPEN_ACL_UNSAFE), flags=1
        )
        w = Writer()
        req.write(w)
        assert w.to_bytes() == (
            b"\x00\x00\x00\x02/a"
            b"\x00\x00\x00\x02hi"
            b"\x00\x00\x00\x01"  # one ACL
            b"\x00\x00\x00\x1f"  # perms 31
            b"\x00\x00\x00\x05world"
            b"\x00\x00\x00\x06anyone"
            b"\x00\x00\x00\x01"  # flags ephemeral
        )
        assert CreateRequest.read(Reader(w.to_bytes())) == req

    def test_stat_is_68_bytes(self):
        w = Writer()
        Stat().write(w)
        # 7 longs (56) + 4 ints (16) = 68... actually 6 longs + 5 ints:
        # czxid mzxid ctime mtime ephemeralOwner pzxid = 6 longs = 48
        # version cversion aversion dataLength numChildren = 5 ints = 20
        assert len(w.to_bytes()) == 68

    def test_stat_roundtrip(self):
        st = Stat(
            czxid=1, mzxid=2, ctime=3, mtime=4, version=5, cversion=6,
            aversion=7, ephemeral_owner=0xABC, data_length=9, num_children=10,
            pzxid=11,
        )
        w = Writer()
        st.write(w)
        assert Stat.read(Reader(w.to_bytes())) == st

    def test_watcher_event_roundtrip(self):
        ev = WatcherEvent(type=2, state=3, path="/x/y")
        w = Writer()
        ev.write(w)
        assert WatcherEvent.read(Reader(w.to_bytes())) == ev

    def test_get_data_response_null_data(self):
        resp = GetDataResponse(data=None, stat=Stat())
        w = Writer()
        resp.write(w)
        assert GetDataResponse.read(Reader(w.to_bytes())).data is None

    def test_set_watches_roundtrip(self):
        sw = SetWatches(relative_zxid=9, data_watches=["/a"], child_watches=["/b"])
        w = Writer()
        sw.write(w)
        assert SetWatches.read(Reader(w.to_bytes())) == sw


class TestFraming:
    def test_frame_golden(self):
        assert frame(b"abc") == b"\x00\x00\x00\x03abc"

    def test_encode_request(self):
        b = encode_request(5, OpCode.DELETE, proto.DeleteRequest(path="/a", version=-1))
        # length(4) + header(8) + path(6) + version(4)
        assert b[:4] == b"\x00\x00\x00\x12"
        r = Reader(b[4:])
        hdr = RequestHeader.read(r)
        assert (hdr.xid, hdr.type) == (5, OpCode.DELETE)
        req = proto.DeleteRequest.read(r)
        assert (req.path, req.version) == ("/a", -1)

    def test_encode_reply_suppresses_body_on_error(self):
        b_err = encode_reply(1, 0, Err.NO_NODE, proto.CreateResponse(path="/a"))
        b_ok = encode_reply(1, 0, Err.OK, proto.CreateResponse(path="/a"))
        assert len(b_err) < len(b_ok)


class TestZKError:
    def test_names(self):
        e = ZKError(Err.NO_NODE, "/x")
        assert e.name == "NO_NODE"
        assert e.code == -101
        assert "/x" in str(e)

    def test_unknown_code(self):
        assert ZKError(-999).name == "ZK_ERROR_-999"


class TestCheckPath:
    @pytest.mark.parametrize("good", ["/", "/a", "/a/b", "/com/joyent/us-east"])
    def test_valid(self, good):
        assert check_path(good) == good

    @pytest.mark.parametrize(
        "bad", ["", "a", "/a/", "//a", "/a//b", "/a/./b", "/a/../b", "/a\x00b"]
    )
    def test_invalid(self, bad):
        with pytest.raises(ValueError):
            check_path(bad)


class LegacyReader:
    """The pre-ISSUE-11 Reader, verbatim: slicing ``_take`` (a bytes
    copy per field), ``read_buffer`` returning the raw slice,
    ``read_ustring`` decoding via an intermediate bytes copy.  The
    differential oracle for the zero-copy decode path: on every golden
    wire capture, the new Reader — over ``bytes`` AND over a
    ``memoryview`` — must produce identical values, positions, and
    failures."""

    def __init__(self, data, pos=0):
        self._data = data
        self._pos = pos

    @property
    def pos(self):
        return self._pos

    def remaining(self):
        return len(self._data) - self._pos

    def _take(self, n):
        if self.remaining() < n:
            raise JuteError("truncated")
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def read_int(self):
        import struct

        return struct.unpack(">i", self._take(4))[0]

    def read_long(self):
        import struct

        return struct.unpack(">q", self._take(8))[0]

    def read_bool(self):
        return self._take(1) != b"\x00"

    def read_buffer(self):
        n = self.read_int()
        if n == -1:
            return None
        if n < -1:
            raise JuteError(f"negative buffer length: {n}")
        return self._take(n)

    def read_ustring(self):
        buf = self.read_buffer()
        return None if buf is None else buf.decode("utf-8")

    def read_vector(self, read_item):
        n = self.read_int()
        if n == -1:
            return None
        if n < -1:
            raise JuteError(f"negative vector length: {n}")
        if n > self.remaining():
            raise JuteError(f"vector length {n} exceeds remaining data")
        return [read_item(self) for _ in range(n)]


def _wire_golden_corpus():
    """Every hand-written golden frame from tests/test_wire_golden.py.

    That module builds each golden through its ``hx(...)`` helper inside
    the test bodies; re-running the (sync, self-contained) tests with a
    capturing ``hx`` collects the full corpus — and re-asserts the
    encode byte-identity pins along the way, so the sweep below always
    runs against the captures as checked in, never a drifted copy.
    """
    import inspect

    import test_wire_golden as golden

    frames = []
    orig_hx = golden.hx

    def capture_hx(*parts):
        b = orig_hx(*parts)
        frames.append(b)
        return b

    golden.hx = capture_hx
    try:
        for name in sorted(dir(golden)):
            fn = getattr(golden, name)
            if (
                name.startswith("test_")
                and callable(fn)
                and not inspect.iscoroutinefunction(fn)
            ):
                fn()
    finally:
        golden.hx = orig_hx
    assert len(frames) >= 25, "golden corpus unexpectedly small"
    return frames


def _walk_ops(payload):
    """A deterministic primitive-read schedule derived from the payload
    bytes themselves, so every capture exercises a different mix."""
    return [payload[i] % 5 for i in range(0, len(payload), 3)] or [0]


def _run_walk(reader, ops):
    """Execute a primitive-read schedule; returns (results, pos) where
    a failure terminates the walk with a ("raise", step) marker."""
    out = []
    for step, op in enumerate(ops):
        try:
            if op == 0:
                out.append(reader.read_int())
            elif op == 1:
                out.append(reader.read_long())
            elif op == 2:
                out.append(reader.read_bool())
            elif op == 3:
                out.append(reader.read_buffer())
            else:
                out.append(reader.read_ustring())
        except JuteError:
            out.append(("raise", step))
            break
        except UnicodeDecodeError:
            out.append(("unicode", step))
            break
    return out, reader.pos


class TestZeroCopyParity:
    """ISSUE 11 satellite: the memoryview decode path against the old
    implementation, on every golden wire capture."""

    def test_parity_sweep_on_every_golden_capture(self):
        # For each capture: the new Reader over bytes, the new Reader
        # over a memoryview, and the legacy Reader must agree on every
        # value, every cursor position, and every failure point — for a
        # read schedule derived from the frame's own bytes, for the
        # whole payload AND for truncated prefixes (the mid-frame
        # corruption shape).
        for frame_bytes in _wire_golden_corpus():
            payload = frame_bytes[4:]  # strip the length prefix
            views = (payload, len(payload) // 2, 7, 1, 0)
            for cut in views:
                blob = payload if cut is payload else payload[:cut]
                ops = _walk_ops(blob)
                legacy = _run_walk(LegacyReader(blob), ops)
                new_bytes = _run_walk(Reader(blob), ops)
                new_view = _run_walk(Reader(memoryview(blob)), ops)
                assert new_bytes == legacy, (blob, ops)
                assert new_view == legacy, (blob, ops)

    def test_view_buffers_materialize_as_real_bytes(self):
        # read_buffer over a memoryview must hand back honest bytes —
        # a view escaping would be unhashable (binderview memoizes on
        # payload bytes) and would pin the whole receive chunk.
        w = Writer().write_buffer(b"payload").write_ustring("text")
        r = Reader(memoryview(w.to_bytes()))
        buf = r.read_buffer()
        assert type(buf) is bytes and buf == b"payload"
        assert r.read_ustring() == "text"

    def test_zero_length_strings_and_buffers(self):
        w = (
            Writer()
            .write_buffer(b"")
            .write_ustring("")
            .write_buffer(None)
            .write_ustring(None)
        )
        for data in (w.to_bytes(), memoryview(w.to_bytes())):
            r = Reader(data)
            assert r.read_buffer() == b""
            assert r.read_ustring() == ""
            assert r.read_buffer() is None
            assert r.read_ustring() is None
            assert r.remaining() == 0

    def test_truncated_view_raises_without_consuming(self):
        r = Reader(memoryview(b"\x00\x00\x00\x05ab"))
        with pytest.raises(JuteError):
            r.read_buffer()
        # the length int was consumed, the failed take was not
        assert r.pos == 4

    def test_long_at_peeks_without_consuming(self):
        w = Writer().write_long(0xABCDEF).write_long(-7)
        r = Reader(memoryview(w.to_bytes()))
        assert r.long_at(8) == -7
        assert r.pos == 0
        assert r.read_long() == 0xABCDEF
        with pytest.raises(JuteError):
            r.long_at(9)  # past the end
        with pytest.raises(JuteError):
            r.long_at(-1)


class TestCheckPathCache:
    def test_cache_bounded_with_fifo_eviction(self):
        # The validated-path cache must stay bounded past its cap AND
        # keep caching NEW paths (FIFO eviction) — a frozen cache would
        # quietly lose the optimization in a long-lived daemon whose
        # instance paths churn.
        from registrar_tpu.zk.protocol import (
            PATH_CACHE_MAX_ENTRIES,
            PathCache,
            check_path,
        )

        cache = PathCache()
        for i in range(PATH_CACHE_MAX_ENTRIES + 50):
            check_path(f"/evict-test/p{i}", cache)
        assert len(cache) <= PATH_CACHE_MAX_ENTRIES
        # the newest path was cached even though the cap was hit ...
        assert f"/evict-test/p{PATH_CACHE_MAX_ENTRIES + 49}" in cache
        # ... and oversized paths never are
        long_path = "/x" * 200
        check_path(long_path, cache)
        assert long_path not in cache

    def test_cache_is_per_instance_not_global(self):
        # ADVICE r5: one process-global cache let any noisy peer churn
        # the daemon's hot entries.  Validation through one cache (or
        # none at all — the server-side mode for untrusted peer paths)
        # must leave another client's cache untouched.
        from registrar_tpu.zk.protocol import PathCache, check_path

        mine, theirs = PathCache(max_entries=4), PathCache(max_entries=4)
        check_path("/my/hot/path", mine)
        # a hostile stream of unique valid paths through ANOTHER cache...
        for i in range(100):
            check_path(f"/thrash/p{i}", theirs)
        # ...and through no cache at all (the server-side mode)...
        for i in range(100):
            check_path(f"/uncached/p{i}")
        # ...cannot evict this client's hot entry.
        assert "/my/hot/path" in mine
        assert len(theirs) <= 4

    def test_client_owns_a_path_cache(self):
        # The ZKClient wires a per-instance cache into every validation.
        from registrar_tpu.zk.client import ZKClient
        from registrar_tpu.zk.protocol import PathCache

        client = ZKClient([("127.0.0.1", 2181)])
        assert isinstance(client._path_cache, PathCache)
        assert client._path_cache is not ZKClient(
            [("127.0.0.1", 2181)]
        )._path_cache

    def test_zero_capacity_cache_is_disabled_not_a_crash(self):
        from registrar_tpu.zk.protocol import PathCache, check_path

        off = PathCache(max_entries=0)
        assert check_path("/a", off) == "/a"  # validates, caches nothing
        assert len(off) == 0 and "/a" not in off
