"""The bench regression gate: compare logic, retry merge, and exit wiring.

Round-2 directive #7: bench.py must fail when a metric regresses >10%
against the checked-in BENCH_BASELINE.json — so a slowdown is caught by
CI/the driver instead of a judge eyeballing two JSONs.  The full bench
is exercised by CI's bench step and the driver; these tests pin the
gate's decision logic and the process exit code without paying for real
benchmark runs.
"""

import json
import os
import subprocess
import sys

import pytest

import bench

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASELINE = {
    "tolerance_pct": 10,
    "metrics": {
        "register_to_visible_ms": {"value": 1000, "direction": "lower"},
        "pipeline_ms_no_settle": {"value": 1.0, "direction": "lower"},
        "concurrent_registrations_per_s": {"value": 2000, "direction": "higher"},
        "daemon_rss_mb": {"value": 30.0, "direction": "lower"},
    },
}


def _result(value=1000.0, pipeline=1.0, conc=2000.0, rss=25.0):
    return {
        "metric": "register_to_visible_ms",
        "value": value,
        "unit": "ms",
        "vs_baseline": 1.0,
        "extra": {
            "baseline": "prose, not a number",
            "pipeline_ms_no_settle": pipeline,
            "concurrent_registrations_per_s": conc,
            "daemon_rss_mb": rss,
        },
    }


class TestGateLogic:
    def test_at_baseline_passes(self):
        assert bench.gate(_result(), BASELINE, 10) == []

    def test_within_tolerance_passes(self):
        # ratio-symmetric: higher-is-better floor is 2000/1.1 = 1818.2
        res = _result(value=1099.0, pipeline=1.09, conc=1850.0)
        assert bench.gate(res, BASELINE, 10) == []

    def test_lower_is_better_regression_fails(self):
        res = _result(pipeline=1.11)  # 11% over
        failures = bench.gate(res, BASELINE, 10)
        assert len(failures) == 1
        assert failures[0].startswith("pipeline_ms_no_settle:")

    def test_higher_is_better_regression_fails(self):
        res = _result(conc=1810.0)  # below 2000/1.1
        failures = bench.gate(res, BASELINE, 10)
        assert len(failures) == 1
        assert failures[0].startswith("concurrent_registrations_per_s:")

    def test_wide_tolerance_still_gates_throughput_collapse(self):
        # At tolerance >= 100% a subtractive bound would pass ANY value;
        # the ratio bound keeps gating: floor at 300% is 2000/4 = 500.
        res = _result(conc=499.0)
        failures = bench.gate(res, BASELINE, 300)
        assert len(failures) == 1
        assert failures[0].startswith("concurrent_registrations_per_s:")
        assert bench.gate(_result(conc=501.0), BASELINE, 300) == []

    def test_headline_metric_gated_too(self):
        failures = bench.gate(_result(value=1101.0), BASELINE, 10)
        assert len(failures) == 1
        assert failures[0].startswith("register_to_visible_ms:")

    def test_none_metric_skipped(self):
        res = _result()
        res["extra"]["daemon_rss_mb"] = None  # off-Linux
        assert bench.gate(res, BASELINE, 10) == []

    def test_missing_metric_is_a_regression(self):
        res = _result()
        del res["extra"]["pipeline_ms_no_settle"]
        failures = bench.gate(res, BASELINE, 10)
        assert failures == ["pipeline_ms_no_settle: missing from bench output"]

    def test_env_tolerance_override(self, monkeypatch):
        monkeypatch.setenv("BENCH_TOLERANCE_PCT", "50")
        res = _result(pipeline=1.4)  # 40% over: fails at 10%, passes at 50%
        assert bench.gate(res, BASELINE) == []
        monkeypatch.setenv("BENCH_TOLERANCE_PCT", "10")
        assert bench.gate(res, BASELINE) != []

    def test_best_of_is_direction_aware(self):
        a = _result(value=1200.0, pipeline=0.9, conc=1500.0)
        b = _result(value=1000.0, pipeline=1.2, conc=2100.0)
        best = bench.best_of(a, b, BASELINE)
        assert best["register_to_visible_ms"] == 1000.0  # lower wins
        assert best["pipeline_ms_no_settle"] == 0.9
        assert best["concurrent_registrations_per_s"] == 2100.0  # higher wins

    def test_checked_in_baseline_is_well_formed(self):
        baseline = bench.load_baseline()
        assert baseline is not None
        assert baseline["tolerance_pct"] == 10
        for name, spec in baseline["metrics"].items():
            assert spec["direction"] in ("lower", "higher"), name
            assert isinstance(spec["value"], (int, float)), name


class TestBaselineGovernance:
    """Round-4 verdict #6: the baseline is GENERATED from the append-only
    BENCH_HISTORY.json by rule (per-metric best across rounds, fixed
    headroom) — a hand-nudged baseline without a matching history entry
    fails `make check` (bench.py --check-baseline) and these tests."""

    HISTORY = {
        "headroom_pct": 15,
        "tolerance_pct": 10,
        "directions": {
            "pipeline_ms_no_settle": "lower",
            "concurrent_registrations_per_s": "higher",
        },
        "rounds": [
            {"round": "a", "metrics": {"pipeline_ms_no_settle": 0.9,
                                       "concurrent_registrations_per_s": 2000}},
            {"round": "b", "metrics": {"pipeline_ms_no_settle": 0.8,
                                       "concurrent_registrations_per_s": 2500}},
            {"round": "c", "metrics": {"pipeline_ms_no_settle": 1.1}},
        ],
    }

    def test_rule_is_best_of_rounds_with_headroom(self):
        out = bench.baseline_from_history(self.HISTORY)
        # lower-is-better: best 0.8 * 1.15; higher: best 2500 * 0.85.
        assert out["metrics"]["pipeline_ms_no_settle"] == {
            "value": 0.92, "direction": "lower",
        }
        assert out["metrics"]["concurrent_registrations_per_s"] == {
            "value": 2125.0, "direction": "higher",
        }
        assert out["tolerance_pct"] == 10

    def test_metric_missing_from_every_round_is_an_error(self):
        bad = {**self.HISTORY, "directions": {"ghost_metric": "lower"}}
        with pytest.raises(ValueError, match="ghost_metric"):
            bench.baseline_from_history(bad)

    def test_checked_in_baseline_matches_rule_of_history(self):
        # THE governance assertion: the shipped baseline is exactly
        # rule(shipped history) — any hand edit diverges and fails here.
        assert bench.check_baseline() == []

    def test_hand_nudged_baseline_is_detected(self, tmp_path):
        nudged = bench.baseline_from_history(bench.load_history())
        nudged["metrics"]["concurrent_registrations_per_s"]["value"] -= 200
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps(nudged))
        problems = bench.check_baseline(baseline_path=str(p))
        assert len(problems) == 1
        assert problems[0].startswith("concurrent_registrations_per_s:")

    def test_repin_writes_rule_output(self, tmp_path):
        hist = tmp_path / "history.json"
        hist.write_text(json.dumps(self.HISTORY))
        bl = tmp_path / "baseline.json"
        bench.repin(history_path=str(hist), baseline_path=str(bl))
        assert bench.check_baseline(
            history_path=str(hist), baseline_path=str(bl)
        ) == []

    def test_history_rounds_cover_every_gated_metric(self):
        # The shipped history must produce a baseline covering the same
        # metric set the gate relies on — losing a metric from the
        # history silently ungates it.
        history = bench.load_history()
        baseline = bench.load_baseline()
        assert set(history["directions"]) == set(baseline["metrics"])


class TestGateExitWiring:
    """The process-level contract: one JSON line on stdout; exit 1 plus a
    stderr report on regression.  Uses a stubbed _bench so the test does
    not pay for (or flake on) real benchmark runs."""

    def _run(self, baseline: dict, fake_value, extra_env: dict = None):
        """``fake_value``: one headline value per run; the last repeats if
        retries outnumber the supplied values."""
        values = (
            list(fake_value)
            if isinstance(fake_value, (list, tuple))
            else [fake_value]
        )
        stub = f"""
import asyncio, json, sys
sys.path.insert(0, {REPO!r})
import bench

values = {values!r}

async def fake_bench():
    v = values.pop(0) if len(values) > 1 else values[0]
    return {{
        "metric": "register_to_visible_ms", "value": v,
        "unit": "ms", "vs_baseline": 1.0,
        "extra": {{"pipeline_ms_no_settle": 1.0,
                   "concurrent_registrations_per_s": 2000.0,
                   "daemon_rss_mb": 25.0}},
    }}

bench._bench = fake_bench
sys.exit(bench.main())
"""
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            bl_path = os.path.join(td, "baseline.json")
            with open(bl_path, "w", encoding="utf-8") as f:
                json.dump(baseline, f)
            env = {**os.environ, "PYTHONPATH": REPO,
                   "BENCH_BASELINE_PATH": bl_path, "BENCH_GATE": "1",
                   **(extra_env or {})}
            # hermetic: an exported tolerance (e.g. from reproducing the
            # CI bench step locally) must not flip these outcomes
            if "BENCH_TOLERANCE_PCT" not in (extra_env or {}):
                env.pop("BENCH_TOLERANCE_PCT", None)
            return subprocess.run(
                [sys.executable, "-c", stub],
                capture_output=True, text=True, timeout=60, cwd=REPO,
                env=env,
            )

    def test_pass_exits_zero_with_one_json_line(self):
        out = self._run(BASELINE, fake_value=1000.0)
        assert out.returncode == 0, out.stderr
        lines = out.stdout.strip().splitlines()
        assert len(lines) == 1
        assert json.loads(lines[0])["metric"] == "register_to_visible_ms"

    def test_noise_recovers_on_retry(self):
        # The retry's whole point: one contended run must not fail the
        # round.  First run 20% over, retry clean -> exit 0, no
        # regression report, and the printed line is the latest run.
        out = self._run(BASELINE, fake_value=[1200.0, 1000.0])
        assert out.returncode == 0, out.stderr
        assert "(attempt 1)" in out.stderr
        assert "REGRESSION" not in out.stderr
        lines = out.stdout.strip().splitlines()
        assert len(lines) == 1
        assert json.loads(lines[0])["value"] == 1000.0

    def test_regression_exits_one_after_retry(self):
        out = self._run(BASELINE, fake_value=1200.0)  # 20% over, every run
        assert out.returncode == 1
        # a genuine regression burns both retries before failing
        assert "(attempt 1)" in out.stderr
        assert "(attempt 2)" in out.stderr
        assert "REGRESSION vs BENCH_BASELINE.json" in out.stderr
        assert "register_to_visible_ms" in out.stderr
        # the output contract holds even on failure: one JSON line
        assert len(out.stdout.strip().splitlines()) == 1

    def test_gate_disabled_by_env(self):
        # 9999 ms is a 10x regression; BENCH_GATE=0 must wave it through.
        out = self._run(BASELINE, fake_value=9999.0,
                        extra_env={"BENCH_GATE": "0"})
        assert out.returncode == 0


class TestBenchMetricsDeclaration:
    """ISSUE 11: BENCH_METRICS is the declared metric-name registry —
    the runtime twin of checklib's bench-metric-drift rule."""

    def test_history_directions_match_declaration(self):
        with open(os.path.join(REPO, "BENCH_HISTORY.json")) as fh:
            directions = json.load(fh)["directions"]
        for name, direction in directions.items():
            assert bench.BENCH_METRICS.get(name) == direction, (
                f"history pins {name!r} as {direction!r} but BENCH_METRICS "
                f"declares {bench.BENCH_METRICS.get(name)!r}"
            )

    def test_baseline_metrics_are_declared(self):
        baseline = bench.load_baseline()
        assert baseline is not None
        for name in baseline["metrics"]:
            assert name in bench.BENCH_METRICS

    def test_undeclared_emitted_metric_fails_gate(self):
        res = _result()
        res["extra"]["rogue_metric_ms"] = 1.0
        failures = bench.gate(res, BASELINE, 10)
        assert any("rogue_metric_ms" in f and "BENCH_METRICS" in f
                   for f in failures)

    def test_declared_metrics_pass_declaration_check(self):
        # the canonical result shape emits only declared names
        failures = bench.gate(_result(), BASELINE, 10)
        assert not any("BENCH_METRICS" in f for f in failures)

    def test_hist_quantile_names_are_declared_literals(self):
        for _q, name in bench.HIST_QUANTILE_METRICS:
            assert bench.BENCH_METRICS.get(name) == "lower"
