"""Integration tests: our ZK client against the in-process ZK server.

Unlike the reference's tests (which need a live ZooKeeper at
127.0.0.1:2181, reference test/helper.js:57-62), these run hermetically —
but still over a real TCP socket, exercising framing, jute encoding, xid
ordering, watches, and session semantics end to end.

Covers the reference's connection tests (reference test/zk.test.js) plus
the session/ephemeral behavior the reference never tests.
"""

import asyncio
import time

import pytest

from registrar_tpu.retry import RetryPolicy
from registrar_tpu.testing.netem import DOWN, UP, Blackhole, ChaosProxy
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import (
    OwnershipError,
    ZKClient,
    create_zk_client,
)
from registrar_tpu.zk import protocol as proto
from registrar_tpu.zk.protocol import (
    OPEN_ACL_UNSAFE,
    CreateFlag,
    Err,
    OpCode,
    ZKError,
)


async def _pair(**kw):
    server = await ZKServer().start()
    client = await ZKClient([server.address], **kw).connect()
    return server, client


class TestConnect:
    async def test_connect_and_close(self):
        server = await ZKServer().start()
        try:
            client = await ZKClient([server.address]).connect()
            assert client.connected
            assert client.session_id != 0
            # the patched-on heartbeat surface exists
            # (reference test/zk.test.js:54-71 asserts the same)
            assert callable(client.heartbeat)
            await client.close()
            assert not client.connected
        finally:
            await server.stop()

    async def test_connect_failure_dead_port(self):
        # reference test/zk.test.js:30-51: point at a dead port, bounded
        # retry, expect an error.
        client = ZKClient([("127.0.0.1", 1)], connect_timeout_ms=100)
        with pytest.raises(Exception):
            await client.connect()

    async def test_create_zk_client_retries_then_aborts(self):
        attempts = []
        task = asyncio.ensure_future(
            create_zk_client(
                [("127.0.0.1", 1)],
                connect_timeout_ms=50,
                on_attempt=lambda n, d, e: attempts.append(n),
                retry_policy=RetryPolicy(
                    max_attempts=float("inf"), initial_delay=0.01, max_delay=0.05
                ),
            )
        )
        await asyncio.sleep(0.3)
        assert len(attempts) >= 2  # kept retrying (failAfter(Infinity) analog)
        task.cancel()  # the retry.stop() analog
        with pytest.raises(asyncio.CancelledError):
            await task

    async def test_failover_to_live_server_in_list(self):
        # An ensemble list with dead members: connect() must find the live
        # one (the reference relies on zkplus for this).
        server = await ZKServer().start()
        try:
            client = await ZKClient(
                [("127.0.0.1", 1), server.address, ("127.0.0.1", 2)],
                connect_timeout_ms=200,
            ).connect()
            assert client.connected
            await client.create("/failover", b"")
            await client.close()
        finally:
            await server.stop()

    async def test_timeout_negotiation_clamped(self):
        server = await ZKServer(max_session_timeout_ms=5000).start()
        try:
            client = await ZKClient([server.address], timeout_ms=99999).connect()
            assert client.negotiated_timeout_ms == 5000
            await client.close()
        finally:
            await server.stop()


class TestOps:
    async def test_create_get_stat_roundtrip(self):
        server, client = await _pair()
        try:
            path = await client.create("/a", b"hello")
            assert path == "/a"
            data, stat = await client.get("/a")
            assert data == b"hello"
            assert stat.ephemeral_owner == 0
            st = await client.stat("/a")
            assert st.data_length == 5
        finally:
            await client.close()
            await server.stop()

    async def test_ephemeral_create_sets_owner(self):
        server, client = await _pair()
        try:
            await client.create("/eph", b"x", CreateFlag.EPHEMERAL)
            st = await client.stat("/eph")
            assert st.ephemeral_owner == client.session_id
        finally:
            await client.close()
            await server.stop()

    async def test_set_data_plain_semantics(self):
        # Unlike put (zkplus create-if-missing), set_data is the raw op:
        # NO_NODE when absent, BAD_VERSION on mismatch.
        server, client = await _pair()
        try:
            with pytest.raises(ZKError) as exc:
                await client.set_data("/absent", b"x")
            assert exc.value.code == Err.NO_NODE

            await client.create("/n", b"v0")
            with pytest.raises(ZKError) as exc:
                await client.set_data("/n", b"v1", version=9)
            assert exc.value.code == Err.BAD_VERSION

            stat = await client.set_data("/n", b"v1", version=0)
            assert stat.version == 1
            assert (await client.get("/n"))[0] == b"v1"
        finally:
            await client.close()
            await server.stop()

    async def test_mkdirp_and_nested_create(self):
        server, client = await _pair()
        try:
            await client.mkdirp("/com/joyent/us-east/moray")
            await client.create("/com/joyent/us-east/moray/1", b"{}")
            kids = await client.get_children("/com/joyent/us-east/moray")
            assert kids == ["1"]
            # idempotent
            await client.mkdirp("/com/joyent/us-east/moray")
        finally:
            await client.close()
            await server.stop()

    async def test_mkdirp_pipelined_edge_shapes(self):
        # The pipelined mkdirp (one drain for all ancestor creates) must
        # keep the sequential walk's outcome across the shapes that
        # matter: depth 1, deep chains, shared prefixes, repeats, and
        # many clients racing overlapping paths.
        server, client = await _pair()
        try:
            await client.mkdirp("/solo")
            assert (await client.stat("/solo")).ephemeral_owner == 0
            deep = "/" + "/".join(f"d{i}" for i in range(8))
            await client.mkdirp(deep)
            assert await client.exists(deep) is not None
            # Shared prefix: only the new suffix is created, prefix stats
            # (cversion bumps aside) are untouched.
            before = await client.stat("/d0/d1")
            await client.mkdirp("/d0/d1/other/branch")
            after = await client.stat("/d0/d1")
            assert after.version == before.version  # data untouched
            assert await client.exists("/d0/d1/other/branch") is not None

            # Concurrent overlapping mkdirps from independent sessions:
            # every NODE_EXISTS race inside the fan-out must be absorbed.
            racers = [
                await ZKClient([server.address]).connect() for _ in range(8)
            ]
            try:
                await asyncio.gather(
                    *(
                        c.mkdirp(f"/race/shared/deep/c{i % 3}")
                        for i, c in enumerate(racers)
                    )
                )
            finally:
                for c in racers:
                    await c.close()
            kids = sorted(await client.get_children("/race/shared/deep"))
            assert kids == ["c0", "c1", "c2"]

            # A mid-chain failure reports the root cause: the parent is
            # an ephemeral node, so the child create under it fails with
            # NO_CHILDREN_FOR_EPHEMERALS (not the cascaded NO_NODE).
            await client.create("/eph", b"", CreateFlag.EPHEMERAL)
            with pytest.raises(ZKError) as ei:
                await client.mkdirp("/eph/below/further")
            assert ei.value.code == Err.NO_CHILDREN_FOR_EPHEMERALS
        finally:
            await client.close()
            await server.stop()

    async def test_ephemeral_plus_creates_missing_parent(self):
        server, client = await _pair()
        try:
            await client.create_ephemeral_plus("/x/y/z", b"d")
            st = await client.stat("/x/y/z")
            assert st.ephemeral_owner == client.session_id
            # parents are persistent
            assert (await client.stat("/x/y")).ephemeral_owner == 0
        finally:
            await client.close()
            await server.stop()

    async def test_put_creates_then_updates(self):
        server, client = await _pair()
        try:
            await client.put("/svc", b"v1")  # node absent -> created
            data, _ = await client.get("/svc")
            assert data == b"v1"
            await client.put("/svc", b"v2")  # node present -> setData
            data, stat = await client.get("/svc")
            assert data == b"v2"
            assert stat.version == 1
        finally:
            await client.close()
            await server.stop()

    async def test_unlink_and_no_node(self):
        server, client = await _pair()
        try:
            await client.create("/gone", b"")
            await client.unlink("/gone")
            with pytest.raises(ZKError) as ei:
                await client.unlink("/gone")
            assert ei.value.name == "NO_NODE"  # upper layers match this name
        finally:
            await client.close()
            await server.stop()

    async def test_delete_nonempty_rejected(self):
        server, client = await _pair()
        try:
            await client.mkdirp("/p/c")
            with pytest.raises(ZKError) as ei:
                await client.unlink("/p")
            assert ei.value.code == Err.NOT_EMPTY
        finally:
            await client.close()
            await server.stop()

    async def test_create_under_ephemeral_rejected(self):
        server, client = await _pair()
        try:
            await client.create("/e", b"", CreateFlag.EPHEMERAL)
            with pytest.raises(ZKError) as ei:
                await client.create("/e/child", b"")
            assert ei.value.code == Err.NO_CHILDREN_FOR_EPHEMERALS
        finally:
            await client.close()
            await server.stop()

    async def test_many_parallel_ops_keep_xid_order(self):
        server, client = await _pair()
        try:
            await asyncio.gather(
                *(client.create(f"/n{i}", str(i).encode()) for i in range(50))
            )
            datas = await asyncio.gather(*(client.get(f"/n{i}") for i in range(50)))
            assert [d for d, _ in datas] == [str(i).encode() for i in range(50)]
        finally:
            await client.close()
            await server.stop()

    async def test_get_many_aligns_results_with_paths(self):
        server, client = await _pair()
        try:
            await client.create("/gm1", b"one")
            await client.create("/gm2", b"two")
            results = await client.get_many(["/gm1", "/absent", "/gm2"])
            assert results[0][0] == b"one"
            assert results[1] is None  # NO_NODE is an expected answer
            assert results[2][0] == b"two"
            assert results[0][1].data_length == 3
        finally:
            await client.close()
            await server.stop()

    async def test_get_many_rejects_malformed_paths_upfront(self):
        server, client = await _pair()
        try:
            with pytest.raises(ValueError):
                await client.get_many(["/ok", "not-absolute"])
        finally:
            await client.close()
            await server.stop()

    async def test_get_many_propagates_server_errors(self):
        # Only NO_NODE maps to None; a real server error (here NO_AUTH
        # from an ACL-protected node) must raise, not be swallowed.
        from registrar_tpu.zk.protocol import ACL, Perms, digest_auth_id

        server, client = await _pair()
        try:
            await client.create("/gmopen", b"x")
            await client.create(
                "/gmlocked",
                b"y",
                acls=[ACL(Perms.ALL, "digest", digest_auth_id("u", "pw"))],
            )
            with pytest.raises(ZKError) as ei:
                await client.get_many(["/gmopen", "/gmlocked"])
            assert ei.value.name == "NO_AUTH"
        finally:
            await client.close()
            await server.stop()

    async def test_get_many_empty(self):
        server, client = await _pair()
        try:
            assert await client.get_many([]) == []
        finally:
            await client.close()
            await server.stop()


class TestHeartbeat:
    async def test_heartbeat_ok(self):
        server, client = await _pair()
        try:
            await client.create("/hb1", b"")
            await client.create("/hb2", b"")
            await client.heartbeat(["/hb1", "/hb2"])  # should not raise
        finally:
            await client.close()
            await server.stop()

    async def test_heartbeat_fails_after_bounded_retries(self):
        server, client = await _pair()
        try:
            fast = RetryPolicy(max_attempts=3, initial_delay=0.01, max_delay=0.02)
            with pytest.raises(ZKError) as ei:
                await client.heartbeat(["/missing"], retry=fast)
            assert ei.value.name == "NO_NODE"
        finally:
            await client.close()
            await server.stop()

    async def test_heartbeat_foreign_ephemeral_raises_ownership_error(self):
        # ISSUE 3 satellite: an ephemeral held by ANOTHER session passed
        # the bare existence probe forever (zombie predecessor, hijacking
        # duplicate) — it must now fail with the distinct OwnershipError,
        # without burning the retry budget (the foreign session holds the
        # node until it dies; retrying cannot help).
        server, client = await _pair()
        other = await ZKClient([server.address]).connect()
        try:
            await other.create("/hijacked", b"{}", CreateFlag.EPHEMERAL)
            with pytest.raises(OwnershipError) as ei:
                await client.heartbeat(["/hijacked"])
            assert ei.value.path == "/hijacked"
            assert ei.value.owner == other.session_id
            assert ei.value.session == client.session_id
            assert "0x%x" % other.session_id in str(ei.value)
        finally:
            await other.close()
            await client.close()
            await server.stop()

    async def test_heartbeat_own_ephemeral_and_persistent_pass(self):
        # The ownership sweep must not flag the normal shapes: our own
        # ephemerals and the persistent service record (owner 0).
        server, client = await _pair()
        try:
            await client.create("/own-eph", b"", CreateFlag.EPHEMERAL)
            await client.create("/svc-rec", b"{}")  # persistent
            await client.heartbeat(["/own-eph", "/svc-rec"])  # no raise
        finally:
            await client.close()
            await server.stop()


class TestHeartbeatMany:
    """The coalesced sweep (ISSUE 11 tentpole): per-group contract
    identical to N solo heartbeats, one pipelined flush per attempt."""

    async def test_per_group_outcomes_are_independent(self):
        # A NO_NODE in one service's group must neither fail nor delay
        # another's; ownership failures stay scoped to their group too.
        server, client = await _pair()
        other = await ZKClient([server.address]).connect()
        try:
            await client.create("/sweep-ok", b"", CreateFlag.EPHEMERAL)
            await client.create("/sweep-ok2", b"")
            await other.create("/sweep-foreign", b"", CreateFlag.EPHEMERAL)
            fast = RetryPolicy(
                max_attempts=2, initial_delay=0.01, max_delay=0.02
            )
            outcomes = await client.heartbeat_many(
                [
                    ["/sweep-ok", "/sweep-ok2"],
                    ["/sweep-ok", "/sweep-missing"],
                    ["/sweep-foreign"],
                    [],
                ],
                retry=fast,
            )
            healthy, missing, foreign, empty = outcomes
            assert healthy is None and empty is None
            assert isinstance(missing, ZKError) and missing.name == "NO_NODE"
            assert isinstance(foreign, OwnershipError)
            assert foreign.owner == other.session_id
        finally:
            await other.close()
            await client.close()
            await server.stop()

    async def test_one_flush_per_attempt_across_groups(self):
        # The wire shape claim: all groups' EXISTS requests ride ONE
        # corked write + one drain per attempt.
        server, client = await _pair()
        try:
            paths = []
            for i in range(12):
                p = f"/co{i}"
                await client.create(p, b"", CreateFlag.EPHEMERAL)
                paths.append(p)
            groups = [paths[i * 3 : (i + 1) * 3] for i in range(4)]
            drains = {"n": 0}
            orig_drain = client._writer.drain

            async def counting_drain():
                drains["n"] += 1
                return await orig_drain()

            client._writer.drain = counting_drain
            assert await client.heartbeat_many(groups) == [None] * 4
            assert drains["n"] == 1, (
                f"coalesced sweep drained {drains['n']} times — the "
                "groups did not share one pipelined flush"
            )
        finally:
            await client.close()
            await server.stop()

    async def test_healthy_group_released_before_failing_groups_retry(self):
        # on_outcome fires the moment a group's verdict is final: a
        # healthy service must not wait out a failing sibling's backoff.
        server, client = await _pair()
        try:
            await client.create("/early-ok", b"", CreateFlag.EPHEMERAL)
            order = []
            slow = RetryPolicy(
                max_attempts=3, initial_delay=0.05, max_delay=0.05
            )
            import time as _time

            t0 = _time.monotonic()
            outcomes = await client.heartbeat_many(
                [["/early-ok"], ["/early-missing"]],
                retry=slow,
                on_outcome=lambda i, err: order.append(
                    (i, err, _time.monotonic() - t0)
                ),
            )
            assert outcomes[0] is None
            assert isinstance(outcomes[1], ZKError)
            by_group = dict((i, t) for i, _, t in order)
            # group 0 settled on attempt 1 (before any backoff sleep);
            # group 1 needed the full schedule (2 sleeps of 50 ms)
            assert by_group[0] < 0.04
            assert by_group[1] >= 0.08
        finally:
            await client.close()
            await server.stop()

    async def test_delegating_heartbeat_is_contract_identical(self):
        # heartbeat() is the one-group front of heartbeat_many: the
        # bounded-retry NO_NODE shape and the success shape both hold.
        server, client = await _pair()
        try:
            await client.create("/hb-front", b"", CreateFlag.EPHEMERAL)
            await client.heartbeat(["/hb-front"])
            fast = RetryPolicy(
                max_attempts=2, initial_delay=0.01, max_delay=0.01
            )
            with pytest.raises(ZKError) as ei:
                await client.heartbeat(["/hb-front", "/gone"], retry=fast)
            assert ei.value.name == "NO_NODE"
        finally:
            await client.close()
            await server.stop()


#: rebirth tests want convergence in milliseconds, not the 1-90 s
#: production envelope
_FAST_RECONNECT = RetryPolicy(
    max_attempts=float("inf"), initial_delay=0.02, max_delay=0.1
)


class TestSessionRebirth:
    """The in-process session lifecycle supervisor (ISSUE 3 tentpole)."""

    async def test_expiry_without_opt_in_is_terminal(self):
        # Reference parity: the default client treats expiry as the end —
        # session_expired fires, the client is closed, no rebirth.
        server, client = await _pair(reconnect_policy=_FAST_RECONNECT)
        try:
            reborn = []
            client.on("session_reborn", reborn.append)
            expired = asyncio.ensure_future(
                client.wait_for("session_expired", timeout=10)
            )
            await server.expire_session(client.session_id)
            await expired
            await asyncio.sleep(0.2)  # a rebirth would land in here
            assert client.closed
            assert not client.connected
            assert reborn == []
            assert client.rebirths == 0
        finally:
            await client.close()
            await server.stop()

    async def test_expiry_builds_fresh_session_in_process(self):
        server, client = await _pair(
            survive_session_expiry=True, reconnect_policy=_FAST_RECONNECT
        )
        try:
            expired = []
            client.on("session_expired", lambda *a: expired.append(a))
            old = client.session_id
            reborn = asyncio.ensure_future(
                client.wait_for("session_reborn", timeout=10)
            )
            await server.expire_session(old)
            (new_sid,) = await reborn
            assert new_sid == client.session_id != old
            assert client.connected and not client.closed
            assert client.rebirths == 1
            assert expired == []  # the terminal event never fired
            # the fresh session is fully usable
            await client.create("/reborn-proof", b"", CreateFlag.EPHEMERAL)
            st = await client.stat("/reborn-proof")
            assert st.ephemeral_owner == new_sid
        finally:
            await client.close()
            await server.stop()

    async def test_watch_listeners_survive_a_rebirth(self):
        # Watches registered before the expiry must not go silently dead:
        # the reborn session re-arms them (SetWatches from zxid 0 —
        # conservative delivery is fine, silence is not).
        server, client = await _pair(
            survive_session_expiry=True, reconnect_policy=_FAST_RECONNECT
        )
        try:
            await client.create("/watched-across", b"v1")
            events = []
            client.watch("/watched-across", events.append)
            await client.get("/watched-across", watch=True)
            reborn = asyncio.ensure_future(
                client.wait_for("session_reborn", timeout=10)
            )
            await server.expire_session(client.session_id)
            await reborn
            for _ in range(100):
                if events:
                    break
                await asyncio.sleep(0.02)
            assert events, "watch went dead across the rebirth"
        finally:
            await client.close()
            await server.stop()

    async def test_rebirth_survives_a_drop_in_the_handshake_tail(
        self, monkeypatch
    ):
        # The fresh-session handshake's TAIL (auth replay, watch re-arm)
        # can die on the same turbulence that expired the session.  The
        # rebirth marker must survive the aborted attempt so the retry —
        # which REATTACHES the already-created fresh session — still
        # announces session_reborn; consuming it early loses the event
        # and the agent never re-registers.
        server, client = await _pair(
            survive_session_expiry=True, reconnect_policy=_FAST_RECONNECT
        )
        try:
            real_replay = client._replay_auths
            fail = {"armed": False}

            async def flaky_replay():
                if fail["armed"]:
                    fail["armed"] = False
                    await client._teardown(expected=False)
                    raise ConnectionError("handshake tail died")
                await real_replay()

            monkeypatch.setattr(client, "_replay_auths", flaky_replay)
            reborn = asyncio.ensure_future(
                client.wait_for("session_reborn", timeout=10)
            )
            fail["armed"] = True  # kill the tail of the NEXT connect
            await server.expire_session(client.session_id)
            (sid,) = await reborn
            assert sid == client.session_id != 0
            assert client.rebirths == 1
            assert client.connected and not client.closed
        finally:
            await client.close()
            await server.stop()

    async def test_circuit_breaker_falls_back_to_terminal_expiry(self):
        server, client = await _pair(
            survive_session_expiry=True,
            max_session_rebirths=2,
            reconnect_policy=_FAST_RECONNECT,
        )
        try:
            trips = []
            client.on("rebirth_breaker_tripped", trips.append)
            for _ in range(2):
                reborn = asyncio.ensure_future(
                    client.wait_for("session_reborn", timeout=10)
                )
                await server.expire_session(client.session_id)
                await reborn
            assert client.rebirths == 2
            # The third expiry inside the window exceeds the bound: the
            # reference-exact terminal path (exit(1) upstairs) applies.
            expired = asyncio.ensure_future(
                client.wait_for("session_expired", timeout=10)
            )
            await server.expire_session(client.session_id)
            await expired
            assert trips == [2]
            assert client.closed
            assert client.rebirths == 2  # no third rebirth
        finally:
            await client.close()
            await server.stop()

    def test_max_session_rebirths_validated(self):
        with pytest.raises(ValueError):
            ZKClient([("127.0.0.1", 2181)], max_session_rebirths=0)


class TestConstructorValidation:
    def test_empty_server_list_rejected(self):
        with pytest.raises(ValueError):
            ZKClient([])

    def test_malformed_server_entries_rejected(self):
        # A 2-tuple with the wrong field types reaches the isinstance
        # guard itself (a "host:port" string would fail earlier, at
        # tuple unpacking, leaving the guard uncovered).
        with pytest.raises(ValueError):
            ZKClient([("127.0.0.1", "2181")])  # port must be an int

    async def test_add_auth_scheme_must_be_nonempty(self):
        server, client = await _pair()
        try:
            with pytest.raises(ValueError):
                await client.add_auth("", b"cred")
        finally:
            await client.close()
            await server.stop()


class TestAttachPreference:
    """The connect-order hint (ISSUE 12): spread/follower placement for
    read-heavy fleets, with 'any' staying reference-exact."""

    SERVERS = [("10.0.0.1", 2181), ("10.0.0.2", 2181), ("10.0.0.3", 2181)]

    def test_invalid_preference_rejected_at_construction(self):
        for bad in ("spread", "spread:1-of-", "spread:3-of-3",
                    "spread:-1-of-2", "leader", ""):
            with pytest.raises(ValueError):
                ZKClient(self.SERVERS, attach_preference=bad)

    async def test_spread_rotation_is_deterministic(self):
        import random as random_mod

        # Worker k of n starts its pass at a distinct rotation of the
        # CONFIGURED order — and the seeded shuffle is deliberately NOT
        # applied (the documented rng interaction: two workers with
        # different slots must not converge by shuffle luck).
        starts = set()
        for k in range(3):
            orders = []
            for seed in (1, 2):  # different rngs, same order expected
                client = ZKClient(
                    self.SERVERS,
                    attach_preference=f"spread:{k}-of-3",
                    rng=random_mod.Random(seed),
                )
                orders.append(await client._connect_order())
            assert orders[0] == orders[1]
            assert sorted(orders[0]) == sorted(self.SERVERS)
            starts.add(orders[0][0])
        assert starts == set(self.SERVERS)  # all three slots distinct

    async def test_any_keeps_the_seeded_shuffle(self):
        import random as random_mod

        client = ZKClient(
            self.SERVERS, attach_preference="any",
            rng=random_mod.Random(42),
        )
        expected = list(self.SERVERS)
        random_mod.Random(42).shuffle(expected)
        assert await client._connect_order() == expected

    async def test_follower_preference_avoids_the_leader(self):
        from registrar_tpu.testing.server import ZKEnsemble

        ens = await ZKEnsemble(3).start()
        try:
            leader_addr = ens.servers[ens.leader_index].address
            # The probe-ordered pass puts the leader LAST, whatever the
            # shuffle said — across several seeds, so this is the
            # probe's doing, not shuffle luck.
            import random as random_mod

            for seed in (1, 2, 3):
                client = ZKClient(
                    ens.addresses, attach_preference="follower",
                    rng=random_mod.Random(seed), reconnect=False,
                )
                order = await client._connect_order()
                assert order[-1] == leader_addr
            # ...and a real connect lands on a follower.
            client = ZKClient(
                ens.addresses, attach_preference="follower",
                reconnect=False,
            )
            await client.connect()
            try:
                assert client.connected_server != leader_addr
            finally:
                await client.close()
        finally:
            await ens.stop()

    async def test_follower_probe_failure_leaves_order_alone(self):
        import random as random_mod

        # Nothing answers srvr on these ports: the hint must not make
        # an unreachable ensemble less reachable — order falls back to
        # the plain seeded shuffle.
        client = ZKClient(
            [("127.0.0.1", 1), ("127.0.0.1", 2), ("127.0.0.1", 3)],
            attach_preference="follower",
            rng=random_mod.Random(7),
            connect_timeout_ms=200,
        )
        expected = [("127.0.0.1", 1), ("127.0.0.1", 2), ("127.0.0.1", 3)]
        random_mod.Random(7).shuffle(expected)
        assert await client._connect_order() == expected

    async def test_create_zk_client_passes_the_hint_through(self):
        server = await ZKServer().start()
        try:
            client = await create_zk_client(
                [server.address], attach_preference="spread:0-of-2",
            )
            try:
                assert client.attach_preference == "spread:0-of-2"
                assert client.connected
            finally:
                await client.close()
        finally:
            await server.stop()


class TestBurstInterruption:
    async def test_replies_before_malformed_frame_are_delivered(self):
        # A burst of [valid request, malformed frame]: the server kills
        # the connection at the bad frame, but the reply already
        # generated for the valid request must still be delivered first
        # (pre-batching each reply went out immediately).
        server, client = await _pair()
        try:
            client._cork()
            try:
                fut = client._post(
                    client._next_xid(), OpCode.CREATE,
                    proto.CreateRequest(
                        path="/pre-bad", data=b"",
                        acls=list(OPEN_ACL_UNSAFE),
                        flags=CreateFlag.PERSISTENT,
                    ),
                )
                # a COMPLETE frame whose 1-byte body cannot hold a header
                client._corked.append(b"\x00\x00\x00\x01\x00")
            finally:
                client._uncork()
            await client._writer.drain()
            r = await asyncio.wait_for(fut, timeout=5)
            assert proto.CreateResponse.read(r).path == "/pre-bad"
        finally:
            await client.close()
            await server.stop()

    async def test_server_stop_mid_sweep_fails_cleanly(self):
        # A 500-frame pipelined heartbeat interrupted by server death must
        # fail with a clean error (every posted future resolved), not hang.
        server, client = await _pair()
        try:
            paths = [f"/sw{i}" for i in range(500)]
            await asyncio.gather(
                *(client.create(p, b"", CreateFlag.EPHEMERAL) for p in paths)
            )
            fast = RetryPolicy(max_attempts=1, initial_delay=0.01, max_delay=0.02)
            hb = asyncio.ensure_future(client.heartbeat(paths, retry=fast))
            stop = asyncio.ensure_future(server.stop())
            with pytest.raises((ZKError, ConnectionError, OSError)):
                await asyncio.wait_for(hb, timeout=10)
            await stop
            assert not client._pending  # no zombie futures left behind
        finally:
            await client.close()
            await server.stop()


class TestSessions:
    async def test_ephemerals_vanish_on_close(self):
        server, client = await _pair()
        try:
            await client.create("/e1", b"", CreateFlag.EPHEMERAL)
            assert server.get_node("/e1") is not None
            await client.close()
            assert server.get_node("/e1") is None
        finally:
            await server.stop()

    async def test_ephemerals_vanish_on_session_expiry(self):
        server, client = await _pair(timeout_ms=200, reconnect=False)
        try:
            await client.create("/e2", b"", CreateFlag.EPHEMERAL)
            sid = client.session_id
            # Sever the TCP connection; the expiry countdown starts.
            await server.drop_connections()
            await asyncio.sleep(0.6)  # > negotiated timeout
            assert server.get_node("/e2") is None
            assert sid not in server.sessions
        finally:
            await client.close()
            await server.stop()

    async def test_reconnect_reattaches_session(self):
        server, client = await _pair(timeout_ms=5000)
        try:
            await client.create("/e3", b"", CreateFlag.EPHEMERAL)
            sid = client.session_id
            await server.drop_connections()
            await client.wait_for("connect", timeout=10)
            assert client.session_id == sid
            # ephemeral survived because the session never expired
            assert (await client.stat("/e3")).ephemeral_owner == sid
        finally:
            await client.close()
            await server.stop()

    async def test_rolling_restart_preserves_session_and_ephemerals(self):
        # A real ensemble keeps state across a member restart: the client
        # reattaches with the same session and its ephemerals survive.
        server = await ZKServer(port=0).start()
        port = server.port
        client = await ZKClient([("127.0.0.1", port)], timeout_ms=60000).connect()
        try:
            await client.create("/roll", b"x", CreateFlag.EPHEMERAL)
            sid = client.session_id
            reconnected = asyncio.Event()
            client.on("connect", lambda *a: reconnected.set())
            await server.stop()
            server = await ZKServer(port=port, snapshot=server).start()
            await asyncio.wait_for(reconnected.wait(), timeout=15)
            assert client.session_id == sid
            st = await client.stat("/roll")
            assert st.ephemeral_owner == sid
        finally:
            await client.close()
            await server.stop()

    async def test_session_expired_emitted_on_stale_reattach(self):
        server, client = await _pair(timeout_ms=200)
        try:
            await client.create("/e4", b"", CreateFlag.EPHEMERAL)
            expired = asyncio.Event()
            client.on("session_expired", lambda *a: expired.set())
            # Force-expire server-side, then let the client try to reattach.
            await server.expire_session(client.session_id)
            await asyncio.wait_for(expired.wait(), timeout=10)
            assert client.closed
        finally:
            await server.stop()

    async def test_unresponsive_server_detected_by_watchdog(self):
        # TCP stays up but the server stops answering: the client must
        # drop the connection within ~2/3 of the session timeout instead
        # of letting ops hang forever.
        server, client = await _pair(timeout_ms=600)
        try:
            await client.create("/alive", b"")
            server.freeze = True
            disconnected = asyncio.Event()
            client.on("close", lambda *a: disconnected.set())
            await asyncio.wait_for(disconnected.wait(), timeout=10)
            # after the server thaws, the reconnect loop restores service
            server.freeze = False
            reconnected = asyncio.Event()
            client.on("connect", lambda *a: reconnected.set())
            await asyncio.wait_for(reconnected.wait(), timeout=10)
            assert await client.exists("/alive") is not None
        finally:
            await client.close()
            await server.stop()

    async def test_freeze_mid_burst_delivers_pre_wedge_replies(self):
        # Reply batching must not let a wedge (freeze) retroactively
        # withhold replies already generated for earlier requests in the
        # same pipelined burst: those predate the wedge and are flushed.
        from registrar_tpu.testing.server import ZKServer

        class FreezeAfterFirst(ZKServer):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.froze = False

            async def _dispatch(self, conn, sess, hdr, r):
                reply = await super()._dispatch(conn, sess, hdr, r)
                if not self.froze:
                    self.froze = True
                    self.freeze = True
                return reply

        server = await FreezeAfterFirst().start()
        client = await ZKClient([server.address]).connect()
        try:
            server.froze = False  # the handshake/connect ops don't count
            server.freeze = False
            # One corked burst of two creates: the server dispatches the
            # first, wedges itself, then swallows the second.
            client._cork()
            try:
                f1 = client._post(
                    client._next_xid(), OpCode.CREATE,
                    proto.CreateRequest(
                        path="/pre-wedge", data=b"",
                        acls=list(OPEN_ACL_UNSAFE),
                        flags=CreateFlag.PERSISTENT,
                    ),
                )
                f2 = client._post(
                    client._next_xid(), OpCode.CREATE,
                    proto.CreateRequest(
                        path="/post-wedge", data=b"",
                        acls=list(OPEN_ACL_UNSAFE),
                        flags=CreateFlag.PERSISTENT,
                    ),
                )
            finally:
                client._uncork()
            await client._writer.drain()
            # the pre-wedge reply arrives...
            r1 = await asyncio.wait_for(f1, timeout=5)
            assert proto.CreateResponse.read(r1).path == "/pre-wedge"
            # ...while the post-wedge one is swallowed by the frozen server
            done, _pending = await asyncio.wait({f2}, timeout=0.3)
            assert not done
        finally:
            server.freeze = False
            await client.close()
            await server.stop()

    async def test_force_expire_notifies_connected_client(self):
        server, client = await _pair()
        try:
            states = []
            client.on("state", states.append)
            await server.expire_session(client.session_id)
            await asyncio.sleep(0.1)
            assert "disconnected" in states
        finally:
            await client.close()
            await server.stop()


class TestSessionHandoff:
    """ISSUE 5: detach-without-close + seed_session cross-"process" resume
    (two client OBJECTS standing in for two processes — the wire exchange
    is identical)."""

    async def test_detach_leaves_session_and_ephemerals_alive(self):
        server, client = await _pair(timeout_ms=5000)
        try:
            await client.create("/ho1", b"", CreateFlag.EPHEMERAL)
            sid = client.session_id
            await client.detach()
            assert client.closed
            # No CLOSE_SESSION went out: the session and its ephemeral
            # are still there for a successor.
            assert sid in server.sessions
            assert server.get_node("/ho1") is not None
        finally:
            await server.stop()

    async def test_seed_session_resumes_across_client_objects(self):
        server, client = await _pair(timeout_ms=5000)
        successor = None
        try:
            await client.create("/ho2", b"payload", CreateFlag.EPHEMERAL)
            sid, passwd = client.session_id, client.session_passwd
            timeout_ms = client.negotiated_timeout_ms
            zxid = client.last_zxid
            await client.detach()

            successor = ZKClient([server.address], timeout_ms=5000)
            resumed = []
            successor.on("session_resumed", resumed.append)
            successor.seed_session(
                sid, passwd, negotiated_timeout_ms=timeout_ms,
                last_zxid=zxid,
            )
            await successor.connect()
            assert successor.session_id == sid
            assert resumed == [sid]
            # The ephemeral never flickered and is OURS to operate on.
            st = await successor.stat("/ho2")
            assert st.ephemeral_owner == sid
            data, _ = await successor.get("/ho2")
            assert data == b"payload"
            # ... and a clean close now reaps it (the successor really
            # owns the session, not a lookalike).
            await successor.close()
            successor = None
            assert server.get_node("/ho2") is None
        finally:
            if successor is not None:
                await successor.close()
            await server.stop()

    async def test_refused_resume_falls_back_to_fresh_session(self):
        server, client = await _pair(timeout_ms=5000)
        successor = None
        try:
            await client.create("/ho3", b"", CreateFlag.EPHEMERAL)
            sid, passwd = client.session_id, client.session_passwd
            await client.detach()
            # The session dies in the handoff gap.
            await server.expire_session(sid)

            successor = ZKClient([server.address], timeout_ms=5000)
            refused = asyncio.Event()
            terminal = asyncio.Event()
            successor.on("resume_refused", lambda *a: refused.set())
            successor.on("session_expired", lambda *a: terminal.set())
            successor.seed_session(sid, passwd)
            # The refusing attempt surfaces SessionExpiredError but the
            # client stays OPEN, reset to a fresh handshake...
            with pytest.raises(ZKError):
                await successor.connect()
            assert refused.is_set()
            assert not terminal.is_set()
            assert not successor.closed
            assert successor.session_id == 0
            # ...and the next attempt builds a brand-new session.
            await successor.connect()
            assert successor.session_id not in (0, sid)
            await successor.create("/ho3b", b"", CreateFlag.EPHEMERAL)
        finally:
            if successor is not None and not successor.closed:
                await successor.close()
            await server.stop()

    async def test_wrong_passwd_resume_is_refused_not_adopted(self):
        server, client = await _pair(timeout_ms=5000)
        successor = None
        try:
            await client.create("/ho4", b"", CreateFlag.EPHEMERAL)
            sid = client.session_id
            await client.detach()

            successor = ZKClient([server.address], timeout_ms=5000)
            successor.seed_session(sid, b"\xff" * 16)
            with pytest.raises(ZKError):
                await successor.connect()
            await successor.connect()  # fresh session
            assert successor.session_id != sid
            # the REAL session (and its ephemeral) was not hijacked
            assert sid in server.sessions
            assert server.get_node("/ho4") is not None
        finally:
            if successor is not None and not successor.closed:
                await successor.close()
            await server.stop()

    async def test_seed_session_validates_inputs(self):
        server = await ZKServer().start()
        try:
            client = ZKClient([server.address])
            with pytest.raises(ValueError):
                client.seed_session(1, b"short")
            connected = await ZKClient([server.address]).connect()
            with pytest.raises(RuntimeError):
                connected.seed_session(1, b"\x00" * 16)
            await connected.close()
        finally:
            await server.stop()


class TestWatches:
    async def test_data_watch_fires_on_delete(self):
        server, client = await _pair()
        try:
            await client.create("/w", b"v")
            fired = asyncio.Event()
            events = []
            client.watch("/w", lambda ev: (events.append(ev), fired.set()))
            await client.stat("/w", watch=True)
            await client.unlink("/w")
            await asyncio.wait_for(fired.wait(), timeout=5)
            assert events[0].path == "/w"
        finally:
            await client.close()
            await server.stop()

    async def test_exist_watch_fires_on_create(self):
        server, client = await _pair()
        try:
            fired = asyncio.Event()
            client.watch("/later", lambda ev: fired.set())
            with pytest.raises(ZKError):
                await client.stat("/later", watch=True)  # NO_NODE, watch armed
            await client.create("/later", b"")
            await asyncio.wait_for(fired.wait(), timeout=5)
        finally:
            await client.close()
            await server.stop()

    async def test_missed_watch_delivered_after_reconnect(self):
        # A data watch armed before a disconnect must still deliver the
        # NodeDeleted that happened during the outage (SetWatches catch-up).
        server, client = await _pair(timeout_ms=10000)
        try:
            await client.create("/missed", b"v")
            fired = asyncio.Event()
            client.watch("/missed", lambda ev: fired.set())
            await client.stat("/missed", watch=True)
            reconnected = asyncio.Event()
            client.on("connect", lambda *a: reconnected.set())
            # Pause automatic reconnection so the deletion reliably happens
            # while `client` is offline.
            client.reconnect = False
            await server.drop_connections()
            other = await ZKClient([server.address]).connect()
            await other.unlink("/missed")
            await other.close()
            client.reconnect = True
            await client.connect()
            await asyncio.wait_for(reconnected.wait(), timeout=10)
            await asyncio.wait_for(fired.wait(), timeout=5)
        finally:
            await client.close()
            await server.stop()

    async def test_child_watch_fires(self):
        server, client = await _pair()
        try:
            await client.mkdirp("/dir")
            fired = asyncio.Event()
            client.watch("/dir", lambda ev: fired.set())
            await client.get_children("/dir", watch=True)
            await client.create("/dir/kid", b"")
            await asyncio.wait_for(fired.wait(), timeout=5)
        finally:
            await client.close()
            await server.stop()


class TestReadNode:
    """The pipelined data+children helper (ISSUE 4 satellite): one
    corked flush instead of sequential get + get_children waits."""

    async def test_reads_data_and_children_in_one_flush(self):
        server, client = await _pair()
        try:
            await client.mkdirp("/svc")
            await client.put("/svc", b'{"type":"service"}')
            await client.create("/svc/a", b"A")
            await client.create("/svc/b", b"B")
            drains = {"n": 0}
            orig_drain = client._writer.drain

            async def counting_drain():
                drains["n"] += 1
                return await orig_drain()

            client._writer.drain = counting_drain
            node = await client.read_node("/svc")
            assert drains["n"] == 1, "read_node paid more than one flush"
            data, stat, children = node
            assert data == b'{"type":"service"}'
            assert stat.num_children == 2
            assert sorted(children) == ["a", "b"]
        finally:
            await client.close()
            await server.stop()

    async def test_absent_node_returns_none(self):
        server, client = await _pair()
        try:
            assert await client.read_node("/nope") is None
        finally:
            await client.close()
            await server.stop()

    async def test_watch_arms_data_and_child_watches(self):
        server, client = await _pair()
        try:
            await client.mkdirp("/w")
            events = []
            client.watch("/w", events.append)
            await client.read_node("/w", watch=True)
            assert "/w" in client._watch_paths["data"]
            assert "/w" in client._watch_paths["child"]
            await client.set_data("/w", b"x")
            await client.create("/w/kid", b"")
            deadline = asyncio.get_running_loop().time() + 5
            while len(events) < 2:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.01)
            kinds = sorted(ev.type for ev in events)
            assert kinds == [
                proto.EventType.NODE_DATA_CHANGED,
                proto.EventType.NODE_CHILDREN_CHANGED,
            ]
        finally:
            await client.close()
            await server.stop()

    async def test_no_node_with_watch_leaves_no_bookkeeping(self):
        server, client = await _pair()
        try:
            assert await client.read_node("/ghost", watch=True) is None
            assert "/ghost" not in client._watch_paths["data"]
            assert "/ghost" not in client._watch_paths["child"]
            assert "/ghost" not in client._watch_paths["exist"]
        finally:
            await client.close()
            await server.stop()

    async def test_get_many_watch_arms_only_existing(self):
        server, client = await _pair()
        try:
            await client.mkdirp("/gm")
            await client.create("/gm/a", b"A")
            out = await client.get_many(["/gm/a", "/gm/ghost"], watch=True)
            assert out[0][0] == b"A" and out[1] is None
            assert "/gm/a" in client._watch_paths["data"]
            assert "/gm/ghost" not in client._watch_paths["data"]
            fired = asyncio.Event()
            client.watch("/gm/a", lambda ev: fired.set())
            await client.set_data("/gm/a", b"A2")
            await asyncio.wait_for(fired.wait(), timeout=5)
        finally:
            await client.close()
            await server.stop()

    async def test_forget_watches_drops_rearm_bookkeeping(self):
        server, client = await _pair()
        try:
            await client.mkdirp("/f")
            await client.read_node("/f", watch=True)
            client.forget_watches("/f")
            for kind in client._watch_paths.values():
                assert "/f" not in kind
        finally:
            await client.close()
            await server.stop()

    async def test_chrooted_read_node(self):
        server = await ZKServer().start()
        setup = await ZKClient([server.address]).connect()
        try:
            await setup.mkdirp("/app/svc")
            await setup.put("/app/svc", b"payload")
            await setup.create("/app/svc/kid", b"")
            client = await ZKClient(
                [server.address], chroot="/app"
            ).connect()
            try:
                data, _stat, children = await client.read_node("/svc")
                assert data == b"payload"
                assert children == ["kid"]
            finally:
                await client.close()
        finally:
            await setup.close()
            await server.stop()


class TestWatchRearmFailure:
    async def test_rearm_failure_emits_event(self):
        """A failed SetWatches re-arm must be observable: the zkcache
        degrades on it rather than serving entries whose coherence
        signal silently died."""
        server, client = await _pair(
            reconnect_policy=RetryPolicy(
                max_attempts=float("inf"), initial_delay=0.02, max_delay=0.2
            )
        )
        try:
            await client.mkdirp("/r")
            await client.get("/r", watch=True)
            failed = asyncio.Event()
            client.on("watch_rearm_failed", lambda err: failed.set())
            orig = client._submit

            async def failing_submit(xid, op, body):
                if op == OpCode.SET_WATCHES:
                    raise ZKError(Err.CONNECTION_LOSS)
                return await orig(xid, op, body)

            client._submit = failing_submit
            await server.drop_connections()
            await asyncio.wait_for(failed.wait(), timeout=10)
        finally:
            client._submit = orig
            await client.close()
            await server.stop()


class TestRacedConnect:
    """ISSUE 20 tentpole: happy-eyeballs staggered connects, opt-in via
    ``connect_race_stagger_ms``.  The serial reference pass must stay
    byte-identical when the knob is absent."""

    async def test_race_beats_hung_candidate(self):
        """A candidate that accepts TCP but never answers the handshake
        must not serialize the pass: the stagger releases the next
        member, which wins in milliseconds instead of after the hung
        one's full connect timeout."""

        async def _hold(reader, writer):
            try:
                await asyncio.sleep(30)
            finally:
                writer.close()

        hung = await asyncio.start_server(_hold, "127.0.0.1", 0)
        hung_addr = hung.sockets[0].getsockname()[:2]
        server = await ZKServer().start()
        client = None
        try:
            client = ZKClient(
                [tuple(hung_addr), server.address],
                connect_race_stagger_ms=30,
                connect_timeout_ms=3000,
                # spread:0-of-1 pins the candidate order (no shuffle):
                # the hung member is ALWAYS dialed first, so a fast
                # connect proves the race, not shuffle luck.
                attach_preference="spread:0-of-1",
            )
            t0 = time.monotonic()
            await client.connect()
            elapsed = time.monotonic() - t0
            assert client.connected
            # Far under the 3s the serial pass would burn waiting out
            # the hung candidate before even dialing the live one.
            assert elapsed < 2.0
            assert client.race_stats["wins"] == 1
            host, port = server.address
            assert client.race_stats["last_winner"] == f"{host}:{port}"
            assert client.race_stats["last_candidates"] == 2
            # The session works end to end.
            await client.mkdirp("/raced")
            assert await client.exists("/raced") is not None
        finally:
            if client is not None:
                await client.close()
            hung.close()
            await hung.wait_closed()
            await server.stop()

    async def test_losing_handshake_closes_its_session(self):
        """Fresh-session races mint one session per handshake; the loser
        must CLOSE_SESSION so the ensemble never accumulates orphans
        (which under quorum loss could not even expire)."""
        server = await ZKServer().start()
        client = ZKClient(
            [server.address, server.address], connect_race_stagger_ms=0
        )
        orig = client._dial_handshake
        n_done = 0
        gate = asyncio.Event()

        async def gated(host, port, max_wait=None):
            # Let BOTH handshakes complete before either returns, so the
            # race deterministically sees one winner and one completed
            # loser (not a cancelled half-dial).
            nonlocal n_done
            res = await orig(host, port, max_wait=max_wait)
            n_done += 1
            if n_done >= 2:
                gate.set()
                # Yield once so the gate-parked attempt finishes before
                # this one does: both land in the same done-set and the
                # loser takes the completed-handshake abort path.
                await asyncio.sleep(0)
                return res
            await gate.wait()
            return res

        client._dial_handshake = gated
        try:
            await client.connect()
            assert client.race_stats["wins"] == 1
            assert client.race_stats["last_candidates"] == 2
            assert client.race_stats["last_aborted"] == 1
            # The loser's freshly-minted session gets closed server-side;
            # only the winner's survives.
            for _ in range(200):
                if len(server.sessions) == 1:
                    break
                await asyncio.sleep(0.02)
            assert len(server.sessions) == 1
            assert client.session_id in server.sessions
        finally:
            await client.close()
            await server.stop()

    async def test_knob_absent_uses_serial_reference_pass(self):
        """Config parity: without ``connect_race_stagger_ms`` the raced
        path must never run — the serial pass is reference-exact."""
        server = await ZKServer().start()
        client = ZKClient([server.address])

        async def boom(order, deadline):  # pragma: no cover - must not run
            raise AssertionError("raced connect used without the knob")

        client._connect_raced = boom
        try:
            await client.connect()
            assert client.connected
            assert client.race_stats == {
                "wins": 0,
                "last_winner": None,
                "last_candidates": 0,
                "last_aborted": 0,
            }
        finally:
            await client.close()
            await server.stop()

    async def test_knob_validation(self):
        with pytest.raises(ValueError):
            ZKClient([("127.0.0.1", 1)], connect_race_stagger_ms=-1)
        with pytest.raises(ValueError):
            ZKClient([("127.0.0.1", 1)], ping_interval_ms=0)
        with pytest.raises(ValueError):
            ZKClient([("127.0.0.1", 1)], dead_after_ms=-5)


class TestPingSchedule:
    """ISSUE 20 tentpole: sub-session-timeout failure detection.  The
    default schedule is the Apache client's thirds rule off the
    negotiated timeout; ``ping_interval_ms`` / ``dead_after_ms``
    override each half independently."""

    async def test_reference_thirds_rule(self):
        client = ZKClient([("127.0.0.1", 1)])
        client.negotiated_timeout_ms = 6000
        assert client._ping_schedule() == (2.0, 4.0)
        # Tiny negotiated timeouts hit the interval floor (20ms) and the
        # dead-after floor (two intervals).
        client.negotiated_timeout_ms = 30
        assert client._ping_schedule() == (0.02, 0.04)

    async def test_overrides_decouple_from_session_timeout(self):
        client = ZKClient(
            [("127.0.0.1", 1)], ping_interval_ms=40, dead_after_ms=100
        )
        client.negotiated_timeout_ms = 6000
        # 40ms/100ms detection under a 6s session: the whole point.
        assert client._ping_schedule() == (0.04, 0.1)

    async def test_dead_after_floored_at_interval(self):
        """The watchdog can never fire between its own pings: an
        inverted configuration floors dead-after at the interval."""
        client = ZKClient(
            [("127.0.0.1", 1)], ping_interval_ms=500, dead_after_ms=100
        )
        client.negotiated_timeout_ms = 6000
        assert client._ping_schedule() == (0.5, 0.5)

    async def test_watchdog_drops_blackholed_connection(self):
        """TCP alive but totally unresponsive (blackhole both ways): the
        tuned watchdog declares the server dead in ~dead_after_ms, far
        inside the session timeout."""
        server = await ZKServer().start()
        proxy = ChaosProxy(server.address)
        await proxy.start()
        client = None
        try:
            client = await ZKClient(
                [proxy.address],
                ping_interval_ms=20,
                dead_after_ms=80,
                reconnect=False,
            ).connect()
            closed = asyncio.Event()
            client.on("close", lambda *_a: closed.set())
            proxy.add(Blackhole(), direction=UP)
            proxy.add(Blackhole(), direction=DOWN)
            t0 = time.monotonic()
            await asyncio.wait_for(closed.wait(), timeout=5)
            assert client.watchdog_drops >= 1
            # Suspicion well inside even the minimum session timeout.
            assert time.monotonic() - t0 < 2.0
        finally:
            if client is not None:
                await client.close()
            await proxy.stop()
            await server.stop()
