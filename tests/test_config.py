"""Config loading/validation tests (reference main.js:52-84, SURVEY.md §2.7)."""

import json

import pytest

from registrar_tpu.config import ConfigError, load_config, parse_config


def _coal():
    # mirror of the reference's sample config, etc/config.coal.json
    return {
        "registration": {
            "domain": "test.coal.joyent.us",
            "type": "host",
            "aliases": ["alias-1.test.coal.joyent.us"],
        },
        "zookeeper": {
            "connectTimeout": 1000,
            "servers": [{"host": "10.99.99.11", "port": 2181}],
            "timeout": 6000,
        },
        "maxAttempts": 10,
    }


class TestParse:
    def test_coal_sample(self):
        cfg = parse_config(_coal())
        assert cfg.zookeeper.servers == [("10.99.99.11", 2181)]
        assert cfg.zookeeper.timeout_ms == 6000
        assert cfg.zookeeper.connect_timeout_ms == 1000
        assert cfg.registration["domain"] == "test.coal.joyent.us"
        # maxAttempts is inert in the reference (read by nothing,
        # SURVEY.md §2.7); here it configures the heartbeat retry.
        assert cfg.heartbeat_retry.max_attempts == 10

    def test_defaults(self):
        cfg = parse_config(
            {
                "registration": {"domain": "a.b", "type": "host"},
                "zookeeper": {"servers": [{"host": "h", "port": 1}]},
            }
        )
        assert cfg.zookeeper.timeout_ms == 30000
        assert cfg.heartbeat_interval_s == 3.0
        assert cfg.heartbeat_retry.max_attempts == 5
        assert cfg.health_check is None
        assert cfg.admin_ip is None
        assert cfg.repair_heartbeat_miss is False  # parity default

    def test_example_config_validates(self):
        # etc/config.example.json documents every key; it must stay valid
        # (the same check registrar -n applies).
        import os

        from registrar_tpu.registration import _validate_registration

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cfg = load_config(os.path.join(repo, "etc", "config.example.json"))
        _validate_registration(cfg.registration)
        assert cfg.unknown_keys == ()
        assert cfg.zookeeper.chroot == "/tenants/example"
        assert cfg.metrics.port == 9090
        assert cfg.health_check["stdout_match"]["invert"] is True
        assert cfg.survive_session_expiry is False  # documented, parity off
        assert cfg.max_session_rebirths == 5
        assert cfg.reconcile.interval_s == 60.0
        assert cfg.reconcile.repair is False

    def test_request_timeout_opt_in(self):
        # Per-operation deadline (ISSUE 2): off by default (reference
        # behavior — wait forever), a positive ms number when configured.
        base = {
            "registration": {"domain": "a.b", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
        }
        assert parse_config(base).zookeeper.request_timeout_ms is None
        base["zookeeper"]["requestTimeout"] = 5000
        assert parse_config(base).zookeeper.request_timeout_ms == 5000
        base["zookeeper"]["requestTimeout"] = "5s"
        with pytest.raises(ConfigError):
            parse_config(base)
        base["zookeeper"]["requestTimeout"] = -1
        with pytest.raises(ConfigError):
            parse_config(base)

    def test_can_be_read_only_opt_in(self):
        # Ensemble read-only attach (ISSUE 10): off by default
        # (reference-exact handshake bytes), a boolean when configured.
        base = {
            "registration": {"domain": "a.b", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
        }
        assert parse_config(base).zookeeper.can_be_read_only is False
        base["zookeeper"]["canBeReadOnly"] = True
        assert parse_config(base).zookeeper.can_be_read_only is True
        base["zookeeper"]["canBeReadOnly"] = "yes"
        with pytest.raises(ConfigError):
            parse_config(base)

    def test_event_loop_opt_in(self):
        # eventLoop (ISSUE 11): absent = None (stdlib loop, no policy
        # change); "asyncio"/"uvloop" accepted; anything else rejected.
        base = {
            "registration": {"domain": "a.b", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
        }
        assert parse_config(base).zookeeper.event_loop is None
        base["zookeeper"]["eventLoop"] = "asyncio"
        assert parse_config(base).zookeeper.event_loop == "asyncio"
        base["zookeeper"]["eventLoop"] = "uvloop"
        assert parse_config(base).zookeeper.event_loop == "uvloop"
        for bad in ("trio", "", 1, True):
            base["zookeeper"]["eventLoop"] = bad
            with pytest.raises(ConfigError):
                parse_config(base)

    def test_unknown_top_level_keys_surfaced(self):
        cfg = parse_config(
            {
                "registration": {"domain": "a.b", "type": "host"},
                "zookeeper": {"servers": [{"host": "h", "port": 1}]},
                "healthcheck": {"command": "true"},  # typo: lowercase c
                "zzz": 1,
            }
        )
        assert cfg.unknown_keys == ("healthcheck", "zzz")
        assert cfg.health_check is None  # the typo key was NOT honored

    def test_repair_heartbeat_miss_opt_in(self):
        cfg = parse_config(
            {
                "registration": {"domain": "a.b", "type": "host"},
                "zookeeper": {"servers": [{"host": "h", "port": 1}]},
                "repairHeartbeatMiss": True,
            }
        )
        assert cfg.repair_heartbeat_miss is True

    def test_survive_session_expiry_opt_in(self):
        # ISSUE 3: off by default (reference behavior: expiry = exit(1)).
        base = {
            "registration": {"domain": "a.b", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
        }
        cfg = parse_config(base)
        assert cfg.survive_session_expiry is False
        assert cfg.max_session_rebirths is None  # client default applies
        assert cfg.reconcile is None
        cfg = parse_config(
            {**base, "surviveSessionExpiry": True, "maxSessionRebirths": 3}
        )
        assert cfg.survive_session_expiry is True
        assert cfg.max_session_rebirths == 3
        with pytest.raises(ConfigError):
            parse_config({**base, "surviveSessionExpiry": "yes"})
        with pytest.raises(ConfigError):
            parse_config({**base, "maxSessionRebirths": 0})
        with pytest.raises(ConfigError):
            parse_config({**base, "maxSessionRebirths": True})

    def test_reconcile_block(self):
        # ISSUE 3: seconds-based (the name carries the unit), repair off
        # by default (detect-only).
        base = {
            "registration": {"domain": "a.b", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
        }
        cfg = parse_config({**base, "reconcile": {}})
        assert cfg.reconcile.interval_s == 60.0
        assert cfg.reconcile.repair is False
        cfg = parse_config(
            {**base, "reconcile": {"intervalSeconds": 2.5, "repair": True}}
        )
        assert cfg.reconcile.interval_s == 2.5
        assert cfg.reconcile.repair is True
        with pytest.raises(ConfigError):
            parse_config({**base, "reconcile": 60})
        with pytest.raises(ConfigError):
            parse_config({**base, "reconcile": {"intervalSeconds": 0}})
        with pytest.raises(ConfigError):
            parse_config({**base, "reconcile": {"intervalSeconds": True}})
        with pytest.raises(ConfigError):
            parse_config({**base, "reconcile": {"repair": "on"}})

    def test_top_level_admin_ip_shim(self):
        # reference main.js:146-147
        cfg = parse_config(
            {
                "adminIp": "10.0.0.9",
                "registration": {"domain": "a.b", "type": "host"},
                "zookeeper": {"servers": [{"host": "h", "port": 1}]},
            }
        )
        assert cfg.admin_ip == "10.0.0.9"

    def test_registration_admin_ip_wins(self):
        cfg = parse_config(
            {
                "adminIp": "10.0.0.1",
                "registration": {
                    "domain": "a.b", "type": "host", "adminIp": "10.0.0.2",
                },
                "zookeeper": {"servers": [{"host": "h", "port": 1}]},
            }
        )
        assert cfg.admin_ip == "10.0.0.2"
        assert "adminIp" not in cfg.registration

    def test_health_check_ms_to_seconds(self):
        cfg = parse_config(
            {
                "registration": {"domain": "a.b", "type": "host"},
                "zookeeper": {"servers": [{"host": "h", "port": 1}]},
                "healthCheck": {
                    "command": "true",
                    "interval": 5000,
                    "timeout": 500,
                    "threshold": 3,
                    "period": 60000,
                    "ignoreExitStatus": True,
                    "stdoutMatch": {"pattern": "ok", "invert": True},
                },
            }
        )
        hc = cfg.health_check
        assert hc["interval"] == 5.0
        assert hc["timeout"] == 0.5
        assert hc["period"] == 60.0
        assert hc["threshold"] == 3
        assert hc["ignore_exit_status"] is True
        assert hc["stdout_match"]["invert"] is True

    def test_heartbeat_interval_ms(self):
        cfg = parse_config(
            {
                "registration": {
                    "domain": "a.b", "type": "host", "heartbeatInterval": 500,
                },
                "zookeeper": {"servers": [{"host": "h", "port": 1}]},
            }
        )
        assert cfg.heartbeat_interval_s == 0.5
        assert "heartbeatInterval" not in cfg.registration

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda c: c.pop("zookeeper"),
            lambda c: c.pop("registration"),
            lambda c: c["zookeeper"].update(servers=[]),
            lambda c: c["zookeeper"].update(servers=[{"host": "h"}]),
            lambda c: c["zookeeper"].update(servers=[{"host": 1, "port": 1}]),
            lambda c: c["zookeeper"].update(timeout=-5),
            lambda c: c.update(adminIp=42),
            lambda c: c.update(healthCheck={"interval": 5}),
            lambda c: c.update(healthCheck={"command": ""}),
            # a "5" (string) threshold used to pass -n pre-flight and then
            # kill the health consumer task at runtime
            lambda c: c.update(healthCheck={"command": "true",
                                            "threshold": "5"}),
            lambda c: c.update(healthCheck={"command": "true",
                                            "threshold": 0}),
            lambda c: c.update(healthCheck={"command": "true",
                                            "threshold": True}),
            lambda c: c.update(healthCheck={"command": "true",
                                            "stdoutMatch": "ok"}),
            lambda c: c.update(healthCheck={"command": "true",
                                            "stdoutMatch": {"pattern": 5}}),
            lambda c: c.update(healthCheck={
                "command": "true",
                "stdoutMatch": {"pattern": "("},  # does not compile
            }),
            lambda c: c.update(healthCheck={
                "command": "true",
                "stdoutMatch": {"pattern": "ok", "flags": "x"},  # unsupported
            }),
            lambda c: c.update(healthCheck={
                "command": "true",
                "stdoutMatch": {"pattern": ""},  # would disable matching
            }),
            lambda c: c.update(healthCheck={
                "command": "true",
                # "false" is truthy: would invert the match at runtime
                "stdoutMatch": {"pattern": "ok", "invert": "false"},
            }),
            lambda c: c.update(healthCheck={
                "command": "true",
                "stdoutMatch": {"pattern": "ok", "flags": 3},
            }),
            lambda c: c.update(logLevel=3),
            lambda c: c.update(maxAttempts=0),
            lambda c: c.update(repairHeartbeatMiss="yes"),
            lambda c: c.update(healthCheck="true"),  # not an object
            lambda c: c.update(metrics="on"),        # not an object
            lambda c: c.update(metrics={"port": "9090"}),
            lambda c: c.update(metrics={"port": 0}),
            lambda c: c.update(metrics={"port": 65536}),
            lambda c: c.update(metrics={"port": 9090, "host": 7}),
            lambda c: c.update(zookeeper={"servers": [
                {"host": "h", "port": 2181}], "chroot": "no-slash"}),
        ],
    )
    def test_invalid(self, mutate):
        raw = _coal()
        mutate(raw)
        with pytest.raises(ConfigError):
            parse_config(raw)

    def test_whole_config_must_be_object(self):
        with pytest.raises(ConfigError):
            parse_config(["not", "an", "object"])


class TestLoad:
    def test_load_from_file(self, tmp_path):
        p = tmp_path / "config.json"
        p.write_text(json.dumps(_coal()))
        cfg = load_config(str(p))
        assert cfg.registration["type"] == "host"

    def test_missing_file(self):
        from registrar_tpu.config import ConfigUnreadableError

        with pytest.raises(ConfigUnreadableError):
            load_config("/nonexistent/config.json")

    def test_malformed_json(self, tmp_path):
        from registrar_tpu.config import ConfigUnreadableError

        p = tmp_path / "bad.json"
        p.write_text("{nope")
        with pytest.raises(ConfigError) as exc:
            load_config(str(p))
        # parse failure is invalid-config (EX_CONFIG), not unreadable
        assert not isinstance(exc.value, ConfigUnreadableError)


class TestCacheBlock:
    """ISSUE 4: the `cache` block (resolve-cache tuning for zkcli
    serve-view; absent = defaults, daemon behavior untouched)."""

    def test_absent_block_is_none(self):
        from registrar_tpu.config import parse_config

        cfg = parse_config({
            "registration": {"domain": "a.b.c", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 2181}]},
        })
        assert cfg.cache is None

    def test_parsed_with_defaults_and_override(self):
        from registrar_tpu.config import parse_config

        base = {
            "registration": {"domain": "a.b.c", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 2181}]},
        }
        cfg = parse_config({**base, "cache": {}})
        assert cfg.cache is not None and cfg.cache.max_entries == 4096
        cfg = parse_config({**base, "cache": {"maxEntries": 128}})
        assert cfg.cache.max_entries == 128

    def test_validation_errors(self):
        import pytest

        from registrar_tpu.config import ConfigError, parse_config

        base = {
            "registration": {"domain": "a.b.c", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 2181}]},
        }
        for bad in ([1], {"maxEntries": 0}, {"maxEntries": "big"},
                    {"maxEntries": True}):
            with pytest.raises(ConfigError):
                parse_config({**base, "cache": bad})

    def test_cache_is_a_known_key(self):
        # a config using the documented key must not trip the
        # unknown-key typo warning
        from registrar_tpu.config import parse_config

        cfg = parse_config({
            "registration": {"domain": "a.b.c", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 2181}]},
            "cache": {"maxEntries": 64},
        })
        assert "cache" not in cfg.unknown_keys


class TestRestartBlock:
    """ISSUE 5: the `restart` config block."""

    BASE = {
        "registration": {"domain": "a.b", "type": "host"},
        "zookeeper": {"servers": [{"host": "h", "port": 1}]},
    }

    def _parse(self, restart):
        return parse_config({**self.BASE, "restart": restart})

    def test_handoff_defaults(self):
        cfg = self._parse({"stateFile": "/var/run/registrar/state.json"})
        assert cfg.restart.state_file == "/var/run/registrar/state.json"
        assert cfg.restart.mode == "handoff"
        assert cfg.restart.drain_grace_s == 0.0

    def test_drain_with_grace(self):
        cfg = self._parse({"stateFile": "/s", "mode": "drain",
                           "drainGraceSeconds": 2.5})
        assert cfg.restart.mode == "drain"
        assert cfg.restart.drain_grace_s == 2.5

    def test_absent_block_means_off(self):
        assert parse_config(self.BASE).restart is None

    def test_state_file_required(self):
        with pytest.raises(ConfigError, match="stateFile"):
            self._parse({"mode": "handoff"})
        with pytest.raises(ConfigError, match="stateFile"):
            self._parse({"stateFile": ""})

    def test_mode_must_be_known(self):
        with pytest.raises(ConfigError, match="mode"):
            self._parse({"stateFile": "/s", "mode": "yolo"})

    def test_grace_must_be_non_negative_number(self):
        with pytest.raises(ConfigError, match="drainGraceSeconds"):
            self._parse({"stateFile": "/s", "drainGraceSeconds": -1})
        with pytest.raises(ConfigError, match="drainGraceSeconds"):
            self._parse({"stateFile": "/s", "drainGraceSeconds": True})

    def test_block_must_be_object(self):
        with pytest.raises(ConfigError, match="restart"):
            self._parse("handoff")

    def test_source_path_recorded_by_load_config(self, tmp_path):
        import json as json_mod

        path = tmp_path / "c.json"
        path.write_text(json_mod.dumps(self.BASE))
        cfg = load_config(str(path))
        assert cfg.source_path == str(path)
        assert parse_config(self.BASE).source_path is None


class TestServeBlock:
    """ISSUE 12: the `serve` block (namespace-sharded resolve tier for
    zkcli serve-sharded; absent = no tier, daemon behavior untouched)."""

    BASE = {
        "registration": {"domain": "a.b.c", "type": "host"},
        "zookeeper": {"servers": [{"host": "h", "port": 2181}]},
    }

    def test_absent_block_is_none(self):
        from registrar_tpu.config import parse_config

        assert parse_config(self.BASE).serve is None

    def test_parsed_with_defaults_and_override(self):
        from registrar_tpu.config import parse_config

        cfg = parse_config({
            **self.BASE,
            "serve": {"shards": 4, "socketPath": "/run/r.sock"},
        })
        assert cfg.serve.shards == 4
        assert cfg.serve.socket_path == "/run/r.sock"
        assert cfg.serve.attach_spread == "spread"
        cfg = parse_config({
            **self.BASE,
            "serve": {"shards": 1, "socketPath": "/run/r.sock",
                      "attachSpread": "follower"},
        })
        assert cfg.serve.attach_spread == "follower"

    def test_validation_errors(self):
        import pytest

        from registrar_tpu.config import ConfigError, parse_config

        for bad in (
            [1],
            {},  # shards required
            {"shards": 0, "socketPath": "/s"},
            {"shards": True, "socketPath": "/s"},
            {"shards": "4", "socketPath": "/s"},
            {"shards": 2},  # socketPath required
            {"shards": 2, "socketPath": ""},
            {"shards": 2, "socketPath": 7},
            {"shards": 2, "socketPath": "/s", "attachSpread": "leader"},
            {"shards": 2, "socketPath": "/s",
             "attachSpread": "spread:0-of-2"},  # per-worker form is internal
        ):
            with pytest.raises(ConfigError):
                parse_config({**self.BASE, "serve": bad})

    def test_serve_is_a_known_key(self):
        from registrar_tpu.config import parse_config

        cfg = parse_config({
            **self.BASE,
            "serve": {"shards": 2, "socketPath": "/run/r.sock"},
        })
        assert "serve" not in cfg.unknown_keys


class TestAvailabilityKnobs:
    """ISSUE 20: the nines levers — raced connects, sub-session-timeout
    failure detection, and serve-stale — every key absent means
    reference-exact behavior, and each parses/validates independently."""

    BASE = {
        "registration": {"domain": "a.b.c", "type": "host"},
        "zookeeper": {"servers": [{"host": "h", "port": 2181}]},
    }

    def _zk(self, **extra):
        return {
            **self.BASE,
            "zookeeper": {
                "servers": [{"host": "h", "port": 2181}], **extra,
            },
        }

    def test_absent_keys_mean_reference_behavior(self):
        cfg = parse_config(dict(self.BASE))
        assert cfg.zookeeper.connect_race_stagger_ms is None
        assert cfg.zookeeper.ping_interval_ms is None
        assert cfg.zookeeper.dead_after_ms is None

    def test_zookeeper_knobs_parse(self):
        cfg = parse_config(self._zk(
            connectRaceStaggerMs=40, pingIntervalMs=40, deadAfterMs=100,
        ))
        assert cfg.zookeeper.connect_race_stagger_ms == 40
        assert cfg.zookeeper.ping_interval_ms == 40
        assert cfg.zookeeper.dead_after_ms == 100
        # JSON null is the same as absent
        cfg = parse_config(self._zk(connectRaceStaggerMs=None))
        assert cfg.zookeeper.connect_race_stagger_ms is None

    @pytest.mark.parametrize(
        "key", ["connectRaceStaggerMs", "pingIntervalMs", "deadAfterMs"]
    )
    @pytest.mark.parametrize("bad", [0, -1, "fast", True, float("nan")])
    def test_zookeeper_knobs_validate(self, key, bad):
        with pytest.raises(ConfigError):
            parse_config(self._zk(**{key: bad}))

    def test_stale_max_age_parses(self):
        cfg = parse_config({**self.BASE, "cache": {"staleMaxAgeS": 30}})
        assert cfg.cache.stale_max_age_s == 30.0
        cfg = parse_config({**self.BASE, "cache": {"staleMaxAgeS": 2.5}})
        assert cfg.cache.stale_max_age_s == 2.5
        # absent (or null) = the PR-4 flush-on-degrade default
        cfg = parse_config({**self.BASE, "cache": {}})
        assert cfg.cache.stale_max_age_s is None
        cfg = parse_config({**self.BASE, "cache": {"staleMaxAgeS": None}})
        assert cfg.cache.stale_max_age_s is None

    @pytest.mark.parametrize("bad", [-1, "long", True, float("inf")])
    def test_stale_max_age_validates(self, bad):
        with pytest.raises(ConfigError):
            parse_config({**self.BASE, "cache": {"staleMaxAgeS": bad}})
