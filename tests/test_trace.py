"""ISSUE 8: the span layer, flight recorder, and introspection surface.

Pins the tentpole's contracts:

  * span identity + contextvar propagation across tasks, head sampling,
    the recorder ring bound, slow-span warnings with the parent chain;
  * the ZK client's per-op spans: queue-vs-wire split (submit →
    flushed → reply), op/xid tagging, no leaked in-flight spans;
  * Histogram rendering/quantiles and instrument_tracing's routing;
  * GET /status and GET /debug/trace shapes, the 405 + header-bytes
    hardening, and the daemon-wired end-to-end (in-process run());
  * SIGUSR2 dump + jlog trace-correlation against the real daemon
    binary (subprocess);
  * **tracing-disabled parity**: with no `observability` block, zero
    new log fields, zero new metric series, zero new wire operations —
    byte-identical to the untraced daemon;
  * the session-loss → rebirth → re-registration span chain the chaos
    storm's flight-recorder dump must carry (deterministic single-server
    variant here; the seeded storm rider lives in tests/test_chaos.py).
"""

import asyncio
import json
import logging
import os
import signal
import socket
import subprocess
import sys

import pytest

from registrar_tpu import binderview, jlog, trace
from registrar_tpu.agent import register_plus
from registrar_tpu.config import ConfigError, parse_config
from registrar_tpu.metrics import (
    MAX_HEADER_BYTES,
    Histogram,
    MetricsRegistry,
    MetricsServer,
    instrument,
    instrument_tracing,
)
from registrar_tpu.registration import register
from registrar_tpu.retry import RetryPolicy
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.trace import DISABLED, NOOP_SPAN, TraceContextFilter, Tracer
from registrar_tpu.zk.client import ZKClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HOSTNAME = socket.gethostname()


async def _http_get(host, port, path, method="GET", extra_headers=b""):
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(
        f"{method} {path} HTTP/1.0\r\nHost: {host}\r\n".encode()
        + extra_headers
        + b"\r\n"
    )
    await writer.drain()
    try:
        raw = await asyncio.wait_for(reader.read(), timeout=5)
    except ConnectionResetError:
        raw = b""
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1]) if head else 0
    return status, head.decode("latin-1", "replace"), body


def _spans(tracer, name=None):
    entries = tracer.dump()["entries"]
    return [
        e for e in entries
        if e["kind"] == "span" and (name is None or e["name"] == name)
    ]


def _events(tracer, name=None):
    entries = tracer.dump()["entries"]
    return [
        e for e in entries
        if e["kind"] == "event" and (name is None or e["name"] == name)
    ]


class TestSpans:
    async def test_identity_and_nesting(self):
        tracer = Tracer()
        with tracer.span("outer.op", who="x") as outer:
            with tracer.span("inner.op") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
                assert inner.span_id != outer.span_id
        entries = tracer.dump()["entries"]
        assert [e["name"] for e in entries] == ["inner.op", "outer.op"]
        assert entries[1]["attrs"] == {"who": "x"}
        assert entries[0]["parent_id"] == entries[1]["span_id"]
        assert entries[0]["duration_ms"] is not None
        assert entries[0]["status"] == "ok"

    async def test_context_propagates_across_tasks(self):
        # asyncio.create_task copies the context, so spans opened inside
        # a spawned task chain to the span active at spawn time — the
        # agent's repair task parenting, in miniature.
        tracer = Tracer()

        async def child() -> None:
            with tracer.span("child.op"):
                await asyncio.sleep(0)

        with tracer.span("parent.op") as parent:
            await asyncio.gather(
                asyncio.create_task(child()), asyncio.create_task(child())
            )
        children = _spans(tracer, "child.op")
        assert len(children) == 2
        for c in children:
            assert c["trace_id"] == parent.trace_id
            assert c["parent_id"] == parent.span_id

    async def test_exception_marks_error_and_propagates(self):
        tracer = Tracer()
        with pytest.raises(RuntimeError):
            with tracer.span("will.fail"):
                raise RuntimeError("boom")
        (span,) = _spans(tracer, "will.fail")
        assert span["status"] == "error"
        assert "boom" in span["attrs"]["err"]

    async def test_finish_is_idempotent(self):
        tracer = Tracer()
        span = tracer.start_span("manual.op")
        span.finish("error", err=-4)
        span.finish("ok")  # late duplicate: first verdict stands
        (entry,) = _spans(tracer, "manual.op")
        assert entry["status"] == "error"
        assert len(_spans(tracer)) == 1

    async def test_sampling_zero_records_nothing(self):
        tracer = Tracer(sample_rate=0.0)
        sink_calls = []
        tracer.on_span(sink_calls.append)
        with tracer.span("root.op") as root:
            assert not root.sampled
            with tracer.span("child.op") as child:
                # the verdict is inherited, not re-rolled per child
                assert not child.sampled
                # ids still exist: log correlation works unsampled
                assert child.trace_id == root.trace_id
        assert tracer.dump()["entries"] == []
        assert sink_calls == []

    async def test_event_in_unsampled_trace_is_dropped(self):
        # The head-based verdict covers the whole trace, events
        # included — otherwise a low sampleRate still lets a churning
        # path's events evict the rare sampled spans from the ring.
        tracer = Tracer(sample_rate=0.0)
        with tracer.span("root.op"):
            tracer.event("inside.event")
        tracer.event("outside.event")  # no trace: no verdict to inherit
        entries = tracer.dump()["entries"]
        assert [e["name"] for e in entries] == ["outside.event"]
        assert tracer.events_recorded == 1

    async def test_ring_bound_and_counters(self):
        tracer = Tracer(max_spans=10)
        for i in range(50):
            with tracer.span("ring.op", i=i):
                pass
        dump = tracer.dump()
        assert len(dump["entries"]) == 10
        assert dump["spans_recorded"] == 50
        assert [e["attrs"]["i"] for e in dump["entries"]] == list(
            range(40, 50)
        )
        assert len(tracer.dump(3)["entries"]) == 3

    async def test_slow_span_warns_with_parent_chain(self, caplog):
        tracer = Tracer(slow_span_ms=0.0)  # every span is "slow"
        with caplog.at_level(logging.WARNING, "registrar_tpu.trace"):
            with tracer.span("slow.outer"):
                with tracer.span("slow.inner"):
                    pass
        records = [r for r in caplog.records if "slow span" in r.message]
        assert records, caplog.text
        chains = [r.zdata["chain"] for r in records]
        assert ["slow.outer", "slow.inner"] in chains

    async def test_cross_tracer_spans_do_not_chain(self):
        # A privately-traced cache under a globally-traced caller must
        # not write parent ids another recorder owns.
        a, b = Tracer(), Tracer()
        with a.span("a.root"):
            with b.span("b.root") as inner:
                assert inner.parent_id is None

    async def test_event_carries_active_trace_id(self):
        tracer = Tracer()
        tracer.event("lonely.event", detail=1)
        with tracer.span("evt.parent") as span:
            tracer.event("attached.event")
        lonely = _events(tracer, "lonely.event")[0]
        attached = _events(tracer, "attached.event")[0]
        assert lonely["trace_id"] is None
        assert attached["trace_id"] == span.trace_id

    async def test_annotate_stamps_ambient_attrs(self):
        # ISSUE 9: annotate() marks every span/event created inside the
        # block — across nested call layers — without threading attrs
        # through signatures (the SLO prober's scenario/fault marks).
        from registrar_tpu.trace import annotate

        tracer = Tracer()
        with tracer.span("amb.before"):
            pass
        with annotate(scenario="crash-loop", faults="crash-loop"):
            with tracer.span("amb.outer"):
                with annotate(scenario="inner", extra=1):
                    with tracer.span("amb.inner", extra=2):
                        tracer.event("amb.event")
            with tracer.span("amb.after_inner"):
                pass
        with tracer.span("amb.outside"):
            pass
        assert _spans(tracer, "amb.before")[0]["attrs"] == {}
        assert _spans(tracer, "amb.outer")[0]["attrs"] == {
            "scenario": "crash-loop", "faults": "crash-loop",
        }
        # nested blocks merge per key; explicit call-site attrs win
        assert _spans(tracer, "amb.inner")[0]["attrs"] == {
            "scenario": "inner", "faults": "crash-loop", "extra": 2,
        }
        assert _events(tracer, "amb.event")[0]["attrs"]["scenario"] == "inner"
        # exiting the inner block restores the outer view...
        assert _spans(tracer, "amb.after_inner")[0]["attrs"] == {
            "scenario": "crash-loop", "faults": "crash-loop",
        }
        # ...and exiting the outer one restores clean spans
        assert _spans(tracer, "amb.outside")[0]["attrs"] == {}

    async def test_dump_to_file(self, tmp_path):
        tracer = Tracer()
        with tracer.span("dumped.op"):
            pass
        path = tracer.dump_to_file(str(tmp_path / "dump.json"))
        payload = json.loads(open(path, encoding="utf-8").read())
        assert payload["enabled"] is True
        assert payload["pid"] == os.getpid()
        assert [e["name"] for e in payload["entries"]] == ["dumped.op"]


class TestQueueWireSplit:
    """The ZK client's per-request spans against the testing server."""

    async def test_op_spans_tag_op_xid_and_split(self):
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        client.tracer = Tracer()
        try:
            await client.create("/qw", b"x")
            await client.get("/qw")
            await client.exists("/missing-qw")
        finally:
            await client.close()
            await server.stop()
        by_op = {e["attrs"]["op"]: e for e in _spans(client.tracer, "zk.op")}
        assert set(by_op) >= {"create", "getData", "exists"}
        for entry in by_op.values():
            assert isinstance(entry["attrs"]["xid"], int)
            assert entry["duration_ms"] is not None
            # the queue/wire split: flushed is stamped between submit
            # and reply, so 0 <= queue <= total
            assert 0 <= entry["marks"]["flushed"] <= entry["duration_ms"]
        assert by_op["create"]["status"] == "ok"
        # NO_NODE is an error verdict carrying the code
        assert by_op["exists"]["status"] == "error"
        assert by_op["exists"]["attrs"]["err"] == -101

    async def test_pipelined_burst_spans_every_request(self):
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        client.tracer = Tracer(max_spans=4096)
        try:
            await client.mkdirp("/burst")
            paths = [f"/burst/e{i}" for i in range(20)]
            for p in paths:
                await client.create(p, b"")
            client.tracer = Tracer(max_spans=4096)  # reset the recorder
            await client.heartbeat(paths)
        finally:
            await client.close()
            await server.stop()
        exists_spans = [
            e for e in _spans(client.tracer, "zk.op")
            if e["attrs"]["op"] == "exists"
        ]
        assert len(exists_spans) == 20
        assert all("flushed" in e["marks"] for e in exists_spans)
        # one drain for the burst: every span carries the mark (the
        # split is per-request even when the flush is shared)
        assert client._op_spans == {}  # nothing leaked in flight

    async def test_teardown_fails_inflight_spans(self):
        server = await ZKServer().start()
        client = await ZKClient([server.address], reconnect=False).connect()
        client.tracer = Tracer()
        try:
            # Stall the server's reply path by posting to a server we
            # stop before it can answer: the teardown must close the
            # span with the CONNECTION_LOSS verdict, not leak it.
            await server.stop()
            with pytest.raises(Exception):
                await asyncio.wait_for(client.get("/x"), timeout=5)
        finally:
            await client.close()
        spans = _spans(client.tracer, "zk.op")
        if spans:  # the post may fail before a span is minted — either
            # way nothing stays in flight
            assert all(e["status"] == "error" for e in spans)
        assert client._op_spans == {}


class TestDisabledParity:
    """Default OFF = reference parity: zero new ops, fields, series."""

    async def test_module_default_is_disabled(self):
        assert trace.get_tracer() is DISABLED
        assert trace.get_tracer().span("any.name") is NOOP_SPAN
        assert trace.get_tracer().dump() == {"enabled": False, "entries": []}
        # the no-op span is reusable and inert
        with NOOP_SPAN as sp:
            sp.mark("flushed")
            sp.set_attr("k", "v")
            sp.finish()

    async def test_zero_new_wire_ops_and_no_span_state(self):
        # Identical workloads traced and untraced must issue identical
        # request streams (xid counters equal) — tracing observes ops,
        # it must never add them.
        async def workload(tracer):
            server = await ZKServer().start()
            client = await ZKClient([server.address]).connect()
            if tracer is not None:
                client.tracer = tracer
            try:
                await register(
                    client, {"domain": "parity.test.us", "type": "host"},
                    admin_ip="10.0.0.9", hostname="pbox", settle_delay=0,
                )
                await binderview.resolve(client, "pbox.parity.test.us", "A")
                return client._xid
            finally:
                await client.close()
                await server.stop()

        untraced_xid = await workload(None)
        traced_xid = await workload(Tracer())
        assert untraced_xid == traced_xid

    async def test_jlog_has_no_trace_fields_without_filter(self):
        logger = logging.getLogger("parity.jlog.test")
        formatter = jlog.BunyanFormatter("registrar")
        tracer = Tracer()
        with tracer.span("active.span"):
            record = logger.makeRecord(
                logger.name, logging.INFO, "f.py", 1, "hello", (), None
            )
            line = json.loads(formatter.format(record))
        assert "trace_id" not in line and "span_id" not in line

    async def test_jlog_correlates_with_filter_inside_span(self):
        logger = logging.getLogger("correlated.jlog.test")
        formatter = jlog.BunyanFormatter("registrar")
        filt = TraceContextFilter()
        tracer = Tracer()
        trace.set_tracer(tracer)
        try:
            with tracer.span("active.span") as span:
                record = logger.makeRecord(
                    logger.name, logging.INFO, "f.py", 1, "hello", (), None
                )
                filt.filter(record)
                line = json.loads(formatter.format(record))
            assert line["trace_id"] == span.trace_id
            assert line["span_id"] == span.span_id
            # outside any span: the filter stamps nothing
            record = logger.makeRecord(
                logger.name, logging.INFO, "f.py", 1, "bye", (), None
            )
            filt.filter(record)
            line = json.loads(formatter.format(record))
            assert "trace_id" not in line
        finally:
            trace.set_tracer(None)


class TestHistogram:
    def test_buckets_render_cumulative_with_sum_and_count(self):
        h = Histogram("t_seconds", "help", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = "\n".join(h.render())
        assert 't_seconds_bucket{le="0.1"} 1' in text
        assert 't_seconds_bucket{le="1"} 2' in text
        assert 't_seconds_bucket{le="+Inf"} 3' in text
        assert "t_seconds_count 3" in text
        assert "t_seconds_sum 5.55" in text
        # the bare family name never renders as a series
        assert "\nt_seconds " not in f"\n{text}"

    def test_labels_render_independent_series(self):
        h = Histogram("l_seconds", "help", buckets=(1.0,))
        h.observe(0.5, labels={"op": "a"})
        h.observe(2.0, labels={"op": "b"})
        text = "\n".join(h.render())
        assert 'l_seconds_bucket{op="a",le="1"} 1' in text
        assert 'l_seconds_bucket{op="b",le="1"} 0' in text
        assert 'l_seconds_count{op="b"} 1' in text

    def test_preseed_creates_zero_series(self):
        reg = MetricsRegistry()
        h = reg.histogram("p_seconds", "help", buckets=(1.0,))
        h.preseed({"op": "create"})
        text = reg.render()
        assert 'p_seconds_bucket{op="create",le="+Inf"} 0' in text
        assert 'p_seconds_count{op="create"} 0' in text

    def test_quantile_interpolates(self):
        h = Histogram("q_seconds", "h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.5, 3.0):
            h.observe(v)
        assert h.quantile(0.0) == 0.0
        # p50: rank 2 falls in the (1, 2] bucket
        assert 1.0 <= h.quantile(0.5) <= 2.0
        assert 2.0 <= h.quantile(1.0) <= 4.0
        assert h.quantile(0.5, labels={"op": "x"}) is None
        with pytest.raises(ValueError):
            h.quantile(1.5)

    def test_exactly_bucket_boundary_counts_inclusive(self):
        h = Histogram("b_seconds", "h", buckets=(0.1, 1.0))
        h.observe(0.1)
        text = "\n".join(h.render())
        assert 'b_seconds_bucket{le="0.1"} 1' in text

    async def test_instrument_tracing_routes_span_names(self):
        tracer = Tracer()
        reg = instrument_tracing(tracer, MetricsRegistry())
        with tracer.span("zk.op", op="create", xid=1):
            pass
        with tracer.span("resolve.query", qtype="A", source="cached"):
            pass
        with tracer.span("health.exec", command="true"):
            pass
        with tracer.span("reconcile.sweep"):
            pass
        with tracer.span("unrouted.name"):
            pass
        zk_op = reg.get("registrar_zk_op_seconds")
        assert zk_op.count({"op": "create"}) == 1
        assert reg.get("registrar_resolve_seconds").count(
            {"source": "cached"}
        ) == 1
        assert reg.get("registrar_health_exec_seconds").count() == 1
        assert reg.get("registrar_reconcile_sweep_seconds").count() == 1
        # pre-seeded series exist before traffic
        text = reg.render()
        assert 'registrar_zk_op_seconds_count{op="delete"} 0' in text
        assert 'registrar_resolve_seconds_count{source="live"} 0' in text

    async def test_instrument_stands_down_its_sweep_gauge(self, caplog):
        # With the histogram registered first (tracing on), instrument()
        # must not collide on the family name — and without it the
        # last-value gauge renders exactly as before (parity).
        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            ee = register_plus(
                client, {"domain": "h.test.us", "type": "host"},
                admin_ip="10.0.0.1", hostname="hbox", settle_delay=0.01,
            )
            tracer = Tracer()
            reg = instrument_tracing(tracer, MetricsRegistry())
            instrument(ee, client, reg)  # must not raise duplicate
            await ee.wait_for("register", timeout=10)
            with caplog.at_level(logging.ERROR, "registrar_tpu.events"):
                ee.emit(
                    "reconcile", {"duration": 0.5, "drift": 0, "repaired": 0}
                )
            # The sweep handler must not blow up against the Histogram
            # (emit swallows listener exceptions into this log — a
            # regression here is invisible without the assertion) and
            # the sweeps counter still counts.
            assert not [
                r for r in caplog.records if "listener" in r.message
            ], caplog.text
            text = reg.render()
            assert "registrar_reconcile_sweeps_total 1" in text
            # histogram series, not the bare gauge sample
            assert "registrar_reconcile_sweep_seconds_bucket" in text
            assert "\nregistrar_reconcile_sweep_seconds 0.5" not in text
            ee.stop()
        finally:
            await client.close()
            await server.stop()


class TestEndpoints:
    async def test_status_endpoint_shape(self):
        async def provider():
            return {"session": {"id": "0x1"}, "ok": True}

        server = await MetricsServer(
            MetricsRegistry(), status_provider=provider
        ).start()
        try:
            status, head, body = await _http_get(
                server.host, server.port, "/status"
            )
            assert status == 200
            assert "application/json" in head
            assert json.loads(body) == {"session": {"id": "0x1"}, "ok": True}
        finally:
            await server.stop()

    async def test_status_provider_error_still_answers(self):
        async def provider():
            raise RuntimeError("introspection broke")

        server = await MetricsServer(
            MetricsRegistry(), status_provider=provider
        ).start()
        try:
            status, _, body = await _http_get(
                server.host, server.port, "/status"
            )
            assert status == 200
            assert "introspection broke" in json.loads(body)["error"]
        finally:
            await server.stop()

    async def test_debug_trace_endpoint_passes_n(self):
        seen = []

        def provider(n):
            seen.append(n)
            return {"enabled": True, "entries": []}

        server = await MetricsServer(
            MetricsRegistry(), trace_provider=provider
        ).start()
        try:
            status, _, body = await _http_get(
                server.host, server.port, "/debug/trace?n=7"
            )
            assert status == 200
            assert json.loads(body)["enabled"] is True
            await _http_get(server.host, server.port, "/debug/trace")
            await _http_get(server.host, server.port, "/debug/trace?n=bogus")
            assert seen == [7, None, None]
        finally:
            await server.stop()

    async def test_unwired_endpoints_404(self):
        server = await MetricsServer(MetricsRegistry()).start()
        try:
            for path in ("/status", "/debug/trace"):
                status, _, _ = await _http_get(server.host, server.port, path)
                assert status == 404, path
        finally:
            await server.stop()

    async def test_non_get_on_known_paths_is_405_with_allow(self):
        async def provider():
            return {}

        server = await MetricsServer(
            MetricsRegistry(),
            status_provider=provider,
            trace_provider=lambda n: {"enabled": False, "entries": []},
        ).start()
        try:
            for path in ("/metrics", "/status", "/debug/trace"):
                for method in ("POST", "PUT", "DELETE", "HEAD"):
                    status, head, _ = await _http_get(
                        server.host, server.port, path, method=method
                    )
                    assert status == 405, (method, path)
                    assert "Allow: GET" in head
            # unknown path keeps its 404, whatever the method
            status, _, _ = await _http_get(
                server.host, server.port, "/nope", method="POST"
            )
            assert status == 404
        finally:
            await server.stop()

    async def test_header_byte_flood_dropped(self):
        reg = MetricsRegistry()
        reg.counter("alive_total", "h").inc(1)
        server = await MetricsServer(reg).start()
        try:
            # Many modest header lines, together far past the bound:
            # the per-line limit never trips, the total-bytes bound must.
            flood = b"".join(
                b"X-Pad-%d: " % i + b"A" * 1024 + b"\r\n" for i in range(64)
            )
            assert len(flood) > MAX_HEADER_BYTES
            status, _, body = await _http_get(
                server.host, server.port, "/metrics", extra_headers=flood
            )
            assert status == 0 and body == b""  # dropped, no response owed
            # ...and the server is still alive for honest clients
            status, _, body = await _http_get(
                server.host, server.port, "/metrics"
            )
            assert status == 200 and b"alive_total 1" in body
        finally:
            await server.stop()

    async def test_modest_headers_still_fine(self):
        server = await MetricsServer(MetricsRegistry()).start()
        try:
            headers = b"User-Agent: prom/2.0\r\nAccept: text/plain\r\n"
            status, _, _ = await _http_get(
                server.host, server.port, "/metrics", extra_headers=headers
            )
            assert status == 200
        finally:
            await server.stop()


def _daemon_cfg(server, port, observability=None, **over):
    cfg = {
        "registration": {
            "domain": "traced.test.us",
            "type": "host",
            "heartbeatInterval": 100,
        },
        "adminIp": "10.7.7.7",
        "zookeeper": {
            "servers": [{"host": server.host, "port": server.port}],
            "timeout": 8000,
        },
        "metrics": {"port": port},
    }
    if observability is not None:
        cfg["observability"] = observability
    cfg.update(over)
    return cfg


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _poll_http(port, path, pred, timeout=20):
    deadline = asyncio.get_running_loop().time() + timeout
    last = None
    while asyncio.get_running_loop().time() < deadline:
        try:
            status, _, body = await _http_get("127.0.0.1", port, path)
            last = (status, body)
            if status == 200 and pred(body):
                return body
        except OSError:
            pass
        await asyncio.sleep(0.1)
    raise AssertionError(f"{path} never satisfied predicate (last={last})")


class TestDaemonEndToEnd:
    async def test_traced_daemon_serves_status_trace_and_histograms(self):
        from registrar_tpu.main import run

        port = _free_port()
        server = await ZKServer().start()
        cfg = parse_config(_daemon_cfg(
            server, port,
            observability={"sampleRate": 1.0, "flightRecorderSpans": 256},
            reconcile={"intervalSeconds": 0.2, "repair": False},
        ))
        task = asyncio.create_task(run(cfg, _exit=lambda code: None))
        try:
            body = await _poll_http(
                port, "/metrics",
                lambda b: b"registrar_registrations_total 1" in b,
            )
            # the tracing histograms exist and saw the pipeline's ops
            assert b'registrar_zk_op_seconds_bucket{op="create"' in body
            assert b"registrar_reconcile_sweep_seconds_bucket" in body

            status_body = await _poll_http(
                port, "/status",
                lambda b: json.loads(b)["registration"]["registered"],
            )
            snapshot = json.loads(status_body)
            assert snapshot["session"]["connected"] is True
            assert snapshot["session"]["id"].startswith("0x")
            (znode,) = snapshot["registration"]["znodes"]
            assert znode["path"].endswith(HOSTNAME)
            assert isinstance(znode["mzxid"], int)
            assert snapshot["config"]["fingerprint"]
            assert snapshot["observability"]["enabled"] is True
            assert snapshot["health"] == {
                "configured": False, "down": False, "checkerDown": False,
            }

            trace_body = await _poll_http(
                port, "/debug/trace?n=500",
                lambda b: json.loads(b)["enabled"],
            )
            dump = json.loads(trace_body)
            names = {e["name"] for e in dump["entries"]}
            assert "register.pipeline" in names
            assert "zk.op" in names
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            await server.stop()
        # the daemon restored the module default on the way out
        assert trace.get_tracer() is DISABLED

    async def test_untraced_daemon_metric_output_is_parity(self):
        from registrar_tpu.main import run

        port = _free_port()
        server = await ZKServer().start()
        cfg = parse_config(_daemon_cfg(server, port))  # no observability
        task = asyncio.create_task(run(cfg, _exit=lambda code: None))
        try:
            body = await _poll_http(
                port, "/metrics",
                lambda b: b"registrar_registrations_total 1" in b,
            )
            # zero new series without the block
            assert b"registrar_zk_op_seconds" not in body
            assert b"registrar_resolve_seconds" not in body
            assert b"registrar_health_exec_seconds" not in body
            # the sweep gauge is still the plain gauge
            assert b"# TYPE registrar_reconcile_sweep_seconds gauge" in body
            # /debug/trace answers honestly: tracing is off
            status, _, tbody = await _http_get(
                "127.0.0.1", port, "/debug/trace"
            )
            assert status == 200
            assert json.loads(tbody)["enabled"] is False
            assert trace.get_tracer() is DISABLED
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            await server.stop()


class TestRebirthChain:
    async def test_recorder_carries_loss_rebirth_reregistration_chain(self):
        # The deterministic single-server version of the chaos storm's
        # flight-recorder acceptance: force one expiry, watch the whole
        # recovery arc land in the ring as a connected span chain.
        server = await ZKServer().start()
        client = await ZKClient(
            [server.address],
            survive_session_expiry=True,
            reconnect_policy=RetryPolicy(
                max_attempts=float("inf"), initial_delay=0.02, max_delay=0.1
            ),
        ).connect()
        client.tracer = Tracer(max_spans=4096)
        try:
            ee = register_plus(
                client, {"domain": "chain.test.us", "type": "host"},
                admin_ip="10.0.0.5", hostname="cbox",
                heartbeat_interval=60, settle_delay=0.01,
            )
            await ee.wait_for("register", timeout=10)
            rereg = asyncio.ensure_future(ee.wait_for("register", timeout=10))
            await server.expire_session(client.session_id)
            await rereg
            entries = client.tracer.dump()["entries"]
            names = {e["name"] for e in entries}
            assert {"zk.session_lost", "zk.session_reborn"} <= names
            repairs = {
                e["span_id"]: e["trace_id"]
                for e in entries
                if e["kind"] == "span" and e["name"] == "agent.repair"
            }
            assert any(
                e["kind"] == "span"
                and e["name"] == "register.pipeline"
                and e.get("parent_id") in repairs
                for e in entries
            ), names
            ee.stop()
        finally:
            await client.close()
            await server.stop()


def _spawn_daemon(cfg_path, env_extra=None):
    return subprocess.Popen(
        [sys.executable, "-m", "registrar_tpu", "-f", str(cfg_path)],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env={**os.environ, "PYTHONPATH": REPO,
             "LOG_LEVEL": "debug", **(env_extra or {})},
    )


class TestSigusr2Subprocess:
    async def test_sigusr2_dumps_flight_recorder_and_logs_correlate(
        self, tmp_path
    ):
        server = await ZKServer().start()
        observer = await ZKClient([server.address]).connect()
        dump_path = tmp_path / "flight.json"
        cfg_path = tmp_path / "config.json"
        cfg_path.write_text(json.dumps(_daemon_cfg(
            server, _free_port(),
            observability={"sampleRate": 1.0,
                           "dumpPath": str(dump_path)},
        )))
        proc = None
        try:
            proc = _spawn_daemon(cfg_path)
            deadline = asyncio.get_running_loop().time() + 20
            while (await observer.exists("/us/test/traced")) is None:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.1)
            proc.send_signal(signal.SIGUSR2)
            deadline = asyncio.get_running_loop().time() + 10
            while not dump_path.exists():
                assert asyncio.get_running_loop().time() < deadline, (
                    "SIGUSR2 produced no dump file"
                )
                await asyncio.sleep(0.1)
            # the dump may still be mid-write on slow disks: poll for
            # parseable JSON within the same deadline
            payload = None
            while payload is None:
                try:
                    payload = json.loads(dump_path.read_text())
                except ValueError:
                    assert asyncio.get_running_loop().time() < deadline
                    await asyncio.sleep(0.1)
            assert payload["enabled"] is True
            assert "register.pipeline" in {
                e["name"] for e in payload["entries"]
            }
        finally:
            if proc is not None:
                proc.terminate()
                out = proc.stdout.read().decode()
                proc.wait(15)
            await observer.close()
            await server.stop()
        # jlog correlation, end to end: debug lines logged inside spans
        # carry trace_id/span_id; the dump confirmation line is plain.
        traced_lines = []
        for line in out.splitlines():
            try:
                record = json.loads(line)
            except ValueError:
                continue
            if "trace_id" in record:
                traced_lines.append(record)
        assert traced_lines, "no log line carried trace correlation"
        assert all(
            record.get("span_id") for record in traced_lines
        )
        assert any("flight recorder dumped" in line for line in out.splitlines())


class TestZkcliStatusTrace:
    async def _run_cli(self, argv, capsys):
        from registrar_tpu.tools.zkcli import _amain

        code = await _amain(argv)
        captured = capsys.readouterr()
        return code, captured.out, captured.err

    async def test_status_and_trace_against_live_daemon(
        self, tmp_path, capsys
    ):
        from registrar_tpu.main import run

        port = _free_port()
        server = await ZKServer().start()
        raw = _daemon_cfg(
            server, port, observability={"sampleRate": 1.0}
        )
        cfg_path = tmp_path / "config.json"
        cfg_path.write_text(json.dumps(raw))
        cfg = parse_config(raw)
        cfg.source_path = str(cfg_path)
        task = asyncio.create_task(run(cfg, _exit=lambda code: None))
        try:
            await _poll_http(
                port, "/status",
                lambda b: json.loads(b)["registration"]["registered"],
            )
            code, out, err = await self._run_cli(
                ["status", "-f", str(cfg_path)], capsys
            )
            assert code == 0, err
            assert "healthy" in err
            # ISSUE 10: the connected member's real role, probed off its
            # srvr admin word (a standalone test server reports exactly
            # that), plus the /status readOnly flag
            assert "role=standalone" in err
            snapshot = json.loads(out)
            assert snapshot["session"]["connected"] is True
            assert snapshot["session"]["readOnly"] is False

            code, out, err = await self._run_cli(
                ["trace", "-f", str(cfg_path), "-n", "50"], capsys
            )
            assert code == 0, err
            assert "register.pipeline" in out
            assert "entries" in err
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            await server.stop()

    async def test_trace_reports_disabled_as_one(self, tmp_path, capsys):
        from registrar_tpu.main import run

        port = _free_port()
        server = await ZKServer().start()
        raw = _daemon_cfg(server, port)  # observability absent
        cfg_path = tmp_path / "config.json"
        cfg_path.write_text(json.dumps(raw))
        cfg = parse_config(raw)
        task = asyncio.create_task(run(cfg, _exit=lambda code: None))
        try:
            await _poll_http(
                port, "/metrics",
                lambda b: b"registrar_registrations_total 1" in b,
            )
            code, _out, err = await self._run_cli(
                ["trace", "-f", str(cfg_path)], capsys
            )
            assert code == 1
            assert "disabled" in err
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            await server.stop()

    async def test_unreachable_and_missing_metrics_block_exit_2(
        self, tmp_path, capsys
    ):
        # no metrics block at all
        cfg_path = tmp_path / "nometrics.json"
        cfg_path.write_text(json.dumps({
            "registration": {"domain": "x.test.us", "type": "host"},
            "zookeeper": {"servers": [{"host": "127.0.0.1", "port": 1}]},
        }))
        for cmd in ("status", "trace"):
            code, _out, err = await self._run_cli(
                [cmd, "-f", str(cfg_path)], capsys
            )
            assert code == 2
            assert "metrics" in err
        # metrics block present but nothing listening
        cfg_path2 = tmp_path / "dead.json"
        cfg_path2.write_text(json.dumps({
            "registration": {"domain": "x.test.us", "type": "host"},
            "zookeeper": {"servers": [{"host": "127.0.0.1", "port": 1}]},
            "metrics": {"port": _free_port()},
        }))
        for cmd in ("status", "trace"):
            code, _out, _err = await self._run_cli(
                [cmd, "-f", str(cfg_path2), "--timeout", "1"], capsys
            )
            assert code == 2

    async def test_status_degraded_exits_one(self, capsys, tmp_path):
        # A snapshot reporting a disconnected, unregistered instance
        # must exit 1 with the reasons named.
        async def provider():
            return {
                "session": {"connected": False, "state": "disconnected"},
                "registration": {"registered": False, "znodes": []},
                "health": {"down": True},
                "reconcile": {"lastSweep": {"drift": 3}},
            }

        mserver = await MetricsServer(
            MetricsRegistry(), status_provider=provider
        ).start()
        try:
            cfg_path = tmp_path / "degraded.json"
            cfg_path.write_text(json.dumps({
                "registration": {"domain": "x.test.us", "type": "host"},
                "zookeeper": {"servers": [{"host": "127.0.0.1", "port": 1}]},
                "metrics": {"port": mserver.port},
            }))
            code, _out, err = await self._run_cli(
                ["status", "-f", str(cfg_path)], capsys
            )
            assert code == 1
            assert "DEGRADED" in err
            for reason in ("disconnected", "not registered", "health-down",
                           "drift=3"):
                assert reason in err
        finally:
            await mserver.stop()


class TestObservabilityConfig:
    def _base(self, observability):
        return {
            "registration": {"domain": "c.test.us", "type": "host"},
            "zookeeper": {"servers": [{"host": "h", "port": 1}]},
            "observability": observability,
        }

    def test_defaults(self):
        cfg = parse_config(self._base({}))
        obs = cfg.observability
        assert obs.sample_rate == 1.0
        assert obs.slow_span_ms == 1500.0
        assert obs.flight_recorder_spans == 1024
        assert obs.dump_path is None

    def test_absent_block_is_none(self):
        raw = self._base({})
        del raw["observability"]
        assert parse_config(raw).observability is None

    def test_explicit_values(self):
        cfg = parse_config(self._base({
            "sampleRate": 0.25, "slowSpanMs": 50,
            "flightRecorderSpans": 16, "dumpPath": "/tmp/t.json",
        }))
        obs = cfg.observability
        assert obs.sample_rate == 0.25
        assert obs.slow_span_ms == 50.0
        assert obs.flight_recorder_spans == 16
        assert obs.dump_path == "/tmp/t.json"

    def test_slow_span_null_disables(self):
        cfg = parse_config(self._base({"slowSpanMs": None}))
        assert cfg.observability.slow_span_ms is None

    @pytest.mark.parametrize("bad", [
        {"sampleRate": -0.1}, {"sampleRate": 1.1}, {"sampleRate": "1"},
        {"sampleRate": True}, {"slowSpanMs": 0}, {"slowSpanMs": "fast"},
        {"flightRecorderSpans": 0}, {"flightRecorderSpans": 1.5},
        {"flightRecorderSpans": True}, {"dumpPath": ""}, {"dumpPath": 7},
        "not-an-object",
    ])
    def test_invalid_blocks_rejected(self, bad):
        with pytest.raises(ConfigError):
            parse_config(self._base(bad))

    def test_tracer_rejects_bad_args(self):
        with pytest.raises(ValueError):
            Tracer(sample_rate=1.5)
        with pytest.raises(ValueError):
            Tracer(max_spans=0)


class TestFailoverSpan:
    """ISSUE 10: an unexpected disconnect opens ONE zk.failover span
    that closes on the next successful handshake — old member, new
    member, and a duration covering the whole between-members window."""

    async def test_member_death_records_failover_span(self):
        from registrar_tpu.retry import RetryPolicy
        from registrar_tpu.testing.server import ZKEnsemble

        fast = RetryPolicy(
            max_attempts=float("inf"), initial_delay=0.02, max_delay=0.2
        )
        async with ZKEnsemble(3) as ens:
            client = ZKClient(
                ens.addresses, timeout_ms=60_000, reconnect_policy=fast
            )
            client.tracer = trace.Tracer(sample_rate=1.0)
            await client.connect()
            try:
                old = client.connected_server
                reconnected = asyncio.Event()
                client.on("connect", lambda *a: reconnected.set())
                for i, member in enumerate(ens.servers):
                    if (member.host, member.port) == old:
                        await ens.kill(i)
                        break
                await asyncio.wait_for(reconnected.wait(), timeout=10)
                spans = _spans(client.tracer, "zk.failover")
                assert len(spans) == 1
                sp = spans[0]
                assert sp["attrs"]["from"] == f"{old[0]}:{old[1]}"
                new = client.connected_server
                assert sp["attrs"]["to"] == f"{new[0]}:{new[1]}"
                assert sp["status"] == "ok"
                assert sp["duration_ms"] >= 0
            finally:
                await client.close()

    async def test_terminal_close_finishes_open_failover_span_error(self):
        from registrar_tpu.retry import RetryPolicy
        from registrar_tpu.testing.server import ZKEnsemble

        fast = RetryPolicy(
            max_attempts=float("inf"), initial_delay=0.05, max_delay=0.2
        )
        async with ZKEnsemble(1) as ens:
            client = ZKClient(
                ens.addresses, timeout_ms=60_000, reconnect_policy=fast
            )
            client.tracer = trace.Tracer(sample_rate=1.0)
            await client.connect()
            await ens.kill(0)  # nothing to fail over to
            await asyncio.sleep(0.05)
            await client.close()  # terminal: the failover never landed
            spans = _spans(client.tracer, "zk.failover")
            assert len(spans) == 1
            assert spans[0]["status"] == "error"
