"""Shared test configuration.

* Forces JAX (used only by the harness-compliance tests for
  __graft_entry__.py) onto a virtual 8-device CPU mesh, per the driver's
  documented validation mode.
* Minimal async-test support: ``async def`` tests run under a fresh event
  loop via ``asyncio.run`` (pytest-asyncio is not available in this image).
"""

import asyncio
import inspect
import os
import sys

# Must be set before jax is imported anywhere in the test process.  An
# explicit override (not setdefault): the image may pin JAX_PLATFORMS to
# an experimental TPU plugin whose initialization can hang for minutes;
# the opt-in jax-marked tests validate the virtual CPU mesh only.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
if "jax" in sys.modules:
    # The image's sitecustomize may have imported jax at interpreter start
    # (binding JAX_PLATFORMS=axon from the env); the env change above is
    # then too late, so pin the live config too.  Backends have not been
    # initialized yet at conftest-import time, so this still takes effect.
    sys.modules["jax"].config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest


def pytest_collection_modifyitems(items):
    # Allow plain `async def` test functions.
    for item in items:
        if isinstance(item, pytest.Function) and inspect.iscoroutinefunction(
            item.function
        ):
            item.add_marker(pytest.mark.asyncio_shim)


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    func = pyfuncitem.obj  # bound method for class-based tests
    if inspect.iscoroutinefunction(func):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        # Hang guard.  The chaos storm runs CHAOS_SECONDS of churn plus a
        # convergence pass, so its budget must scale with the requested
        # storm length (a fixed 60 s cap silently forbids `CHAOS_SECONDS`
        # beyond ~55) — same slack for every test, chaos just starts later.
        try:
            budget = 60 + float(os.environ.get("CHAOS_SECONDS", 0) or 0)
        except ValueError:
            # Malformed value: keep the default so only the chaos test
            # (which parses the variable itself) reports it, instead of
            # every async test in the suite erroring.
            budget = 60

        async def _run():
            await asyncio.wait_for(func(**kwargs), timeout=budget)
            # One extra tick so subprocess/socket transports finish closing
            # before asyncio.run tears the loop down (avoids GC warnings).
            await asyncio.sleep(0.01)

        asyncio.run(_run())
        return True
    return None


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio_shim: run coroutine test via asyncio.run")
    config.addinivalue_line(
        "markers",
        "jax: needs jax; deselected by default (see pyproject addopts), "
        "run with `make test-jax`",
    )
