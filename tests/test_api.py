"""Public API surface test.

The reference flat-re-exports every symbol from health/register/zk beside
the default register_plus export (reference lib/index.js:184-186), and its
tests consume that surface (reference test/helper.js:45).  Pin ours.
"""

import registrar_tpu


def test_flat_reexport_surface():
    # the reference's module surface, translated
    assert callable(registrar_tpu.register_plus)
    assert callable(registrar_tpu.register)
    assert callable(registrar_tpu.unregister)
    assert callable(registrar_tpu.create_health_check)
    assert callable(registrar_tpu.create_zk_client)
    assert callable(registrar_tpu.domain_to_path)
    assert callable(registrar_tpu.host_record)
    assert callable(registrar_tpu.service_record)
    assert callable(registrar_tpu.default_address)
    assert isinstance(registrar_tpu.HOST_RECORD_TYPES, dict)
    # classes
    assert isinstance(registrar_tpu.ZKClient, type)
    assert isinstance(registrar_tpu.HealthCheck, type)
    assert isinstance(registrar_tpu.RegistrarEvents, type)


def test_every_export_in_all_resolves():
    for name in registrar_tpu.__all__:
        assert getattr(registrar_tpu, name) is not None, name


def test_extension_exports():
    # beyond-reference surface: metrics + Binder-view resolution
    assert isinstance(registrar_tpu.MetricsRegistry, type)
    assert isinstance(registrar_tpu.MetricsServer, type)
    assert callable(registrar_tpu.instrument)
    assert callable(registrar_tpu.resolve)


def test_version():
    assert registrar_tpu.__version__


def test_unknown_attribute_raises():
    try:
        registrar_tpu.nope
    except AttributeError as e:
        assert "nope" in str(e)
    else:
        raise AssertionError("expected AttributeError")
