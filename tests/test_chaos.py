"""Chaos soak: seeded fault injection under register/unregister churn.

The existing soak/ensemble tests only ever kill a member *between*
operations.  Here a chaos task kills and restarts ensemble members,
severs client connections, and toggles per-member replication lag at
random moments — statistically landing inside the five-stage
registration pipeline (cleanup → settle → mkdirp → create → service
put), exactly where orphan ephemerals or half-registrations would be
minted — while N registrars churn register/heartbeat/unregister
through it all (stale reads, ahead-of-view connection refusals, and
catch-up all exercised under churn).

Afterwards the system must converge:

  * every registrar ends registered, its host znode ephemeral-owned by
    its own live session;
  * the persistent service record at the domain node is intact;
  * no orphan ephemerals anywhere in the tree (an ephemeral whose owner
    session no longer exists);
  * the Binder view answers with exactly the N live instances.

Reproducibility: the run is driven by one RNG seed, printed at start
(so it appears in pytest's captured output on failure).  Pin it with
``CHAOS_SEED=<n>``; lengthen the churn window with ``CHAOS_SECONDS=<s>``
(default keeps the whole test well under 10 s).

Network faults: unless ``CHAOS_NETEM=0``, every worker's connections are
routed through a per-member :class:`registrar_tpu.testing.netem.ChaosProxy`
and the storm also toggles seeded wire toxics (latency/jitter, bandwidth
throttle, frame slicing, reset-after-N — the transient entries of
``netem.STORM_TOXICS``) on and off, so the churn exercises the client's
per-operation deadlines and reconnect armor, not just server-side kills.
The same ``CHAOS_SEED`` drives the toxic schedule; the storm-over cleanup
heals every proxy before convergence is asserted.

Failure-detection parity: SURVEY.md §5 — liveness via sessions,
crash-and-restart recovery, idempotent re-registration
(reference lib/register.js:78-105 cleanup stage) are the app's core
domain; this is the adversarial test of all three at once.
"""

import asyncio
import json
import os
import random
import sys

from registrar_tpu import binderview
from registrar_tpu.agent import register_plus
from registrar_tpu.records import parse_payload
from registrar_tpu.registration import register, unregister
from registrar_tpu.retry import RetryPolicy
from registrar_tpu.testing.netem import DOWN, STORM_TOXICS, UP, ChaosProxy
from registrar_tpu.testing.server import ZKEnsemble
from registrar_tpu.zk.client import SessionExpiredError, ZKClient
from registrar_tpu.zk.protocol import CreateFlag, ZKError

DOMAIN = "chaos.prod.us"
PATH = "/us/prod/chaos"
N_WORKERS = 6
ENSEMBLE = 3

#: chaos-appropriate reconnect: spin back fast instead of the production
#: 1–90 s schedule, so convergence after the storm is quick
FAST_RECONNECT = RetryPolicy(
    max_attempts=float("inf"), initial_delay=0.02, max_delay=0.25
)


def _reg():
    return {
        "domain": DOMAIN,
        "type": "load_balancer",
        "service": {
            "type": "service",
            "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
        },
    }


class _Worker:
    """One registrar instance churning through the chaos."""

    def __init__(
        self, i: int, ens: ZKEnsemble, seed: int, addresses=None,
        can_be_read_only: bool = False,
    ):
        self.i = i
        self.ens = ens
        self.rng = random.Random(seed)
        self.hostname = f"chaos{i}"
        self.admin_ip = f"10.9.0.{i + 1}"
        #: where this worker dials: the ensemble directly, or (netem mode)
        #: the per-member ChaosProxy front doors
        self.addresses = addresses or ens.addresses
        self.can_be_read_only = can_be_read_only
        self.client: ZKClient = None
        self.nodes = None
        self.ops = 0

    async def connect(self) -> None:
        self.client = ZKClient(
            self.addresses,
            timeout_ms=8000,
            # fail fast through a faulted proxy instead of hanging an op
            # on a sliced/stalled wire for the rest of the storm
            request_timeout_ms=1500,
            connect_timeout_ms=500,
            reconnect_policy=FAST_RECONNECT,
            # connect order seeded off the worker's own seeded RNG, so a
            # CHAOS_SEED replay walks the members identically (ISSUE 10)
            rng=random.Random(self.rng.randrange(2**32)),
            can_be_read_only=self.can_be_read_only,
        )
        if self.can_be_read_only:
            self.client.rw_probe_interval_s = 0.1
        await self.client.connect()

    async def _register(self) -> None:
        self.nodes = await register(
            self.client,
            _reg(),
            admin_ip=self.admin_ip,
            hostname=self.hostname,
            # short but non-zero: keeps the pipeline window open so
            # chaos can land between its stages
            settle_delay=self.rng.uniform(0.005, 0.04),
        )

    async def churn(self, stop: asyncio.Event) -> None:
        while not stop.is_set():
            try:
                if self.nodes is None:
                    await self._register()
                else:
                    roll = self.rng.random()
                    if roll < 0.45:
                        await self.client.heartbeat(
                            self.nodes, retry=RetryPolicy(max_attempts=1)
                        )
                    elif roll < 0.75:
                        await unregister(self.client, self.nodes)
                        self.nodes = None
                    else:
                        # re-register over the live registration: the
                        # cleanup stage must make this idempotent
                        await self._register()
                self.ops += 1
            except SessionExpiredError:
                await self.connect()  # fresh session; ephemerals are gone
                self.nodes = None
            except (ZKError, ConnectionError, OSError):
                # interrupted mid-pipeline; state unknown — the next
                # register()'s cleanup stage reconciles it
                self.nodes = None
                if self.client.closed:
                    # a force-expired session surfaces as CONNECTION_LOSS
                    # on ops (SessionExpiredError is only raised from the
                    # connect path) and self-closes the client: build a
                    # fresh session NOW so recovery happens under churn,
                    # not just in the post-storm converge pass
                    try:
                        await self.connect()
                    except Exception:  # noqa: BLE001 - all members down
                        pass  # retried next iteration
            await asyncio.sleep(self.rng.uniform(0.0, 0.02))

    async def converge(self) -> None:
        """Post-storm: end registered, however the churn left us."""
        for _ in range(200):
            try:
                if self.client.closed:
                    await self.connect()
                await self._register()
                return
            except SessionExpiredError:
                await self.connect()
                self.nodes = None
            except (ZKError, ConnectionError, OSError):
                await asyncio.sleep(0.05)
        raise AssertionError(f"worker {self.i} never converged")


async def _chaos_task(
    ens: ZKEnsemble,
    rng: random.Random,
    stop: asyncio.Event,
    events: list,
    max_events: float = float("inf"),
    proxies: list = None,
) -> None:
    while not stop.is_set() and len(events) < max_events:
        await asyncio.sleep(rng.uniform(0.02, 0.1))
        live = [
            i
            for i, m in enumerate(ens.servers)
            if m is not None and m._server is not None
        ]
        dead = [i for i in range(ENSEMBLE) if i not in live]
        if proxies is not None and rng.random() < 0.3:
            # Network fault instead of a server fault this round: toggle
            # a seeded toxic on one member's proxy (off if one is on).
            # STORM_TOXICS is the transient palette — traffic eventually
            # passes or resets, so the storm stays convergeable; the
            # forever-silent toxics have their own deterministic tests.
            i = rng.randrange(len(proxies))
            proxy = proxies[i]
            if proxy.toxics(UP) or proxy.toxics(DOWN):
                proxy.clear()
                events.append(("netem-off", i))
            else:
                kind = rng.choice(sorted(STORM_TOXICS))
                direction = rng.choice((UP, DOWN))
                proxy.add(STORM_TOXICS[kind](rng), direction=direction)
                events.append(("netem-on", i, kind, direction))
            continue
        roll = rng.random()
        if roll < 0.3 and len(live) > 1:
            i = rng.choice(live)
            await ens.kill(i)
            events.append(("kill", i))
        elif roll < 0.55 and dead:
            i = rng.choice(dead)
            await ens.restart(i)
            events.append(("restart", i))
        elif roll < 0.7 and live:
            # toggle replication lag: stale reads, refused reconnects
            # from ahead-of-view clients, catch-up on writes — all under
            # churn
            i = rng.choice(live)
            lagging = ens.servers[i].apply_delay_ms > 0
            ens.set_lag(i, 0 if lagging else 150)
            events.append(("lag-off" if lagging else "lag-on", i))
        elif roll < 0.85 and live:
            # force-expire a random session (ZK's worst news for a
            # registrar): its ephemerals must be swept, the worker must
            # build a fresh session and re-register.  This is the path
            # that mints orphans if ephemeral sweeping ever breaks —
            # without it the storm is too short for natural expiry and
            # the orphan detector guards nothing (verified by mutation).
            sids = sorted(
                s.session_id
                for s in ens.state.sessions.values()
                if s.connected
            )
            if sids:
                # record the index, not the (time-seeded) session id, so
                # fixed-seed schedules compare equal across runs
                idx = rng.randrange(len(sids))
                await ens.servers[live[0]].expire_session(sids[idx])
                events.append(("expire", idx))
        elif live:
            i = rng.choice(live)
            await ens.servers[i].drop_connections()
            events.append(("drop", i))
    # storm over: restore full strength, linearizable reads, clean wires
    for i in range(ENSEMBLE):
        await ens.restart(i)
        ens.set_lag(i, 0)
    for proxy in proxies or []:
        proxy.clear()


def _orphan_ephemerals(ens: ZKEnsemble) -> list:
    """Every ephemeral in the tree whose owner session is gone."""
    orphans = []

    def walk(node, prefix):
        for name, child in node.children.items():
            path = f"{prefix}/{name}" if prefix != "/" else f"/{name}"
            if child.ephemeral_owner:
                sess = ens.state.sessions.get(child.ephemeral_owner)
                if sess is None or sess.closed:
                    orphans.append((path, child.ephemeral_owner))
            walk(child, path)

    walk(ens.state.root, "/")
    return orphans


async def test_chaos_churn_converges():
    seed = int(os.environ.get("CHAOS_SEED", random.randrange(2**32)))
    churn_s = float(os.environ.get("CHAOS_SECONDS", "2.5"))
    netem = os.environ.get("CHAOS_NETEM", "1") != "0"
    print(
        f"CHAOS_SEED={seed} CHAOS_SECONDS={churn_s} "
        f"CHAOS_NETEM={int(netem)}",
        file=sys.stderr,
    )
    rng = random.Random(seed)

    async with ZKEnsemble(ENSEMBLE, tick_ms=20) as ens:
        # Netem mode: one fault-injection proxy fronts each member; the
        # workers only ever dial the proxies, so every byte of the churn
        # crosses the toxic-injectable wire.  (The victim client and the
        # orphan sweep below stay direct — they assert server-side truth.)
        proxies = []
        if netem:
            for addr in ens.addresses:
                proxies.append(
                    await ChaosProxy(addr, seed=rng.randrange(2**32)).start()
                )
        worker_addrs = [p.address for p in proxies] if netem else None
        workers = [
            _Worker(i, ens, rng.randrange(2**32), addresses=worker_addrs)
            for i in range(N_WORKERS)
        ]
        for w in workers:
            await w.connect()

        # A victim ephemeral at a path NO worker ever re-registers: the
        # workers' own cleanup stage recycles their leaked paths, so
        # this is the node that stays orphaned if ephemeral sweeping on
        # session expiry ever breaks (the orphan detector's real teeth —
        # the mutation probe that leaks ephemerals passes without it).
        # Set up before the storm starts: its connect/create must not
        # race the first fault.
        victim = ZKClient(ens.addresses, timeout_ms=8000,
                          reconnect_policy=FAST_RECONNECT)
        await victim.connect()
        await victim.create("/chaos-victim", b"", CreateFlag.EPHEMERAL)

        stop = asyncio.Event()
        events: list = []
        tasks = [asyncio.create_task(w.churn(stop)) for w in workers]
        chaos = asyncio.create_task(
            _chaos_task(ens, rng, stop, events, proxies=proxies or None)
        )

        await asyncio.sleep(churn_s)
        stop.set()
        await asyncio.gather(*tasks)
        await chaos  # restores all members
        assert events, "chaos task injected no faults"
        total_ops = sum(w.ops for w in workers)
        assert total_ops >= N_WORKERS, f"churn barely ran ({total_ops} ops)"

        # The victim's session dies with the storm: its ephemeral must be
        # swept, not orphaned (read via the shared tree — workers may
        # still be mid-recovery here).
        await ens.live[0].expire_session(victim.session_id)
        assert ens.get_node("/chaos-victim") is None, (
            "victim ephemeral survived its session's expiry"
        )

        # -- convergence ---------------------------------------------------
        await asyncio.gather(*(w.converge() for w in workers))

        try:
            # every worker owns its host znode with its live session
            for w in workers:
                st = await w.client.stat(f"{PATH}/{w.hostname}")
                assert st is not None
                assert st.ephemeral_owner == w.client.session_id, (
                    f"worker {w.i}: owner 0x{st.ephemeral_owner:x} != "
                    f"session 0x{w.client.session_id:x}"
                )

            # the persistent service record survived the storm
            svc, svc_st = await workers[0].client.get(PATH)
            assert svc_st.ephemeral_owner == 0
            assert parse_payload(svc)["type"] == "service"

            # no ephemeral anywhere belongs to a dead session
            orphans = _orphan_ephemerals(ens)
            assert not orphans, f"orphan ephemerals: {orphans}"

            # the Binder view answers with exactly the live fleet
            res = await binderview.resolve(workers[0].client, DOMAIN, "A")
            assert sorted(a.data for a in res.answers) == sorted(
                w.admin_ip for w in workers
            )

            # Full teardown drains the domain completely.  Host nodes
            # first; the shared domain node (the service record, which
            # register appended to every worker's owned list) can only
            # go once it has no children — the same NOT_EMPTY ordering a
            # fleet draining against real ZooKeeper must respect.
            for w in workers:
                await unregister(
                    w.client, [n for n in w.nodes if n != PATH]
                )
            kids = await workers[0].client.get_children(PATH)
            assert kids == []
            await unregister(workers[0].client, [PATH])
            assert await workers[0].client.exists(PATH) is None
            orphans = _orphan_ephemerals(ens)
            assert not orphans, f"orphans after teardown: {orphans}"
        finally:
            if not victim.closed:
                await victim.close()
            for w in workers:
                if w.client is not None and not w.client.closed:
                    await w.client.close()
            for proxy in proxies:
                await proxy.stop()


class _RebornWorker:
    """One full agent stack (register_plus + surviveSessionExpiry client +
    repairing reconciler) riding out the expiry storm in-process.

    The ISSUE 5 rider adds :meth:`restart` — the in-process analog of
    "SIGTERM + relaunch" mid-storm, in both restart modes: ``handoff``
    detaches the live session and the successor agent reattaches it
    (``seed_session``) and verifies-not-recreates; ``drain`` unregisters,
    closes, and the successor registers fresh.  A handoff whose session
    the storm expired in the gap is refused by the server and must
    degrade to a fresh registration — never to a terminal expiry.

    The ISSUE 8 rider gives each worker its own flight recorder
    (``tracer``): a failing storm dumps every worker's recent spans and
    events (the CI chaos job uploads the dumps as artifacts), so a
    seed that fails in CI arrives with the span chain across session
    loss → rebirth → re-registration already in hand.
    """

    def __init__(self, i: int, addresses, tracer=None):
        self.i = i
        self.hostname = f"reborn{i}"
        self.admin_ip = f"10.9.1.{i + 1}"
        self.addresses = addresses
        self.tracer = tracer
        self.client: ZKClient = None
        self.ee = None
        #: terminal session_expired events — the "process exit" analog
        #: (main.py's _die fires exactly on this event)
        self.terminal_expiries = 0
        self.restarts = 0
        self.resumed_restarts = 0
        self._restarting = False

    async def start(self, resume=None) -> None:
        """``resume``: a predecessor's ``(session_id, passwd,
        negotiated_timeout_ms, last_zxid, znodes)`` handoff tuple."""
        from registrar_tpu.retry import call_with_backoff

        self.client = ZKClient(
            self.addresses,
            timeout_ms=8000,
            connect_timeout_ms=500,
            survive_session_expiry=True,
            # the storm deliberately expires sessions far faster than any
            # production incident; the breaker must not be the variable
            # under test here (it has its own deterministic test)
            max_session_rebirths=10_000,
            reconnect_policy=FAST_RECONNECT,
        )
        # the recorder survives restarts: the successor client reports
        # into the same per-worker ring as its predecessor
        self.client.tracer = self.tracer
        manifest = None
        if resume is not None:
            sid, passwd, timeout_ms, zxid, znodes = resume
            self.client.seed_session(
                sid, passwd, negotiated_timeout_ms=timeout_ms,
                last_zxid=zxid,
            )
            await call_with_backoff(
                self.client.connect, FAST_RECONNECT,
                retryable=lambda _e: not self.client.closed,
            )
            if self.client.session_id == sid:
                manifest = list(znodes)
                self.resumed_restarts += 1
        else:
            await call_with_backoff(
                self.client.connect, FAST_RECONNECT,
                retryable=lambda _e: not self.client.closed,
            )

        def on_terminal(*_a):
            self.terminal_expiries += 1

        self.client.on("session_expired", on_terminal)
        self.ee = register_plus(
            self.client,
            _reg(),
            admin_ip=self.admin_ip,
            hostname=self.hostname,
            settle_delay=0.01,
            heartbeat_interval=0.1,
            heartbeat_retry=RetryPolicy(
                max_attempts=1, initial_delay=0.01, max_delay=0.01
            ),
            register_retry=RetryPolicy(
                max_attempts=5, initial_delay=0.02, max_delay=0.2,
                jitter="decorrelated",
            ),
            reconcile={"interval_seconds": 0.1, "repair": True},
            resume_manifest=manifest,
        )
        await self.ee.wait_for("register", timeout=10)

    async def restart(self, mode: str) -> None:
        """SIGTERM + relaunch, in-process: stop the agent, hand off or
        drain per ``mode``, then bring up a successor agent — retrying
        until it lands (a "supervisor" that keeps relaunching; the
        convergence assertion owns the overall deadline)."""
        if self._restarting:
            return
        self._restarting = True
        try:
            self.restarts += 1
            ee, client = self.ee, self.client
            znodes = list(ee.znodes)
            ee.stop()
            resume = None
            if mode == "handoff" and not client.closed and client.session_id:
                resume = (
                    client.session_id, client.session_passwd,
                    client.negotiated_timeout_ms, client.last_zxid, znodes,
                )
                await client.detach()
            else:
                try:
                    if not client.closed and znodes:
                        await unregister(client, znodes)
                except (ZKError, ConnectionError, OSError):
                    pass  # mid-storm: the successor's cleanup reconciles
                if not client.closed:
                    await client.close()
            while True:
                try:
                    await self.start(resume=resume)
                    return
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 - relaunch like a supervisor
                    resume = None  # one resume attempt, then fresh
                    if self.ee is not None:
                        self.ee.stop()
                    if self.client is not None and not self.client.closed:
                        try:
                            await self.client.close()
                        except Exception:  # noqa: BLE001
                            pass
                    await asyncio.sleep(0.2)
        finally:
            self._restarting = False

    async def stop(self) -> None:
        if self.ee is not None:
            self.ee.stop()
        if self.client is not None and not self.client.closed:
            await self.client.close()


def _dump_flight_recorders(workers) -> None:
    """A failing storm leaves each worker's flight recorder on disk
    (CHAOS_DUMP_DIR, default cwd) — the CI chaos job uploads the dumps
    next to the job summary, so the failure arrives with the span chain
    already in hand (ISSUE 8 satellite).  ISSUE 13 rider: the biggest
    trace across all recorders is additionally ASSEMBLED into
    ``chaos-worst-trace.txt``/``.json`` next to the per-worker dumps —
    the merged parent tree, not per-process fragments."""
    out_dir = os.environ.get("CHAOS_DUMP_DIR", ".")
    entries = []
    for w in workers:
        if w.tracer is None:
            continue
        try:
            path = w.tracer.dump_to_file(
                os.path.join(out_dir, f"chaos-flight-worker{w.i}.json")
            )
        except OSError as err:
            print(f"flight-recorder dump for worker {w.i} failed: {err!r}",
                  file=sys.stderr)
        else:
            print(f"flight recorder dumped: {path}", file=sys.stderr)
        for entry in w.tracer.dump().get("entries", ()):
            entry = dict(entry)
            entry.setdefault("proc", f"worker{w.i}")
            entries.append(entry)
    by_trace = {}
    for entry in entries:
        tid = entry.get("trace_id")
        if tid:
            by_trace[tid] = by_trace.get(tid, 0) + 1
    if not by_trace:
        return
    from registrar_tpu import traceview

    worst_id = max(by_trace, key=by_trace.get)
    tree = traceview.assemble(entries, worst_id)
    try:
        with open(
            os.path.join(out_dir, "chaos-worst-trace.json"),
            "w", encoding="utf-8",
        ) as fh:
            json.dump(tree, fh, indent=2, default=str)
        with open(
            os.path.join(out_dir, "chaos-worst-trace.txt"),
            "w", encoding="utf-8",
        ) as fh:
            fh.write(traceview.render_text(tree) + "\n")
    except OSError as err:
        print(f"assembled-trace dump failed: {err!r}", file=sys.stderr)
    else:
        print(
            f"assembled worst trace ({worst_id}, {tree['spans']} spans) "
            "dumped: chaos-worst-trace.txt", file=sys.stderr,
        )


async def test_chaos_storm_forced_expiry_survived_in_process():
    """ISSUE 3 acceptance: force-expire sessions mid-storm; the fleet
    (surviveSessionExpiry + reconcile.repair + the rebirth consumer)
    reconverges to the exact znode contract with ZERO process exits —
    no client ever sees the terminal session_expired, nobody rebuilds a
    client by hand (the reference fleet would have crash-restarted once
    per expiry event).  CHAOS_SEED-reproducible like the main storm.
    """
    seed = int(os.environ.get("CHAOS_SEED", random.randrange(2**32)))
    churn_s = float(os.environ.get("CHAOS_SECONDS", "2.5"))
    print(f"CHAOS_SEED={seed} CHAOS_SECONDS={churn_s} (expiry storm)",
          file=sys.stderr)
    rng = random.Random(seed)

    from registrar_tpu.trace import Tracer

    async with ZKEnsemble(ENSEMBLE, tick_ms=20) as ens:
        # ISSUE 8 rider: a per-worker flight recorder at 100% sampling —
        # dumped on failure, and asserted post-storm to carry the span
        # chain across session loss → rebirth → re-registration.
        workers = [
            _RebornWorker(
                i, ens.addresses,
                tracer=Tracer(sample_rate=1.0, max_spans=4096),
            )
            for i in range(N_WORKERS)
        ]
        for w in workers:
            await w.start()

        # ISSUE 8 acceptance: the introspection surface must ANSWER
        # throughout the storm.  One MetricsServer fronts worker 0 with
        # the daemon's own /status snapshot + its flight recorder; a
        # poller hits both endpoints all storm and every poll must
        # succeed (the endpoints are deliberately storm-proof: a dead
        # ensemble degrades the mzxid read-back to `readError`, never
        # to a hung or erroring endpoint).
        import time as time_mod

        from registrar_tpu.config import parse_config
        from registrar_tpu.main import _status_snapshot
        from registrar_tpu.metrics import (
            MetricsRegistry,
            MetricsServer,
            instrument_tracing,
        )

        w0 = workers[0]
        status_cfg = parse_config({
            "registration": _reg(),
            "zookeeper": {"servers": [
                {"host": ens.addresses[0][0], "port": ens.addresses[0][1]}
            ]},
        })
        status_note = {"zk_state": "connected", "last_reconcile": None,
                       "started": time_mod.time()}
        status_registry = MetricsRegistry()
        instrument_tracing(w0.tracer, status_registry)
        mserver = await MetricsServer(
            status_registry,
            status_provider=lambda: _status_snapshot(
                status_cfg, w0.client, w0.ee, status_note
            ),
            trace_provider=lambda n: w0.tracer.dump(n),
        ).start()
        probe_stats = {"status_ok": 0, "trace_ok": 0, "failures": []}

        async def _probe_get(path: str):
            reader, writer = await asyncio.open_connection(
                mserver.host, mserver.port
            )
            try:
                writer.write(
                    f"GET {path} HTTP/1.0\r\n\r\n".encode()
                )
                await writer.drain()
                raw = await asyncio.wait_for(reader.read(), timeout=10)
            finally:
                writer.close()
            head, _, body = raw.partition(b"\r\n\r\n")
            assert head.split()[1] == b"200", head
            import json as json_mod

            return json_mod.loads(body)

        async def introspection_probe(stop: asyncio.Event) -> None:
            while not stop.is_set():
                try:
                    snapshot = await _probe_get("/status")
                    assert snapshot["session"]["id"]
                    probe_stats["status_ok"] += 1
                    dump = await _probe_get("/debug/trace?n=50")
                    assert dump["enabled"] is True
                    probe_stats["trace_ok"] += 1
                except (
                    AssertionError,
                    OSError,
                    ValueError,
                    # Not redundant on 3.9: asyncio.TimeoutError only
                    # became an OSError alias (TimeoutError) in 3.10 —
                    # a timed-out poll must be a recorded failure, not
                    # a probe-task crash that stops the polling.
                    asyncio.TimeoutError,
                ) as err:
                    probe_stats["failures"].append(repr(err))
                await asyncio.sleep(0.05)
        # Binder's-eye cache rider (ISSUE 4): a watch-coherent resolve
        # cache on its own surviveSessionExpiry client rides the same
        # storm.  During the storm it resolves continuously (exercising
        # invalidation, degraded fallback, and rebirth re-arming under
        # fire); at convergence it must agree EXACTLY with the live
        # fleet — a cache serving one dead record past convergence is a
        # DNS outage.
        from registrar_tpu.zkcache import ZKCache

        cache_client = ZKClient(
            ens.addresses,
            timeout_ms=8000,
            connect_timeout_ms=500,
            request_timeout_ms=1500,
            survive_session_expiry=True,
            max_session_rebirths=10_000,
            reconnect_policy=FAST_RECONNECT,
        )
        await cache_client.connect()
        cache = ZKCache(cache_client)
        cache_resolves = {"ok": 0, "failed": 0}

        async def cache_churn(stop: asyncio.Event) -> None:
            while not stop.is_set():
                try:
                    await binderview.resolve(cache, DOMAIN, "A")
                    cache_resolves["ok"] += 1
                except (ZKError, ConnectionError, OSError):
                    cache_resolves["failed"] += 1  # degraded + wire down
                await asyncio.sleep(0.02)

        try:
            stop = asyncio.Event()
            events: list = []
            restart_tasks: list = []

            async def expiry_storm() -> None:
                while not stop.is_set():
                    await asyncio.sleep(rng.uniform(0.02, 0.08))
                    live = [
                        i for i, m in enumerate(ens.servers)
                        if m is not None and m._server is not None
                    ]
                    dead = [i for i in range(ENSEMBLE) if i not in live]
                    roll = rng.random()
                    if roll < 0.4 and live:
                        # THE event under test: a forced session expiry
                        sids = sorted(
                            s.session_id
                            for s in ens.state.sessions.values()
                            if s.connected
                        )
                        if sids:
                            idx = rng.randrange(len(sids))
                            await ens.servers[live[0]].expire_session(
                                sids[idx]
                            )
                            events.append(("expire", idx))
                    elif roll < 0.55 and len(live) > 1:
                        i = rng.choice(live)
                        await ens.kill(i)
                        events.append(("kill", i))
                    elif roll < 0.7 and dead:
                        i = rng.choice(dead)
                        await ens.restart(i)
                        events.append(("restart", i))
                    elif roll < 0.85:
                        # ISSUE 5 rider: SIGTERM + relaunch a random
                        # fleet member mid-storm, alternating restart
                        # modes — handoffs that get force-expired in
                        # the gap exercise the refused-resume fallback.
                        i = rng.randrange(N_WORKERS)
                        mode = "handoff" if rng.random() < 0.5 else "drain"
                        if not workers[i]._restarting:
                            restart_tasks.append(
                                asyncio.create_task(
                                    workers[i].restart(mode)
                                )
                            )
                            events.append(("agent-restart", i, mode))
                    elif live:
                        i = rng.choice(live)
                        await ens.servers[i].drop_connections()
                        events.append(("drop", i))
                for i in range(ENSEMBLE):
                    await ens.restart(i)

            storm = asyncio.create_task(expiry_storm())
            cache_task = asyncio.create_task(cache_churn(stop))
            probe_task = asyncio.create_task(introspection_probe(stop))
            await asyncio.sleep(churn_s)
            stop.set()
            await storm
            await cache_task
            await probe_task
            # every mid-storm restart must complete (its "supervisor"
            # loop keeps relaunching until the successor registers)
            if restart_tasks:
                await asyncio.gather(*restart_tasks)
            assert any(ev[0] == "expire" for ev in events), events
            assert any(ev[0] == "agent-restart" for ev in events), events
            assert cache_resolves["ok"] > 0, "cache never answered in-storm"

            # ISSUE 8: /status + /debug/trace answered EVERY poll of the
            # storm — not one hang, not one 500, not one refused read.
            assert not probe_stats["failures"], probe_stats["failures"]
            assert probe_stats["status_ok"] > 0, "no /status poll landed"
            assert probe_stats["trace_ok"] > 0, "no /debug/trace poll landed"

            # -- convergence: exact §2.6 contract, in-process ------------
            deadline = asyncio.get_running_loop().time() + 30
            pending = set(range(N_WORKERS))
            while pending:
                assert asyncio.get_running_loop().time() < deadline, (
                    f"workers {sorted(pending)} never reconverged; "
                    f"events={events}"
                )
                for i in sorted(pending):
                    w = workers[i]
                    node = ens.get_node(f"{PATH}/{w.hostname}")
                    if (
                        node is not None
                        and w.client.connected
                        and node.ephemeral_owner == w.client.session_id
                    ):
                        pending.discard(i)
                await asyncio.sleep(0.05)

            # zero process exits: nobody saw the terminal event, every
            # client object survived the whole storm in-process
            for w in workers:
                assert w.terminal_expiries == 0, f"worker {w.i} went terminal"
                assert not w.client.closed
            total_rebirths = sum(w.client.rebirths for w in workers)
            expiries = sum(1 for ev in events if ev[0] == "expire")
            agent_restarts = sum(w.restarts for w in workers)
            resumed = sum(w.resumed_restarts for w in workers)
            print(
                f"expiry storm: {expiries} forced expiries, "
                f"{total_rebirths} rebirths, {agent_restarts} agent "
                f"restarts ({resumed} session handoffs resumed), "
                f"{len(events)} faults",
                file=sys.stderr,
            )

            # the persistent service record survived, persistent
            svc = ens.get_node(PATH)
            assert svc is not None and svc.ephemeral_owner == 0
            assert parse_payload(svc.data)["type"] == "service"

            # no ephemeral anywhere belongs to a dead session
            orphans = _orphan_ephemerals(ens)
            assert not orphans, f"orphan ephemerals: {orphans}"

            # and the Binder view answers with exactly the live fleet
            res = await binderview.resolve(
                workers[0].client, DOMAIN, "A"
            )
            assert sorted(a.data for a in res.answers) == sorted(
                w.admin_ip for w in workers
            )

            # ISSUE 4 acceptance: the CACHED view converges to the same
            # answer with zero stale records — bounded poll, then exact
            # equality (the cache client survived every expiry too).
            expected = sorted(w.admin_ip for w in workers)
            deadline = asyncio.get_running_loop().time() + 30
            last = None  # every resolve may raise: "no answer yet" must
            # still render in the timeout message, not UnboundLocalError
            while True:
                try:
                    cres = await binderview.resolve(cache, DOMAIN, "A")
                    last = sorted(a.data for a in cres.answers)
                    if last == expected:
                        break
                except (ZKError, ConnectionError, OSError) as err:
                    last = repr(err)
                assert asyncio.get_running_loop().time() < deadline, (
                    "cached view never converged after the expiry storm "
                    f"(last={last!r})"
                )
                await asyncio.sleep(0.05)
            assert not cache_client.closed
            # warm + authoritative now: the converged answer holds from
            # memory, and equals the live view read through a worker
            cres2 = await binderview.resolve(cache, DOMAIN, "A")
            assert sorted(a.data for a in cres2.answers) == expected
            assert cache.authoritative

            # ISSUE 8 acceptance: the flight recorder carries the whole
            # recovery arc — session loss event, rebirth event, and the
            # agent.repair span with its register.pipeline child on ONE
            # trace (the dump a failing storm leaves behind shows the
            # same chain; asserted here on a worker that was reborn).
            if total_rebirths > 0:
                reborn_workers = [
                    w for w in workers if w.client.rebirths > 0
                ]
                chained = False
                for w in reborn_workers:
                    entries = w.tracer.dump()["entries"]
                    names = {e["name"] for e in entries}
                    if not {"zk.session_lost", "zk.session_reborn"} <= names:
                        continue
                    repairs = {
                        e["span_id"]: e["trace_id"]
                        for e in entries
                        if e["kind"] == "span" and e["name"] == "agent.repair"
                    }
                    chained = any(
                        e["kind"] == "span"
                        and e["name"] == "register.pipeline"
                        and e.get("parent_id") in repairs
                        and e["trace_id"] == repairs[e["parent_id"]]
                        for e in entries
                    )
                    if chained:
                        break
                assert chained, (
                    "no worker's flight recorder shows the session-loss → "
                    "rebirth → re-registration span chain"
                )
        except BaseException:
            # THE debuggability payoff: a failing storm leaves every
            # worker's flight recorder on disk for the CI artifact.
            _dump_flight_recorders(workers)
            raise
        finally:
            await mserver.stop()
            cache.close()
            if not cache_client.closed:
                await cache_client.close()
            for w in workers:
                await w.stop()


async def test_chaos_repeats_with_fixed_seed():
    """The same seed must drive the same fault schedule (kill/restart/drop
    decisions) — reproducibility is what makes a failing run debuggable.
    Driven by event count, not wall clock, so the schedule is exact."""
    async def fault_schedule(seed: int) -> list:
        rng = random.Random(seed)
        async with ZKEnsemble(ENSEMBLE, tick_ms=20) as ens:
            stop = asyncio.Event()
            events: list = []
            # Unstarted proxies: toxic toggles work without sockets, so
            # the netem arm of the schedule is pinned too.
            proxies = [
                ChaosProxy(addr, seed=rng.randrange(2**32))
                for addr in ens.addresses
            ]
            await _chaos_task(
                ens, rng, stop, events, max_events=12, proxies=proxies
            )
            return events

    a = await fault_schedule(1234)
    b = await fault_schedule(1234)
    assert a == b
    assert len(a) == 12
    assert any(ev[0].startswith("netem-") for ev in a), a


async def _quorum_chaos_task(
    ens: ZKEnsemble, rng: random.Random, stop: asyncio.Event, events: list
) -> None:
    """The ISSUE 10 storm palette: leader kills, member restarts,
    rolling restarts, and partition-to-minority/heal — seeded, always
    restorable (the storm-over pass heals and restarts everything)."""
    while not stop.is_set():
        await asyncio.sleep(rng.uniform(0.05, 0.15))
        live = [
            i
            for i, m in enumerate(ens.servers)
            if m is not None and m._server is not None
        ]
        dead = [i for i in range(ENSEMBLE) if i not in live]
        roll = rng.random()
        if roll < 0.30 and len(live) > 1:
            # Leader-kill biased: the fault class this storm exists for.
            leader = ens.leader_index
            target = (
                leader
                if leader in live and rng.random() < 0.7
                else rng.choice(live)
            )
            await ens.kill(target)
            events.append(("kill", target))
        elif roll < 0.60 and dead:
            i = rng.choice(dead)
            await ens.restart(i)
            events.append(("restart", i))
        elif roll < 0.75 and not dead and ens.state.groups is None:
            iso = rng.randrange(ENSEMBLE)
            ens.partition(
                [[j for j in range(ENSEMBLE) if j != iso], [iso]]
            )
            events.append(("partition", iso))
        elif ens.state.groups is not None:
            ens.heal_partition()
            events.append(("heal", -1))
        elif live:
            # rolling-upgrade step: one member out and straight back
            i = rng.choice(live)
            await ens.kill(i)
            await asyncio.sleep(rng.uniform(0.05, 0.2))
            await ens.restart(i)
            events.append(("roll", i))
    # storm over: full strength, full connectivity
    ens.heal_partition()
    for i in range(ENSEMBLE):
        await ens.restart(i)


async def test_chaos_ensemble_quorum_storm():
    """The CI chaos job's ensemble leg (ISSUE 10): a seeded 3-member
    fleet under leader-kill + rolling-restart + partition storm, with
    read-only-capable workers churning registrations throughout.  The
    fleet must converge with zero orphans and a whole Binder answer —
    writes refused during quorum loss must have been retried, never
    half-applied."""
    seed = int(os.environ.get("CHAOS_SEED", random.randrange(2**32)))
    churn_s = float(os.environ.get("CHAOS_SECONDS", "2.5"))
    print(
        f"CHAOS_SEED={seed} CHAOS_SECONDS={churn_s} (ensemble storm)",
        file=sys.stderr,
    )
    rng = random.Random(seed)

    async with ZKEnsemble(ENSEMBLE, tick_ms=20, election_ms=60) as ens:
        workers = [
            _Worker(
                i, ens, rng.randrange(2**32), can_be_read_only=True
            )
            for i in range(N_WORKERS)
        ]
        for w in workers:
            await w.connect()

        stop = asyncio.Event()
        events: list = []
        tasks = [asyncio.create_task(w.churn(stop)) for w in workers]
        chaos = asyncio.create_task(_quorum_chaos_task(ens, rng, stop, events))

        await asyncio.sleep(churn_s)
        stop.set()
        await asyncio.gather(*tasks)
        await chaos  # heals the partition, restarts every member
        assert events, "storm injected no faults"

        try:
            # The final heal/restart may still be inside its election
            # window: quorum returns within election_ms + one sweep tick.
            deadline = asyncio.get_event_loop().time() + 5
            while not ens.has_quorum:
                assert asyncio.get_event_loop().time() < deadline, (
                    "no leader elected after the storm"
                )
                await asyncio.sleep(0.02)

            await asyncio.gather(*(w.converge() for w in workers))
            # every worker owns its host znode with its live session
            for w in workers:
                st = await w.client.stat(f"{PATH}/{w.hostname}")
                assert st.ephemeral_owner == w.client.session_id
            # write refusals (if the storm produced quorum loss) were
            # absorbed by the churn loop's retry — nothing half-applied:
            # no ephemeral anywhere belongs to a dead session
            await asyncio.sleep(0.3)  # one leader sweep for late expiries
            orphans = _orphan_ephemerals(ens)
            assert not orphans, f"orphan ephemerals: {orphans}"
            # the Binder view answers with exactly the live fleet
            res = await binderview.resolve(workers[0].client, DOMAIN, "A")
            assert sorted(a.data for a in res.answers) == sorted(
                w.admin_ip for w in workers
            )
        finally:
            for w in workers:
                if w.client is not None and not w.client.closed:
                    await w.client.close()
