"""Golden malformed-input regression suite (ISSUE 16 satellite).

Every hostile or corrupt input a peer can feed a decode boundary must
land in that surface's contract class — ``JuteError`` (jute
deserialization), ``ConnectionError`` (stream framing / handshake),
``ShardError`` (shard wire protocol) — and NEVER in ``MemoryError``,
``IndexError``, ``struct.error``, or ``UnicodeDecodeError``.  The inputs
here are the frozen counterexamples (each one a shape the generation-5
taint rules reason about: a length that overruns, a negative count, a
count that would size an allocation); tests/test_fuzz.py generalizes
them property-style when hypothesis is installed.

Each reject is also tallied: ``registrar_tpu.malformed.note()`` feeds
``registrar_malformed_frames_total{surface}`` (docs/OPERATIONS.md), so
the goldens assert the counter moves with the raise.
"""

import asyncio
import struct

import pytest

from registrar_tpu import malformed
from registrar_tpu.shard import (
    _HDR,
    _read_frame,
    resolve_name,
    split_traced,
    ShardError,
    TRACE_FLAG,
)
from registrar_tpu.zk.framing import MAX_FRAME, FrameReader
from registrar_tpu.zk.jute import JuteError, Reader, Writer

#: Exception classes that must NEVER escape a decode boundary — each one
#: is a symptom of trusting a peer-supplied size before validating it.
FORBIDDEN = (MemoryError, IndexError, struct.error, UnicodeDecodeError)


def surface_count(surface):
    return malformed.counts()[surface]


def assert_rejects(surface, contract, fn, *args):
    """``fn(*args)`` must raise exactly the surface's contract class and
    bump the surface's malformed tally by one."""
    before = surface_count(surface)
    try:
        fn(*args)
    except contract:
        pass
    except FORBIDDEN as err:  # pragma: no cover - the regression itself
        pytest.fail(f"non-contract escape: {err!r}")
    else:
        pytest.fail("malformed input was accepted")
    assert surface_count(surface) == before + 1


class _FakeReader:
    """StreamReader stand-in serving scripted read()/readexactly() data."""

    def __init__(self, data: bytes):
        self._data = data

    async def read(self, n):
        out, self._data = self._data[:n], self._data[n:]
        return out

    async def readexactly(self, n):
        if len(self._data) < n:
            raise asyncio.IncompleteReadError(self._data, n)
        out, self._data = self._data[:n], self._data[n:]
        return out


class TestJuteGoldens:
    def test_truncated_take(self):
        assert_rejects("jute", JuteError, Reader(b"\x01\x02")._take, 3)

    def test_truncated_int(self):
        assert_rejects("jute", JuteError, Reader(b"\x00\x00").read_int)

    def test_truncated_struct_run(self):
        st = struct.Struct(">iq")
        assert_rejects("jute", JuteError, Reader(b"\x00" * 8).read_struct, st)

    def test_long_at_negative_offset(self):
        assert_rejects("jute", JuteError, Reader(b"\x00" * 16).long_at, -4)

    def test_long_at_past_end(self):
        assert_rejects("jute", JuteError, Reader(b"\x00" * 8).long_at, 4)

    def test_buffer_negative_length(self):
        # -1 means null; anything below is malformed, not a size.
        body = Writer().write_int(-2).to_bytes()
        assert_rejects("jute", JuteError, Reader(body).read_buffer)

    def test_buffer_length_overruns_data(self):
        # The classic allocation bomb: four bytes claim 2 GiB.  The
        # truncation check must fire before any allocation happens.
        body = Writer().write_int(0x7FFFFFFF).to_bytes()
        assert_rejects("jute", JuteError, Reader(body).read_buffer)

    def test_ustring_invalid_utf8(self):
        body = Writer().write_int(2).to_bytes() + b"\xff\xfe"
        assert_rejects("jute", JuteError, Reader(body).read_ustring)

    def test_vector_negative_count(self):
        body = Writer().write_int(-7).to_bytes()
        assert_rejects(
            "jute", JuteError, Reader(body).read_vector, Reader.read_int
        )

    def test_vector_count_exceeds_remaining(self):
        # A count the buffer cannot possibly hold must reject BEFORE the
        # element list is allocated.
        body = Writer().write_int(1 << 30).to_bytes() + b"\x00" * 8
        assert_rejects(
            "jute", JuteError, Reader(body).read_vector, Reader.read_int
        )

    def test_null_sentinels_still_decode(self):
        # The -1 null sentinel is well-formed: no raise, no tally.
        before = surface_count("jute")
        body = Writer().write_int(-1).to_bytes()
        assert Reader(body).read_buffer() is None
        assert Reader(body).read_ustring() is None
        assert Reader(body).read_vector(Reader.read_int) is None
        assert surface_count("jute") == before


class TestFramingGoldens:
    @staticmethod
    def _carve(prefix: bytes):
        fr = FrameReader(_FakeReader(prefix))

        async def go():
            assert await fr.fill()
            return fr.carve()

        return asyncio.run(go())

    def test_negative_length_prefix(self):
        assert_rejects(
            "zk_framing",
            ConnectionError,
            self._carve,
            (-1).to_bytes(4, "big", signed=True),
        )

    def test_oversized_length_prefix(self):
        assert_rejects(
            "zk_framing",
            ConnectionError,
            self._carve,
            (MAX_FRAME + 1).to_bytes(4, "big"),
        )


class TestShardGoldens:
    def test_resolve_body_too_short(self):
        assert_rejects("shard", ShardError, resolve_name, b"\x00")

    def test_resolve_qtype_overruns_body(self):
        # qlen=200 against a 6-byte body: the slice bound must be
        # checked against the body, never silently slice past it.
        assert_rejects(
            "shard", ShardError, resolve_name, bytes((0, 200)) + b"Axyz"
        )

    def test_resolve_name_not_utf8(self):
        assert_rejects(
            "shard", ShardError, resolve_name, bytes((0, 1)) + b"A\xff\xfe"
        )

    def test_traced_frame_too_short_for_context(self):
        frame = _HDR.pack(7, TRACE_FLAG | 1)  # header only, no ctx block
        assert_rejects(
            "shard", ShardError, split_traced, frame, TRACE_FLAG | 1
        )

    def test_read_frame_rejects_bad_length(self):
        def read(prefix):
            return asyncio.run(_read_frame(_FakeReader(prefix)))

        assert_rejects("shard", ShardError, read, (MAX_FRAME + 1).to_bytes(4, "big"))
        # A length below the fixed header can never be a frame either.
        assert_rejects("shard", ShardError, read, (0).to_bytes(4, "big"))

    def test_read_frame_clean_eof_is_none(self):
        before = surface_count("shard")
        assert asyncio.run(_read_frame(_FakeReader(b""))) is None
        assert surface_count("shard") == before


class TestTally:
    def test_unknown_surface_is_ignored(self):
        # note() sits on error paths that must stay on their contract
        # rails: a vocabulary typo is dropped, never raised.
        before = malformed.counts()
        malformed.note("not-a-surface")
        assert malformed.counts() == before

    def test_subscribe_and_unsubscribe(self):
        seen = []
        unsubscribe = malformed.subscribe(seen.append)
        try:
            malformed.note("jute")
            assert seen == ["jute"]
        finally:
            unsubscribe()
        malformed.note("jute")
        assert seen == ["jute"]

    def test_counter_preseeded_and_wired(self):
        # instrument() pre-seeds a zero series per surface (alert
        # rate()s need the series from the first scrape) and subscribes
        # the live tally.
        from registrar_tpu.metrics import instrument

        class _Emitter:
            down = False
            znodes = ()

            def on(self, *_a, **_k):
                pass

        class _ZK(_Emitter):
            connected = False

        reg = instrument(_Emitter(), _ZK())
        text = reg.render()
        for surface in malformed.SURFACES:
            assert (
                f'registrar_malformed_frames_total{{surface="{surface}"}}'
                in text
            )
        with pytest.raises(JuteError):
            Reader(b"").read_int()
        assert (
            'registrar_malformed_frames_total{surface="jute"} 1'
            in reg.render()
        )
