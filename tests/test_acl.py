"""ACL and authentication support: protocol records, client ops, server
enforcement.

The reference never touches ACLs — zkplus creates every node
world:anyone (SURVEY.md §2.4), and `addauth`/`getAcl`/`setAcl` are beyond
its surface.  The rebuild's transport covers the full ZooKeeper 3.4
client protocol, so these tests pin:

  * jute round-trips for AuthPacket / GetACL / SetACL records,
  * the digest id formula (sha1 + base64, matching ZooKeeper's
    DigestAuthenticationProvider so ACLs interoperate with zkCli.sh),
  * server-side enforcement at the 3.4 checkpoints (create -> CREATE on
    parent, delete -> DELETE on parent, setData -> WRITE, getData /
    getChildren -> READ, setACL -> ADMIN; exists and getACL unchecked),
  * scheme semantics: world / digest / ip (with CIDR) / auth-expansion,
  * aversion versioning of setACL,
  * credential replay after reconnect, and AUTH_FAILED connection drop,
  * ACL checks inside multi transactions (validated before apply),
  * ephemeral cleanup bypassing ACLs on session close (internal delete).
"""

import base64
import hashlib

import pytest

from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import MultiError, Op, ZKClient
from registrar_tpu.zk.jute import Reader, Writer
from registrar_tpu.zk import protocol as proto
from registrar_tpu.zk.protocol import (
    ACL,
    CreateFlag,
    Err,
    OPEN_ACL_UNSAFE,
    Perms,
    Stat,
    ZKError,
    creator_all_acl,
    digest_auth_id,
)


async def _pair(**kw):
    server = await ZKServer().start()
    client = await ZKClient([server.address], **kw).connect()
    return server, client


class TestWire:
    def test_auth_packet_roundtrip(self):
        pkt = proto.AuthPacket(type=0, scheme="digest", auth=b"user:pw")
        w = Writer()
        pkt.write(w)
        assert proto.AuthPacket.read(Reader(w.to_bytes())) == pkt

    def test_get_acl_records_roundtrip(self):
        w = Writer()
        proto.GetACLRequest(path="/a").write(w)
        assert proto.GetACLRequest.read(Reader(w.to_bytes())).path == "/a"

        resp = proto.GetACLResponse(
            acls=[ACL(Perms.READ | Perms.WRITE, "digest", "u:h")],
            stat=Stat(*([0] * 11)),
        )
        w = Writer()
        resp.write(w)
        assert proto.GetACLResponse.read(Reader(w.to_bytes())) == resp

    def test_set_acl_records_roundtrip(self):
        req = proto.SetACLRequest(
            path="/a", acls=list(OPEN_ACL_UNSAFE), version=4
        )
        w = Writer()
        req.write(w)
        assert proto.SetACLRequest.read(Reader(w.to_bytes())) == req

    def test_digest_auth_id_formula(self):
        # Pin the exact DigestAuthenticationProvider.generateDigest formula
        # (user:base64(sha1(user:password))) independently of the helper.
        expected = "alice:" + base64.b64encode(
            hashlib.sha1(b"alice:secret").digest()
        ).decode()
        assert digest_auth_id("alice", "secret") == expected
        assert creator_all_acl("alice", "secret") == [
            ACL(Perms.ALL, "digest", expected)
        ]


class TestDefaultAcls:
    async def test_created_nodes_are_world_anyone(self):
        server, client = await _pair()
        try:
            await client.create("/plain", b"x")
            acls, stat = await client.get_acl("/plain")
            assert acls == list(OPEN_ACL_UNSAFE)
            assert stat.aversion == 0
        finally:
            await client.close()
            await server.stop()

    async def test_get_acl_missing_node(self):
        server, client = await _pair()
        try:
            with pytest.raises(ZKError) as exc:
                await client.get_acl("/nope")
            assert exc.value.code == Err.NO_NODE
        finally:
            await client.close()
            await server.stop()


class TestSetAcl:
    async def test_set_acl_bumps_aversion_only(self):
        server, client = await _pair()
        try:
            await client.create("/n", b"d")
            before = await client.stat("/n")
            stat = await client.set_acl(
                "/n", [ACL(Perms.READ, "world", "anyone")]
            )
            assert stat.aversion == 1
            assert stat.version == before.version  # data version untouched
            assert stat.mzxid == before.mzxid  # not a data change
            acls, _ = await client.get_acl("/n")
            assert acls == [ACL(Perms.READ, "world", "anyone")]
        finally:
            await client.close()
            await server.stop()

    async def test_set_acl_version_check(self):
        server, client = await _pair()
        try:
            await client.create("/n", b"")
            with pytest.raises(ZKError) as exc:
                await client.set_acl("/n", list(OPEN_ACL_UNSAFE), version=5)
            assert exc.value.code == Err.BAD_VERSION
            await client.set_acl("/n", list(OPEN_ACL_UNSAFE), version=0)
            with pytest.raises(ZKError) as exc:
                await client.set_acl("/n", list(OPEN_ACL_UNSAFE), version=0)
            assert exc.value.code == Err.BAD_VERSION  # aversion is now 1
        finally:
            await client.close()
            await server.stop()

    async def test_invalid_acls_rejected(self):
        server, client = await _pair()
        try:
            await client.create("/n", b"")
            for bad in (
                [],  # empty list
                [ACL(0, "world", "anyone")],  # no perms
                [ACL(Perms.ALL, "world", "somebody")],  # bad world id
                [ACL(Perms.ALL, "kerberos", "x")],  # unknown scheme
                [ACL(Perms.ALL, "digest", "nohash")],  # digest id w/o ':'
                [ACL(Perms.ALL, "ip", "not-an-ip")],
            ):
                with pytest.raises(ZKError) as exc:
                    await client.set_acl("/n", bad)
                assert exc.value.code == Err.INVALID_ACL, bad
        finally:
            await client.close()
            await server.stop()


class TestDigestEnforcement:
    ACL_OWNER = creator_all_acl("alice", "secret")

    async def _protected(self):
        """Server + authenticated owner client + a /sec node only alice
        can touch (plus a child for read/delete probes)."""
        server, owner = await _pair()
        await owner.add_auth("digest", b"alice:secret")
        await owner.create("/sec", b"top", acls=self.ACL_OWNER)
        await owner.create("/sec/child", b"c", acls=self.ACL_OWNER)
        return server, owner

    async def test_stranger_denied_owner_allowed(self):
        server, owner = await self._protected()
        stranger = await ZKClient([server.address]).connect()
        try:
            # READ gate: getData and getChildren.
            with pytest.raises(ZKError) as exc:
                await stranger.get("/sec")
            assert exc.value.code == Err.NO_AUTH
            with pytest.raises(ZKError) as exc:
                await stranger.get_children("/sec")
            assert exc.value.code == Err.NO_AUTH
            # WRITE gate.
            with pytest.raises(ZKError) as exc:
                await stranger._call(
                    proto.OpCode.SET_DATA,
                    proto.SetDataRequest(path="/sec", data=b"x"),
                )
            assert exc.value.code == Err.NO_AUTH
            # CREATE gate (on the parent).
            with pytest.raises(ZKError) as exc:
                await stranger.create("/sec/intruder", b"")
            assert exc.value.code == Err.NO_AUTH
            # DELETE gate (on the parent).
            with pytest.raises(ZKError) as exc:
                await stranger.unlink("/sec/child")
            assert exc.value.code == Err.NO_AUTH
            # setACL requires ADMIN.
            with pytest.raises(ZKError) as exc:
                await stranger.set_acl("/sec", list(OPEN_ACL_UNSAFE))
            assert exc.value.code == Err.NO_AUTH
            # exists and getACL are unchecked in 3.4.
            assert (await stranger.stat("/sec")).data_length == 3
            acls, _ = await stranger.get_acl("/sec")
            assert acls == self.ACL_OWNER

            # The owner session passes every gate.
            assert (await owner.get("/sec"))[0] == b"top"
            await owner.create("/sec/more", b"")
            await owner.unlink("/sec/more")

            # The stranger becomes alice: everything opens up.
            await stranger.add_auth("digest", b"alice:secret")
            assert (await stranger.get("/sec"))[0] == b"top"
            await stranger.unlink("/sec/child")
        finally:
            await stranger.close()
            await owner.close()
            await server.stop()

    async def test_wrong_password_is_not_alice(self):
        server, owner = await self._protected()
        stranger = await ZKClient([server.address]).connect()
        try:
            await stranger.add_auth("digest", b"alice:wrong")
            with pytest.raises(ZKError) as exc:
                await stranger.get("/sec")
            assert exc.value.code == Err.NO_AUTH
        finally:
            await stranger.close()
            await owner.close()
            await server.stop()

    async def test_auth_replayed_after_reconnect(self):
        import asyncio

        server, owner = await self._protected()
        try:
            await server.drop_connections()
            # The client reconnects with the same session and must replay
            # its digest credential (server-side auth is per-connection).
            # CONNECTION_LOSS is retried (the drop may not have been
            # observed client-side yet); a NO_AUTH would mean the replay
            # didn't happen and fails the test immediately.
            data = None
            for _ in range(200):
                try:
                    data, _ = await owner.get("/sec")
                    break
                except ZKError as err:
                    if err.code != Err.CONNECTION_LOSS:
                        raise
                    await asyncio.sleep(0.05)
            assert data == b"top"
        finally:
            await owner.close()
            await server.stop()

    async def test_reattach_does_not_inherit_auth(self):
        """A connection that reattaches the session (id + passwd) while the
        old connection is still open must NOT inherit its digest
        identities — auth is per-connection, and the new connection has to
        replay addauth itself."""
        server, owner = await self._protected()
        hijacker = ZKClient([server.address], reconnect=False)
        hijacker.session_id = owner.session_id
        hijacker.session_passwd = owner.session_passwd
        try:
            await hijacker.connect()
            with pytest.raises(ZKError) as exc:
                await hijacker.get("/sec")
            assert exc.value.code == Err.NO_AUTH
        finally:
            await hijacker.close()
            await owner.close()
            await server.stop()

    async def test_ephemeral_cleanup_ignores_acls(self):
        server, owner = await self._protected()
        try:
            await owner.create(
                "/sec/eph", b"", CreateFlag.EPHEMERAL, acls=self.ACL_OWNER
            )
            await owner.close()  # session close: server deletes internally
            assert server.get_node("/sec/eph") is None
        finally:
            await server.stop()


class TestAuthScheme:
    async def test_auth_expands_to_session_identities(self):
        server, client = await _pair()
        try:
            await client.add_auth("digest", b"bob:pw")
            await client.create(
                "/mine", b"", acls=[ACL(Perms.ALL, "auth", "")]
            )
            acls, _ = await client.get_acl("/mine")
            assert acls == [ACL(Perms.ALL, "digest", digest_auth_id("bob", "pw"))]
        finally:
            await client.close()
            await server.stop()

    async def test_auth_scheme_without_credentials_invalid(self):
        server, client = await _pair()
        try:
            with pytest.raises(ZKError) as exc:
                await client.create(
                    "/mine", b"", acls=[ACL(Perms.ALL, "auth", "")]
                )
            assert exc.value.code == Err.INVALID_ACL
        finally:
            await client.close()
            await server.stop()

    async def test_malformed_digest_credential_auth_failed(self):
        # Credentials without a colon, with an empty user, or that are
        # not UTF-8 must answer AUTH_FAILED (real ZK's
        # DigestAuthenticationProvider rejects them the same way).
        for cred in (b"no-colon", b":pw-only", b"\xff\xfe:pw"):
            server, client = await _pair(reconnect=False)
            try:
                with pytest.raises(ZKError) as exc:
                    await client.add_auth("digest", cred)
                assert exc.value.code == Err.AUTH_FAILED, cred
            finally:
                await client.close()
                await server.stop()

    async def test_unknown_scheme_auth_failed_drops_connection(self):
        server, client = await _pair(reconnect=False)
        try:
            with pytest.raises(ZKError) as exc:
                await client.add_auth("kerberos", b"whatever")
            assert exc.value.code == Err.AUTH_FAILED
            # Real ZK drops the connection after answering AUTH_FAILED.
            import asyncio

            for _ in range(100):
                if not client.connected:
                    break
                await asyncio.sleep(0.02)
            assert not client.connected
        finally:
            await client.close()
            await server.stop()

    async def test_ip_scheme_addauth_is_accepted_noop(self):
        server, client = await _pair()
        try:
            await client.add_auth("ip", b"anything")
            await client.create("/ok", b"")  # connection still usable
        finally:
            await client.close()
            await server.stop()

    async def test_rejected_replay_credential_is_dropped(self):
        # Round-1 advisor finding: a credential rejected during replay
        # must be dropped, or every reconnect replays it, gets the
        # connection dropped (real ZK hangs up after AUTH_FAILED), and the
        # client loops connect/reject forever.
        import asyncio

        server, client = await _pair()
        try:
            await client.create("/pre", b"")
            # A credential the server will reject on replay (unknown
            # scheme), planted as though it had been accepted once.
            client._auths.append(("kerberos", b"stale"))
            rejections = []
            client.on("auth_failed", rejections.append)
            reconnected = asyncio.Event()
            client.on("connect", lambda *a: reconnected.set())

            await server.drop_connections()
            await asyncio.wait_for(reconnected.wait(), timeout=10)
            # Replay rejected once, credential dropped; if it were still
            # stored, the AUTH_FAILED hang-up loop would keep the client
            # from ever settling — wait until service is restored.
            deadline = asyncio.get_event_loop().time() + 10
            while True:
                try:
                    await client.get("/pre")
                    break
                except Exception:
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(0.05)
            assert "kerberos" in rejections
            assert ("kerberos", b"stale") not in client._auths
            assert client.connected
        finally:
            await client.close()
            await server.stop()


class TestIpScheme:
    async def test_loopback_matches_exact_and_cidr(self):
        server, client = await _pair()
        try:
            await client.create(
                "/byip", b"d",
                acls=[ACL(Perms.READ | Perms.ADMIN, "ip", "127.0.0.1")],
            )
            assert (await client.get("/byip"))[0] == b"d"  # peer is loopback
            await client.set_acl(
                "/byip", [ACL(Perms.READ | Perms.ADMIN, "ip", "127.0.0.0/8")]
            )
            assert (await client.get("/byip"))[0] == b"d"
            # An ACL for some other network denies us (ADMIN kept on
            # loopback so the node stays repairable).
            await client.set_acl(
                "/byip",
                [
                    ACL(Perms.READ, "ip", "10.9.8.0/24"),
                    ACL(Perms.ADMIN, "ip", "127.0.0.1"),
                ],
            )
            with pytest.raises(ZKError) as exc:
                await client.get("/byip")
            assert exc.value.code == Err.NO_AUTH
        finally:
            await client.close()
            await server.stop()


class TestMultiAcl:
    async def test_multi_respects_acls_and_aborts(self):
        server, owner = await _pair()
        await owner.add_auth("digest", b"alice:secret")
        await owner.create(
            "/sec", b"", acls=creator_all_acl("alice", "secret")
        )
        stranger = await ZKClient([server.address]).connect()
        try:
            await stranger.create("/free", b"")
            with pytest.raises(MultiError) as exc:
                await stranger.multi(
                    [
                        Op.create("/free/a", b""),
                        Op.create("/sec/b", b""),  # NO_AUTH here
                    ]
                )
            assert Err.NO_AUTH in exc.value.results
            # Atomicity: the permitted op must not have been applied.
            assert server.get_node("/free/a") is None
            assert server.get_node("/sec/b") is None

            # The owner's identical transaction goes through.
            await owner.multi(
                [Op.create("/free/a", b""), Op.create("/sec/b", b"")]
            )
            assert server.get_node("/sec/b") is not None
        finally:
            await stranger.close()
            await owner.close()
            await server.stop()


class TestRegistrationUnaffected:
    async def test_pipeline_still_world_anyone(self):
        """The registrar pipeline stays byte-identical: every node it
        creates carries world:anyone (the reference's zkplus behavior)."""
        from registrar_tpu.registration import register

        server, client = await _pair()
        try:
            nodes = await register(
                client,
                {"domain": "acl.test.us", "type": "host"},
                admin_ip="10.0.0.9",
                hostname="box",
                settle_delay=0,
            )
            for path in nodes:
                acls, _ = await client.get_acl(path)
                assert acls == list(OPEN_ACL_UNSAFE), path
        finally:
            await client.close()
            await server.stop()
