"""Kitchen-sink daemon run: every opt-in extension enabled together.

Each extension is tested in isolation elsewhere; this guards their
*interactions* — chroot + metrics + repairHeartbeatMiss + healthCheck +
surviveSessionExpiry + reconcile in one `main.run()` — since option
combinations are where integration bugs hide (e.g. repair re-registering
through the chrooted client, metrics counting a health transition that
raced a repair, a reborn session re-registering under the chroot while
the reconciler sweeps).
"""

import asyncio
import os
import tempfile

from registrar_tpu.config import parse_config
from registrar_tpu.main import run
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient
from tests.test_metrics import _http_get  # shared HTTP/1.0 scrape helper


class TestAllOptionsTogether:
    async def test_chroot_metrics_repair_health_in_one_daemon(self):
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            mport = s.getsockname()[1]

        flag = tempfile.NamedTemporaryFile(delete=False)
        flag.close()

        zk_server = await ZKServer().start()
        observer = await ZKClient([zk_server.address]).connect()
        await observer.mkdirp("/tenant")
        cfg = parse_config(
            {
                "registration": {
                    "domain": "all.opts.us",
                    "type": "load_balancer",
                    "heartbeatInterval": 50,
                },
                "adminIp": "10.7.7.1",
                "zookeeper": {
                    "servers": [
                        {"host": zk_server.host, "port": zk_server.port}
                    ],
                    "timeout": 5000,
                    "chroot": "/tenant",
                },
                "healthCheck": {
                    "command": f"test -f {flag.name}",
                    "interval": 100,
                    "threshold": 2,
                },
                "repairHeartbeatMiss": True,
                "maxAttempts": 1,  # surface NO_NODE without 15 s of retries
                "metrics": {"port": mport},
                "surviveSessionExpiry": True,
                "reconcile": {"intervalSeconds": 0.2, "repair": True},
            }
        )
        task = asyncio.create_task(run(cfg, _exit=lambda code: None))
        node = "/tenant/us/opts/all"
        try:
            loop = asyncio.get_running_loop()

            async def wait_for(pred, timeout=20):
                deadline = loop.time() + timeout
                while not await pred():
                    assert loop.time() < deadline
                    await asyncio.sleep(0.05)

            # 1. Registration lands under the chroot.
            children = []

            async def registered():
                children[:] = (
                    await observer.get_children(node)
                    if await observer.exists(node) else []
                )
                return bool(children)

            await wait_for(registered)
            hostnode = f"{node}/{children[0]}"

            # 2. Metrics are served and see the registration.
            _, _, body = await _http_get("127.0.0.1", mport, "/metrics")
            assert "registrar_registrations_total 1" in body
            assert "registrar_zk_connected 1" in body

            # 3. Heartbeat repair works through the chrooted client: delete
            #    the ephemeral (absolute path) and watch it come back.
            st = await observer.stat(hostnode)
            await observer.unlink(hostnode)

            async def repaired():
                new = await observer.exists(hostnode)
                return new is not None and new.czxid != st.czxid

            await wait_for(repaired)

            # 4. Health down deregisters (and repair must NOT undo it).
            os.unlink(flag.name)

            async def gone():
                return await observer.exists(hostnode) is None

            await wait_for(gone)
            await asyncio.sleep(0.5)  # repair window: stays deregistered
            assert await observer.exists(hostnode) is None
            _, _, body = await _http_get("127.0.0.1", mport, "/metrics")
            assert "registrar_health_down 1" in body
            assert 'registrar_health_transitions_total{to="down"} 1' in body

            # 5. Recovery re-registers under the chroot.
            open(flag.name, "w").close()

            async def back():
                return await observer.exists(hostnode) is not None

            await wait_for(back)
            _, _, body = await _http_get("127.0.0.1", mport, "/metrics")
            assert "registrar_health_down 0" in body

            # 6. A forced session expiry is absorbed IN-PROCESS: the
            #    registration returns under a fresh session through the
            #    chroot, the daemon never exits, metrics count the rebirth.
            st = await observer.stat(hostnode)
            old_owner = st.ephemeral_owner
            await zk_server.expire_session(old_owner)

            async def reborn():
                new = await observer.exists(hostnode)
                return new is not None and new.ephemeral_owner not in (
                    0, old_owner
                )

            await wait_for(reborn)
            assert not task.done(), "daemon exited on a survivable expiry"
            _, _, body = await _http_get("127.0.0.1", mport, "/metrics")
            assert "registrar_session_rebirths_total 1" in body
            assert "registrar_rebirth_breaker_trips_total 0" in body

            # 7. Out-of-band payload drift converges through the chrooted
            #    reconciler sweep, back to the exact contract bytes.
            want, _ = await observer.get(hostnode)
            await zk_server.corrupt_node(hostnode, b'{"evil":1}')

            async def contract_restored():
                from registrar_tpu.zk.protocol import Err, ZKError

                got = await observer.exists(hostnode)
                if got is None:
                    return False
                try:
                    data, _ = await observer.get(hostnode)
                except ZKError as err:
                    if err.code == Err.NO_NODE:
                        # exists->get raced the repair pipeline's
                        # delete+recreate window; poll again
                        return False
                    raise
                return data == want

            await wait_for(contract_restored)
            _, _, body = await _http_get("127.0.0.1", mport, "/metrics")
            repaired = {
                line.rsplit(" ", 1)[0]: float(line.rsplit(" ", 1)[1])
                for line in body.splitlines()
                if line.startswith("registrar_drift_repaired_total{")
            }
            assert repaired['registrar_drift_repaired_total{reason="payload"}'] >= 1
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            await observer.close()
            await zk_server.stop()
            if os.path.exists(flag.name):
                os.unlink(flag.name)
