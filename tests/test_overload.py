"""Overload armor (ISSUE 17): admission control, bounded backlogs,
fast-fail shedding, slow-client disconnects, cold-fill stampede
behavior, the `serve.overload` config block, and the seeded workload
generator the storm benches ride.

The in-process worker tests stall the resolve path behind an event so
the backlog shapes are deterministic facts, not races: the pre-armor
unbounded-growth regression and each bound's shed behavior are asserted
at exact counts.
"""

import asyncio
import struct

import pytest

from registrar_tpu.config import ConfigError, parse_config
from registrar_tpu.registration import register
from registrar_tpu.shard import (
    OP_RESOLVE,
    OP_STATUS,
    STATUS_ERR,
    STATUS_OK,
    Channel,
    ShardClient,
    ShardRouter,
    ShardShedError,
    ShardWorker,
    decode_resolution,
    pack_resolve,
)
from registrar_tpu.testing import workload
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient
from registrar_tpu.zkcache import CacheOverloadError, ZKCache


REG = {
    "domain": "one.overload.joyent.us",
    "type": "load_balancer",
    "service": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
    },
}


def _worker_spec(server, path, **armor):
    spec = {
        "socket": path,
        "shard": 0,
        "shards": 1,
        "servers": [[server.host, server.port]],
        "timeoutMs": 4000,
    }
    spec.update(armor)
    return spec


async def _stalled_worker(server, tmp_path, **armor):
    """A started worker whose resolve path parks on a gate event —
    admission accounting runs (it lives outside ``_resolve``), but no
    admitted resolve completes until the gate opens."""
    worker = ShardWorker(_worker_spec(server, str(tmp_path / "w.sock"), **armor))
    await worker.start()
    gate = asyncio.Event()

    async def stalled_resolve(body):
        await gate.wait()
        return b"{}"

    worker._resolve = stalled_resolve
    return worker, gate


async def _wait_for(predicate, timeout=5.0, message="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while not predicate():
        assert asyncio.get_running_loop().time() < deadline, message
        await asyncio.sleep(0.005)


# ---------------------------------------------------------------------------
# Satellite 1: the unbounded dispatch backlog, before and after bounds
# ---------------------------------------------------------------------------


async def test_unarmored_backlog_grows_without_bound(tmp_path):
    """The pre-armor regression shape: with no `serve.overload` knobs a
    single pipelining connection grows the worker's dispatch backlog
    arbitrarily — every frame is admitted, every admitted frame is a
    live task holding an in-flight slot.  This is the collapse mode the
    armor exists to remove (and the parity contract: config absent =
    exactly this behavior)."""
    server = await ZKServer().start()
    worker = chan = None
    try:
        worker, gate = await _stalled_worker(server, tmp_path)
        chan = await Channel.open(worker.socket_path)
        futs = [
            asyncio.ensure_future(
                chan.request(OP_RESOLVE, pack_resolve(REG["domain"], "A"))
            )
            for _ in range(40)
        ]
        # Unbounded admission: the backlog tracks the offered load 1:1.
        await _wait_for(
            lambda: worker.queue_depth == 40,
            message="backlog never reached the offered 40",
        )
        assert worker.sheds["queue_full"] == 0
        gate.set()
        replies = await asyncio.gather(*futs)
        assert all(status == STATUS_OK for status, _ in replies)
        assert worker.queue_depth == 0
    finally:
        if chan is not None:
            await chan.close()
        if worker is not None:
            await worker.close()
        await server.stop()


async def test_per_connection_inflight_bound_sheds_fast(tmp_path):
    """maxInflightPerConn: excess pipelined resolves on one connection
    are refused inline from the read loop — the shed replies resolve
    while the admitted ones are still stalled (fast-fail, never a
    timeout), the backlog is pinned at the bound, and the in-flight
    accounting unwinds to zero."""
    server = await ZKServer().start()
    worker = chan = None
    try:
        worker, gate = await _stalled_worker(
            server, tmp_path, maxInflightPerConn=4
        )
        chan = await Channel.open(worker.socket_path)
        futs = [
            asyncio.ensure_future(
                chan.request(OP_RESOLVE, pack_resolve(REG["domain"], "A"))
            )
            for _ in range(40)
        ]
        await _wait_for(lambda: worker.sheds["queue_full"] == 36)
        assert worker.queue_depth == 4  # pinned at the bound, not 40
        # The 36 sheds answered ALREADY — the gate is still closed, so
        # anything resolved by now traveled the reject path, not the
        # resolve path.
        done, _pending = await asyncio.wait(futs, timeout=2.0)
        assert len(done) == 36
        for fut in done:
            status, body = fut.result()
            assert status == STATUS_ERR
            assert bytes(body).startswith(b"SHED:queue_full")
        gate.set()
        replies = await asyncio.gather(*futs)
        assert sum(1 for status, _ in replies if status == STATUS_OK) == 4
        assert worker.queue_depth == 0
        assert worker.status()["overload"]["sheds"]["queue_full"] == 36
    finally:
        if chan is not None:
            await chan.close()
        if worker is not None:
            await worker.close()
        await server.stop()


async def test_global_queue_depth_bound_across_connections(tmp_path):
    """maxQueueDepth bounds the whole worker's backlog: two connections
    each below their per-conn allowance still cannot push the dispatch
    backlog past the global bound."""
    server = await ZKServer().start()
    worker = None
    chans = []
    try:
        worker, gate = await _stalled_worker(server, tmp_path, maxQueueDepth=6)
        chans = [
            await Channel.open(worker.socket_path),
            await Channel.open(worker.socket_path),
        ]
        futs = [
            asyncio.ensure_future(
                chan.request(OP_RESOLVE, pack_resolve(REG["domain"], "A"))
            )
            for chan in chans
            for _ in range(10)
        ]
        await _wait_for(lambda: worker.sheds["queue_full"] == 14)
        assert worker.queue_depth == 6
        gate.set()
        replies = await asyncio.gather(*futs)
        assert sum(1 for status, _ in replies if status == STATUS_OK) == 6
        assert worker.queue_depth == 0
    finally:
        for chan in chans:
            await chan.close()
        if worker is not None:
            await worker.close()
        await server.stop()


# ---------------------------------------------------------------------------
# Satellite 2: the control-op priority lane
# ---------------------------------------------------------------------------


async def test_status_priority_lane_answers_while_resolves_shed(tmp_path):
    """OP_STATUS skips admission entirely: with the resolve backlog
    saturated (every new resolve shedding), a status request on the
    SAME stuffed connection answers promptly — supervision and `zkcli
    status` stay alive mid-storm by construction."""
    server = await ZKServer().start()
    worker = chan = None
    try:
        worker, gate = await _stalled_worker(
            server, tmp_path, maxInflightPerConn=2
        )
        chan = await Channel.open(worker.socket_path)
        futs = [
            asyncio.ensure_future(
                chan.request(OP_RESOLVE, pack_resolve(REG["domain"], "A"))
            )
            for _ in range(8)
        ]
        await _wait_for(lambda: worker.sheds["queue_full"] == 6)
        status, body = await asyncio.wait_for(
            chan.request(OP_STATUS, b""), timeout=2.0
        )
        assert status == STATUS_OK
        import json

        st = json.loads(bytes(body).decode())
        assert st["overload"]["queue_depth"] == 2
        assert st["overload"]["max_inflight_per_conn"] == 2
        assert st["overload"]["sheds"]["queue_full"] == 6
        gate.set()
        await asyncio.gather(*futs)
    finally:
        if chan is not None:
            await chan.close()
        if worker is not None:
            await worker.close()
        await server.stop()


# ---------------------------------------------------------------------------
# Satellite 3: hostile clients — slow-loris and half-open
# ---------------------------------------------------------------------------


async def test_write_deadline_disconnects_slow_reader_without_leak(tmp_path):
    """A peer that stops reading is aborted at writeDeadlineS: the shed
    is counted as slow_client, the parked handler's in-flight slot
    unwinds (no leak), and the worker keeps answering everyone else."""
    server = await ZKServer().start()
    worker = chan = None
    reader = writer = None
    try:
        worker = ShardWorker(
            _worker_spec(
                server, str(tmp_path / "w.sock"), writeDeadlineS=0.3
            )
        )
        await worker.start()

        # A reply big enough that drain() must wait on the non-reading
        # peer (unix-socket kernel buffer + the transport's high-water
        # mark are both far below this).
        async def big_resolve(body):
            return b"x" * 600_000

        worker._resolve = big_resolve
        reader, writer = await workload._open_raw(
            worker.socket_path, rcvbuf=4096
        )
        from registrar_tpu.shard import pack_request

        writer.write(pack_request(7, OP_RESOLVE, pack_resolve(REG["domain"])))
        await writer.drain()
        # ...and never read.  The armor must fire and unwind the slot.
        await _wait_for(
            lambda: worker.sheds["slow_client"] >= 1,
            message="write deadline never fired",
        )
        await _wait_for(
            lambda: worker.queue_depth == 0,
            message="in-flight slot leaked past the abort",
        )
        # Our side observes the disconnect (EOF or reset).
        try:
            data = await asyncio.wait_for(reader.read(), timeout=5.0)
        except (ConnectionError, OSError):
            data = b""
        assert isinstance(data, bytes)
        # The worker is not wedged: a well-behaved client still resolves.
        del worker._resolve  # restore the class's resolve path
        chan = await Channel.open(worker.socket_path)
        status, body = await chan.request(
            OP_RESOLVE, pack_resolve("absent.overload.joyent.us", "A")
        )
        assert status == STATUS_OK
        assert decode_resolution(body).answers == []
    finally:
        if writer is not None:
            writer.close()
        if chan is not None:
            await chan.close()
        if worker is not None:
            await worker.close()
        await server.stop()


async def test_half_open_client_holds_no_slot_and_wedges_nothing(tmp_path):
    """workload.half_open promises a frame that never arrives: the read
    loop waits it out without admitting anything, the eventual close is
    a clean EOF, and concurrent well-behaved traffic never notices."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    worker = chan = None
    try:
        await register(
            client, REG, admin_ip="10.6.0.1", hostname="h1", settle_delay=0
        )
        worker = ShardWorker(
            _worker_spec(
                server, str(tmp_path / "w.sock"),
                maxInflightPerConn=4, maxQueueDepth=8,
            )
        )
        await worker.start()
        chan = await Channel.open(worker.socket_path)
        half = asyncio.ensure_future(
            workload.half_open(worker.socket_path, hold_s=0.3)
        )
        for _ in range(5):
            status, body = await chan.request(
                OP_RESOLVE, pack_resolve(REG["domain"], "A")
            )
            assert status == STATUS_OK
        await half
        assert worker.queue_depth == 0
        assert all(n == 0 for n in worker.sheds.values())
    finally:
        if chan is not None:
            await chan.close()
        if worker is not None:
            await worker.close()
        await client.close()
        await server.stop()


# ---------------------------------------------------------------------------
# The router's per-client token bucket
# ---------------------------------------------------------------------------


async def test_router_rate_limit_sheds_rate_limited(tmp_path):
    """clientRateLimit at the router front socket: a client bursting
    past its bucket gets ShardShedError("rate_limited") — classified
    client-side from the SHED: body — and the router's shed rollup
    counts it; a sibling connection's bucket is untouched."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    router = sc = sc2 = None
    try:
        await register(
            client, REG, admin_ip="10.6.0.1", hostname="h1", settle_delay=0
        )
        router = await ShardRouter(
            [server.address], 1, str(tmp_path / "rate.sock"),
            attach_spread="any", overload={"clientRateLimit": 3.0},
        ).start()
        sc = await ShardClient(router.socket_path).connect()
        outcomes = []
        for _ in range(8):
            try:
                res = await sc.resolve(REG["domain"], "A")
                outcomes.append(("ok", res))
            except ShardShedError as err:
                outcomes.append(("shed", err.reason))
        oks = [o for o in outcomes if o[0] == "ok"]
        sheds = [o for o in outcomes if o[0] == "shed"]
        assert len(oks) == 3  # burst == rate
        assert len(sheds) == 5
        assert all(reason == "rate_limited" for _tag, reason in sheds)
        assert router.sheds_total()["rate_limited"] >= 5
        # A fresh connection has its own bucket.
        sc2 = await ShardClient(router.socket_path).connect()
        res = await sc2.resolve(REG["domain"], "A")
        assert res.answers
        # ...and a drained bucket refills with time.
        await asyncio.sleep(0.5)
        res = await sc.resolve(REG["domain"], "A")
        assert res.answers
    finally:
        for c in (sc, sc2):
            if c is not None:
                await c.close()
        if router is not None:
            await router.stop()
        await client.close()
        await server.stop()


# ---------------------------------------------------------------------------
# Cold-fill stampedes: single-flight, bounded leaders, stale-over-collapse
# ---------------------------------------------------------------------------


async def test_cache_cold_fill_bound_sheds_new_leaders_only(tmp_path):
    """ZKCache.fill_concurrency bounds NEW fill leaders; a request for a
    path already being filled joins the in-flight future for free (the
    single-flight guarantee is exactly why the bound is safe)."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    cache = None
    try:
        cache = ZKCache(client, fill_concurrency=1)
        # Occupy the one fill slot with a pending in-flight future.
        fut = asyncio.get_running_loop().create_future()
        cache._inflight["/held"] = fut
        # A distinct-path cold read would be a SECOND leader: shed.
        with pytest.raises(CacheOverloadError):
            await cache.read_node("/other")
        assert cache.stats["fill_sheds"] == 1
        # A same-path read JOINS the in-flight fill — no shed.
        joiner = asyncio.ensure_future(cache._fill_node("/held"))
        await asyncio.sleep(0.01)
        assert not joiner.done()
        fut.set_result(None)
        assert await joiner is None
        assert cache.stats["fill_sheds"] == 1
    finally:
        if cache is not None:
            cache.close()
        await client.close()
        await server.stop()


async def test_worker_serves_stale_over_cold_fill_collapse(tmp_path):
    """A warm domain whose cache entry was churned out answers its
    bounded-age last-known-good bytes when the fill path sheds; a
    genuinely cold domain fails fast with SHED:cold_fill_shed."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    worker = chan = None
    try:
        await register(
            client, REG, admin_ip="10.6.0.1", hostname="h1", settle_delay=0
        )
        worker = ShardWorker(_worker_spec(server, str(tmp_path / "w.sock")))
        await worker.start()
        chan = await Channel.open(worker.socket_path)
        status, body = await chan.request(
            OP_RESOLVE, pack_resolve(REG["domain"], "A")
        )
        assert status == STATUS_OK
        warm_answer = bytes(body)

        # Swap in a cold cache that sheds EVERY new fill leader: the
        # stampede shape without the stampede.
        old = worker.cache
        worker.cache = ZKCache(client, fill_concurrency=0)
        old.close()

        status, body = await chan.request(
            OP_RESOLVE, pack_resolve(REG["domain"], "A")
        )
        assert status == STATUS_OK
        assert bytes(body) == warm_answer  # stale-over-collapse
        assert worker.stale_serves == 1
        assert worker.sheds["cold_fill_shed"] == 1

        status, body = await chan.request(
            OP_RESOLVE, pack_resolve("never.overload.joyent.us", "A")
        )
        assert status == STATUS_ERR
        assert bytes(body).startswith(b"SHED:cold_fill_shed")
        assert worker.sheds["cold_fill_shed"] == 2
    finally:
        if chan is not None:
            await chan.close()
        if worker is not None:
            await worker.close()
        await client.close()
        await server.stop()


# ---------------------------------------------------------------------------
# Satellite 5 (docs/CONFIG.md contract): the serve.overload block
# ---------------------------------------------------------------------------


def _serve_cfg(overload=None):
    cfg = {
        "registration": {"domain": "d.example.us", "type": "host"},
        "zookeeper": {"servers": [{"host": "h", "port": 1}]},
        "serve": {"shards": 2, "socketPath": "/tmp/s.sock"},
    }
    if overload is not None:
        cfg["serve"]["overload"] = overload
    return cfg


class TestOverloadConfig:
    def test_absent_block_is_none(self):
        assert parse_config(_serve_cfg()).serve.overload is None

    def test_full_block_round_trips_to_router_kwargs(self):
        cfg = parse_config(
            _serve_cfg(
                {
                    "maxQueueDepth": 96,
                    "maxInflightPerConn": 6,
                    "clientRateLimit": 1000,
                    "coldFillConcurrency": 4,
                    "writeDeadlineS": 0.4,
                }
            )
        )
        ov = cfg.serve.overload
        assert ov.max_queue_depth == 96
        assert ov.max_inflight_per_conn == 6
        assert ov.client_rate_limit == 1000.0
        assert ov.cold_fill_concurrency == 4
        assert ov.write_deadline_s == 0.4
        assert ov.as_router_kwargs() == {
            "maxQueueDepth": 96,
            "maxInflightPerConn": 6,
            "clientRateLimit": 1000.0,
            "coldFillConcurrency": 4,
            "writeDeadlineS": 0.4,
        }

    def test_partial_block_drops_absent_knobs(self):
        cfg = parse_config(_serve_cfg({"maxQueueDepth": 10}))
        assert cfg.serve.overload.as_router_kwargs() == {"maxQueueDepth": 10}

    @pytest.mark.parametrize(
        "block",
        [
            {"maxQueueDepth": 0},
            {"maxQueueDepth": -1},
            {"maxQueueDepth": "many"},
            {"maxInflightPerConn": 1.5},
            {"clientRateLimit": 0},
            {"clientRateLimit": "fast"},
            {"coldFillConcurrency": -2},
            {"writeDeadlineS": 0},
            "not-an-object",
        ],
    )
    def test_invalid_values_are_config_errors(self, block):
        with pytest.raises(ConfigError):
            parse_config(_serve_cfg(block))


# ---------------------------------------------------------------------------
# The workload generator itself
# ---------------------------------------------------------------------------


class TestWorkload:
    def test_zipf_weights_are_heavy_tailed(self):
        w = workload.zipf_weights(16)
        assert len(w) == 16
        assert w[0] > w[1] > w[-1] > 0  # strictly rank-decreasing
        # heavier s = heavier head relative to the tail
        heavy = workload.zipf_weights(16, s=2.0)
        assert heavy[0] / heavy[-1] > w[0] / w[-1]

    def test_zipf_picker_is_seed_deterministic(self):
        import random

        names = [f"n{i}.x.us" for i in range(12)]
        picker = workload._ZipfPicker(names)
        rng_a, rng_b = random.Random(7), random.Random(7)
        draws_a = [picker.pick(rng_a) for _ in range(20)]
        draws_b = [picker.pick(rng_b) for _ in range(20)]
        assert draws_a == draws_b
        assert set(draws_a) <= set(names)

    def test_malformed_frames_keep_valid_length_prefixes(self):
        import random

        frames = workload.malformed_resolve_frames(random.Random(3), 32)
        assert len(frames) == 32
        for frame in frames:
            (size,) = struct.unpack(">I", frame[:4])
            assert size == len(frame) - 4  # poisons the request, not
            assert size >= 5  # the connection

    def test_storm_report_summary_shape(self):
        report = workload.StormReport(seed=42)
        report.sent["warm"] = 10
        report.ok["warm"] = 8
        report.record_shed("queue_full", 0.001)
        report.admitted_warm_s.extend([0.002, 0.003])
        report.duration_s = 1.0
        summary = report.summary()
        assert summary["seed"] == 42
        assert summary["sheds"]["queue_full"] == 1
        assert summary["sheds_total"] == 1
        assert summary["timeouts_total"] == 0
        assert summary["admitted_warm_p99_ms"] is not None
        assert summary["shed_fastfail_p99_ms"] is not None


async def test_storm_against_armored_tier_sheds_and_never_times_out(tmp_path):
    """A small seeded storm end-to-end against a deliberately tight
    armored tier: overload is guaranteed (pipeline 8 against an
    in-flight bound of 1), every excess request sheds fast, and no
    admitted request times out — the ISSUE's core acceptance shape at
    unit-test scale."""
    server = await ZKServer().start()
    client = await ZKClient([server.address]).connect()
    router = None
    try:
        domains = []
        for i in range(4):
            reg = {
                "domain": f"svc{i}.storm.overload.joyent.us",
                "type": "load_balancer",
                "service": {
                    "type": "service",
                    "service": {
                        "srvce": "_http", "proto": "_tcp", "port": 80,
                    },
                },
            }
            await register(
                client, reg, admin_ip=f"10.7.0.{i}", hostname="h0",
                settle_delay=0,
            )
            domains.append(reg["domain"])
        router = await ShardRouter(
            [server.address], 2, str(tmp_path / "storm.sock"),
            attach_spread="any",
            overload={
                "maxQueueDepth": 8,
                "maxInflightPerConn": 1,
                "coldFillConcurrency": 2,
                "writeDeadlineS": 0.5,
            },
        ).start()
        async with ShardClient(router.socket_path) as sc:
            for dom in domains:
                res = await sc.resolve(dom, "A")
                assert res.answers

        storm = workload.StormWorkload(
            router.socket_path, domains, seed=99,
            duration_s=0.6, clients=3, pipeline=8,
            loris_conns=1, loris_frames=200,
            half_open_conns=1, malformed_frames=8,
        )
        report = await storm.run()
        assert report.sent_total > 0
        assert report.ok["warm"] + report.ok["flash"] > 0
        assert report.sheds_total > 0  # pipeline 8 vs in-flight bound 1
        assert set(report.sheds) <= {
            "queue_full", "rate_limited", "cold_fill_shed", "slow_client"
        }
        assert report.timeouts_total == 0  # sheds never look like timeouts
        assert report.half_open["held"] >= 1
        summary = report.summary()
        assert summary["seed"] == 99
        assert summary["shed_fastfail_p99_ms"] is not None
    finally:
        if router is not None:
            await router.stop()
        await client.close()
        await server.stop()
