"""Network-fault armor: every netem toxic against the hardened client.

The contract under test (ISSUE 2 acceptance): for each
:mod:`registrar_tpu.testing.netem` toxic, the client either *recovers*
(reconnects, re-registers, session/ephemerals intact, Binder view
converges) or *fails fast with the documented error class*
(``OperationTimeoutError`` / CONNECTION_LOSS — both classified transient
by :func:`registrar_tpu.retry.is_transient`); and the two wedge scenarios
the watchdog exists for — a blackholed-but-connected server, and a peer
that stops **reading** (the pre-fix ``_ping_loop`` drain wedge) — are
detected within the dead-after budget.  Fault → detection → recovery →
bound is catalogued in docs/FAULTS.md.
"""

import asyncio
import time

import pytest

from registrar_tpu import binderview
from registrar_tpu.registration import REGISTER_RETRY, register
from registrar_tpu.retry import RetryPolicy, is_transient
from registrar_tpu.testing.netem import (
    DOWN,
    UP,
    Bandwidth,
    Blackhole,
    ChaosProxy,
    Latency,
    ResetAfter,
    Slicer,
    StopReading,
    Truncate,
)
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import (
    OperationTimeoutError,
    ZKClient,
)
from registrar_tpu.zk.protocol import CreateFlag, Err, ZKError

#: sub-second reconnects so recovery happens inside test budgets
FAST = RetryPolicy(max_attempts=float("inf"), initial_delay=0.02, max_delay=0.2)

DOMAIN = "netem.test.registrar"
PATH = "/registrar/test/netem"
REG = {
    "domain": DOMAIN,
    "type": "load_balancer",
    "service": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
    },
}


async def _proxied_pair(seed=7, sock_buf=None, **client_kw):
    # Cleanup-on-failure: once this returns, the CALLER owns all three
    # handles — but a proxy/connect failure mid-build must not leak the
    # pieces already started (the lifecycle smell ISSUE 15 is about).
    server = await ZKServer().start()
    proxy = None
    try:
        proxy = await ChaosProxy(
            server.address, seed=seed, sock_buf=sock_buf
        ).start()
        client_kw.setdefault("reconnect_policy", FAST)
        client_kw.setdefault("connect_timeout_ms", 500)
        client = await ZKClient([proxy.address], **client_kw).connect()
    except BaseException:
        if proxy is not None:
            await proxy.stop()
        await server.stop()
        raise
    return server, proxy, client


async def _shutdown(server, proxy, *clients):
    for c in clients:
        if not c.closed:
            await c.close()
    await proxy.stop()
    await server.stop()


def _orphan_ephemerals(server: ZKServer):
    """Every ephemeral in the tree whose owner session is gone."""
    orphans = []

    def walk(node, prefix):
        for name, child in node.children.items():
            path = f"{prefix}/{name}" if prefix != "/" else f"/{name}"
            if child.ephemeral_owner:
                sess = server.sessions.get(child.ephemeral_owner)
                if sess is None or sess.closed:
                    orphans.append((path, child.ephemeral_owner))
            walk(child, path)

    walk(server.root, "/")
    return orphans


class TestPassthrough:
    async def test_clean_proxy_is_transparent(self):
        server, proxy, client = await _proxied_pair()
        try:
            await client.create("/t", b"hello")
            data, stat = await client.get("/t")
            assert data == b"hello"
            kids = await client.get_children("/")
            assert "t" in kids
        finally:
            await _shutdown(server, proxy, client)

    async def test_full_registration_through_proxy(self):
        server, proxy, client = await _proxied_pair()
        try:
            nodes = await register(
                client, REG, admin_ip="10.1.1.1",
                hostname="netemhost", settle_delay=0.01,
            )
            assert nodes == [f"{PATH}/netemhost", PATH]
            res = await binderview.resolve(client, DOMAIN, "A")
            assert [a.data for a in res.answers] == ["10.1.1.1"]
        finally:
            await _shutdown(server, proxy, client)


class TestLatency:
    async def test_ops_survive_latency_and_jitter(self):
        server, proxy, client = await _proxied_pair()
        try:
            proxy.add(Latency(latency_ms=40, jitter_ms=20), direction=DOWN)
            t0 = time.monotonic()
            await client.create("/slow", b"x")
            elapsed = time.monotonic() - t0
            # the reply crossed a >= (40-20) ms injected delay
            assert elapsed >= 0.02, elapsed
            data, _ = await client.get("/slow")
            assert data == b"x"
        finally:
            await _shutdown(server, proxy, client)


class TestBandwidth:
    async def test_throttle_paces_large_replies(self):
        server, proxy, client = await _proxied_pair()
        try:
            payload = bytes(16 * 1024)
            await client.create("/big", payload)
            proxy.add(Bandwidth(bytes_per_s=64 * 1024), direction=DOWN)
            t0 = time.monotonic()
            data, _ = await client.get("/big")
            elapsed = time.monotonic() - t0
            assert data == payload
            # 16 KiB at 64 KiB/s >= 0.25 s of injected pacing
            assert elapsed >= 0.2, elapsed
        finally:
            await _shutdown(server, proxy, client)


class TestSlicer:
    async def test_torn_frames_reassemble(self):
        # Fragmenting every reply into 1-8 byte segments attacks the
        # client's frame buffering (framing.FrameReader): payloads must
        # reassemble byte-identical, headers must never desynchronize.
        server, proxy, client = await _proxied_pair(seed=11)
        try:
            payload = bytes(range(256)) * 16  # 4 KiB, position-sensitive
            await client.create("/sliced", payload)
            proxy.add(Slicer(max_size=8), direction=DOWN)
            data, _ = await client.get("/sliced")
            assert data == payload
            # several ops in a row: xid pairing survives the shredding
            for _ in range(3):
                st = await client.stat("/sliced")
                assert st.data_length == len(payload)
        finally:
            await _shutdown(server, proxy, client)


class TestOperationDeadline:
    async def test_stalled_reply_times_out_and_recovers(self):
        # A server that reads but never answers (ZKServer.freeze) is
        # indistinguishable from a reply stall: the per-op deadline must
        # fire, tear the connection down, and the reconnect must recover
        # the session.
        server = await ZKServer().start()
        client = None
        try:
            client = await ZKClient(
                [server.address], request_timeout_ms=300,
                reconnect_policy=FAST,
            ).connect()
            await client.create("/dl", b"", CreateFlag.EPHEMERAL)
            server.freeze = True
            t0 = time.monotonic()
            with pytest.raises(OperationTimeoutError) as exc:
                await client.get("/dl")
            assert time.monotonic() - t0 < 2.0
            assert exc.value.code == Err.OPERATION_TIMEOUT
            assert is_transient(exc.value)  # the retry layers will retry it
            server.freeze = False
            await client.wait_for("connect", timeout=10)
            # session reattached: the ephemeral survived the stall
            st = await client.stat("/dl")
            assert st.ephemeral_owner == client.session_id
        finally:
            if client is not None:
                await client.close()
            await server.stop()

    async def test_pipelined_ops_share_the_deadline(self):
        # get_many/heartbeat ride one corked burst; the deadline must
        # bound the gathered replies, not just single _call ops.
        server = await ZKServer().start()
        client = None
        try:
            client = await ZKClient(
                [server.address], request_timeout_ms=300,
                reconnect_policy=FAST,
            ).connect()
            await client.create("/p1", b"a")
            await client.create("/p2", b"b")
            server.freeze = True
            with pytest.raises(OperationTimeoutError):
                await client.get_many(["/p1", "/p2"])
            server.freeze = False
            await client.wait_for("connect", timeout=10)
            server.freeze = True
            with pytest.raises(OperationTimeoutError):
                await client.heartbeat(
                    ["/p1", "/p2"], retry=RetryPolicy(max_attempts=1)
                )
        finally:
            server.freeze = False
            if client is not None:
                await client.close()
            await server.stop()


class TestTruncate:
    async def test_half_open_reply_fails_fast_then_recovers(self):
        # Truncate-then-stall on DOWN: the reply's first bytes arrive,
        # then the wire goes silent with no FIN — half-open TCP.  The
        # per-op deadline is the documented detection path.
        server, proxy, client = await _proxied_pair(request_timeout_ms=400)
        try:
            payload = bytes(4096)
            await client.create("/half", payload, CreateFlag.EPHEMERAL)
            toxic = proxy.add(Truncate(n=10), direction=DOWN)
            with pytest.raises(OperationTimeoutError):
                await client.get("/half")
            proxy.remove(toxic)  # heal the wire; reconnect must recover
            await client.wait_for("connect", timeout=10)
            data, st = await client.get("/half")
            assert data == payload
            assert st.ephemeral_owner == client.session_id
            assert _orphan_ephemerals(server) == []
        finally:
            await _shutdown(server, proxy, client)


class TestBlackhole:
    async def test_watchdog_detects_silent_server(self):
        # The 2/3-session-timeout liveness watchdog, deterministically:
        # TCP stays up, nothing ever answers.  Detection bound: dead_after
        # (= 2/3 * negotiated timeout) + one ping interval.
        server, proxy, client = await _proxied_pair(timeout_ms=1500)
        try:
            await client.create("/bh", b"", CreateFlag.EPHEMERAL)
            assert client.negotiated_timeout_ms == 1500
            proxy.add(Blackhole(), direction=UP)
            proxy.add(Blackhole(), direction=DOWN)
            t0 = time.monotonic()
            await client.wait_for("close", timeout=10)
            detected = time.monotonic() - t0
            # dead_after = 1.0 s, interval = 0.5 s; generous CI margin
            assert detected < 4.0, detected
            proxy.clear()
            await client.wait_for("connect", timeout=10)
            # the same session reattached before it could expire
            st = await client.stat("/bh")
            assert st.ephemeral_owner == client.session_id
            assert _orphan_ephemerals(server) == []
        finally:
            await _shutdown(server, proxy, client)

    async def test_connect_pass_is_bounded_by_total_budget(self):
        # A server list full of blackholed entries must not stall one
        # connect() pass beyond connect_pass_timeout_ms — even when each
        # entry's own connect_timeout_ms would allow far more.
        server = await ZKServer().start()
        proxies = []
        try:
            for i in range(3):
                p = await ChaosProxy(server.address, seed=i).start()
                proxies.append(p)  # before add(): a later failure still
                # finds this proxy in the teardown list
                p.add(Blackhole(), direction=UP)
                p.add(Blackhole(), direction=DOWN)
            client = ZKClient(
                [p.address for p in proxies],
                connect_timeout_ms=10_000,       # per-candidate: generous
                connect_pass_timeout_ms=600,     # whole pass: tight
                reconnect=False,
            )
            t0 = time.monotonic()
            with pytest.raises(Exception):
                await client.connect()
            elapsed = time.monotonic() - t0
            # Bound: ~one candidate's handshake at the pass budget, not
            # 3 x 10 s of per-candidate allowance.
            assert elapsed < 3.0, elapsed
        finally:
            for p in proxies:
                await p.stop()
            await server.stop()


class TestResetAfter:
    async def test_reset_surfaces_connection_loss_and_session_survives(self):
        server, proxy, client = await _proxied_pair()
        try:
            await client.create("/rst", b"", CreateFlag.EPHEMERAL)
            toxic = proxy.add(ResetAfter(n=0), direction=UP)
            with pytest.raises((ZKError, ConnectionError, OSError)) as exc:
                # the RST can land on this op or already be latent; either
                # way the op fails with a transient, retryable error
                await client.stat("/rst")
                await client.stat("/rst")
            if isinstance(exc.value, ZKError):
                assert exc.value.code == Err.CONNECTION_LOSS
            assert is_transient(exc.value)
            proxy.remove(toxic)
            await client.wait_for("connect", timeout=10)
            st = await client.stat("/rst")
            assert st.ephemeral_owner == client.session_id
            assert _orphan_ephemerals(server) == []
        finally:
            await _shutdown(server, proxy, client)


class TestStopReadingDrainWedge:
    async def test_watchdog_survives_peer_that_stops_reading(self):
        # REGRESSION (the _ping_loop drain wedge): a peer that accepts
        # the TCP connection but stops READING fills the kernel send
        # buffer; the client's transport rises past its high-water mark
        # and an unbounded `await drain()` parks the watchdog forever —
        # the exact stall it exists to detect.  Pre-fix, no `close` ever
        # fires and this test fails; post-fix the bounded drain times out
        # against the dead-after budget and tears the connection down.
        # Context-managed teardown: pre-ISSUE-15 the three acquires sat
        # BEFORE the try, so a failed connect leaked the server and the
        # proxy — exactly the straggler shape the lifecycle rule exists
        # to flag.
        async with ZKServer() as server, ChaosProxy(
            server.address, seed=3, sock_buf=8192
        ) as proxy:
            client = await ZKClient(
                [proxy.address],
                timeout_ms=1200,       # interval 0.4 s, dead_after 0.8 s
                reconnect=False,       # keep the post-mortem simple
            ).connect()
            try:
                await client.create("/wedge", b"seed")
                # Shrink the client-side buffers so the wedge needs KBs,
                # not MBs: a small kernel send buffer plus a low
                # transport high-water mark make drain() block almost
                # immediately once the proxy stops draining its end.
                import socket as _socket

                sock = client._writer.get_extra_info("socket")
                sock.setsockopt(
                    _socket.SOL_SOCKET, _socket.SO_SNDBUF, 8192
                )
                client._writer.transport.set_write_buffer_limits(
                    high=16384
                )

                proxy.add(StopReading(), direction=UP)
                # Fill the pipe: a write far larger than every buffer in
                # the path wedges this task in _submit's drain — and,
                # pre-fix, the next ping's drain right behind it.
                blocked = asyncio.ensure_future(
                    client.set_data("/wedge", bytes(512 * 1024))
                )
                t0 = time.monotonic()
                await client.wait_for("close", timeout=8)
                detected = time.monotonic() - t0
                assert detected < 6.0, detected
                with pytest.raises((ZKError, ConnectionError, OSError)):
                    await blocked
            finally:
                await client.close()


class TestRebirthUnderWireFaults:
    async def test_session_rebirth_converges_through_a_faulty_wire(self):
        # ISSUE 3 x ISSUE 2: the in-process session supervisor must ride
        # the same reconnect armor as everything else.  The session is
        # force-expired while the wire tears down every connection after
        # a few frames (ResetAfter) — the rebirth's fresh-session
        # handshake retries through the resets, and once the wire heals
        # the full agent stack (rebirth consumer + repairing reconciler)
        # reconverges to an owned registration with zero terminal
        # expiries.
        from registrar_tpu.agent import register_plus

        server, proxy, client = await _proxied_pair(
            survive_session_expiry=True,
            max_session_rebirths=1000,
            request_timeout_ms=1500,
        )
        try:
            ee = register_plus(
                client, REG, admin_ip="10.1.1.9", hostname="rebornnet",
                settle_delay=0.01, heartbeat_interval=60,
                register_retry=RetryPolicy(
                    max_attempts=5, initial_delay=0.02, max_delay=0.2,
                    jitter="decorrelated",
                ),
                reconcile={"interval_seconds": 0.1, "repair": True},
            )
            await ee.wait_for("register", timeout=10)
            terminal = []
            client.on("session_expired", lambda *a: terminal.append(1))

            # every (re)connect attempt forwards ~one handshake frame's
            # worth of bytes upstream, then the wire resets it
            proxy.add(ResetAfter(n=64), direction=UP)
            await server.expire_session(client.session_id)
            # let the expiry + a few reset-mangled reconnect attempts play
            # out on the faulted wire, then heal it
            await asyncio.sleep(0.6)
            proxy.clear()

            deadline = asyncio.get_running_loop().time() + 20
            node = f"{PATH}/rebornnet"
            while True:
                assert asyncio.get_running_loop().time() < deadline
                n = server.get_node(node)
                if (
                    n is not None
                    and client.connected
                    and n.ephemeral_owner == client.session_id
                ):
                    break
                await asyncio.sleep(0.05)
            assert terminal == []  # never went terminal: zero process exits
            assert client.rebirths >= 1
            assert not _orphan_ephemerals(server)
            ee.stop()
        finally:
            await _shutdown(server, proxy, client)


class TestRegistrationRetryLayer:
    async def test_transient_fault_mid_pipeline_retries_to_convergence(self):
        # End-to-end acceptance: a blackholed wire mid-registration makes
        # the pipeline fail with the transient OperationTimeoutError; the
        # classification+retry layer re-runs the idempotent pipeline, and
        # once the wire heals the host converges — registered, ephemeral
        # owned by the live session, Binder answering, no orphans.
        server, proxy, client = await _proxied_pair(
            request_timeout_ms=300, timeout_ms=8000
        )
        try:
            nodes = await register(
                client, REG, admin_ip="10.2.2.2",
                hostname="retryhost", settle_delay=0.01,
            )
            assert nodes == [f"{PATH}/retryhost", PATH]

            proxy.add(Blackhole(), direction=UP)
            proxy.add(Blackhole(), direction=DOWN)
            task = asyncio.ensure_future(
                register(
                    client, REG, admin_ip="10.2.2.2",
                    hostname="retryhost", settle_delay=0.01,
                    retry_policy=RetryPolicy(
                        max_attempts=50, initial_delay=0.1,
                        max_delay=0.5, jitter="decorrelated",
                    ),
                )
            )
            await asyncio.sleep(0.6)   # let >= 1 attempt fail on the fault
            assert not task.done()
            proxy.clear()              # heal; a later retry must converge
            nodes = await asyncio.wait_for(task, timeout=15)
            assert nodes == [f"{PATH}/retryhost", PATH]

            st = await client.stat(nodes[0])
            assert st.ephemeral_owner == client.session_id
            res = await binderview.resolve(client, DOMAIN, "A")
            assert [a.data for a in res.answers] == ["10.2.2.2"]
            assert _orphan_ephemerals(server) == []
        finally:
            await _shutdown(server, proxy, client)

    async def test_fatal_errors_are_not_retried(self):
        # SESSION_EXPIRED must stay fatal through the retry layer —
        # retrying a dead session would mask the supervisor-restart
        # design (and REGISTER_RETRY's classifier must agree).
        server, proxy, client = await _proxied_pair()
        try:
            await server.expire_session(client.session_id)
            await client.wait_for("session_expired", timeout=10)
            t0 = time.monotonic()
            with pytest.raises(ZKError) as exc:
                await register(
                    client, REG, admin_ip="10.3.3.3",
                    hostname="fatalhost", settle_delay=0.01,
                    retry_policy=REGISTER_RETRY,
                )
            assert time.monotonic() - t0 < 2.0  # no backoff attempts burned
            assert exc.value.code in (Err.SESSION_EXPIRED, Err.CONNECTION_LOSS)
            assert not is_transient(ZKError(Err.SESSION_EXPIRED))
        finally:
            await _shutdown(server, proxy, client)


class TestCacheThroughToxics:
    """ISSUE 4: the watch-coherent resolve cache behind a toxic wire.

    Coherence rides on watch delivery; a lossy/slow wire may *delay*
    convergence but must never let the cache settle on a stale answer —
    and a wire cut must degrade the cache rather than freeze it."""

    async def test_convergence_through_latency_and_slices(self):
        from registrar_tpu.zkcache import ZKCache

        server, proxy, client = await _proxied_pair()
        writer = await ZKClient([server.address]).connect()  # clean path
        cache = ZKCache(client)
        try:
            await register(
                writer, REG, admin_ip="10.1.1.1",
                hostname="netemhost", settle_delay=0,
            )
            res = await binderview.resolve(cache, DOMAIN, "A")
            assert [a.data for a in res.answers] == ["10.1.1.1"]
            # watch events now have to cross a delayed, sliced wire
            proxy.add(Latency(latency_ms=30, jitter_ms=10), direction=DOWN)
            proxy.add(Slicer(max_size=7), direction=DOWN)
            await register(
                writer, REG, admin_ip="10.1.1.2",
                hostname="late", settle_delay=0,
            )
            deadline = asyncio.get_running_loop().time() + 10
            while True:
                res = await binderview.resolve(cache, DOMAIN, "A")
                if sorted(a.data for a in res.answers) == [
                    "10.1.1.1", "10.1.1.2",
                ]:
                    break
                assert asyncio.get_running_loop().time() < deadline, (
                    "cache never converged through the toxic wire"
                )
                await asyncio.sleep(0.02)
            assert cache.authoritative
        finally:
            cache.close()
            await writer.close()
            await _shutdown(server, proxy, client)

    async def test_wire_cut_degrades_then_cold_coherent_recovery(self):
        from registrar_tpu.zkcache import ZKCache

        server, proxy, client = await _proxied_pair(request_timeout_ms=500)
        writer = await ZKClient([server.address]).connect()
        cache = ZKCache(client)
        try:
            await register(
                writer, REG, admin_ip="10.1.1.1",
                hostname="netemhost", settle_delay=0,
            )
            await binderview.resolve(cache, DOMAIN, "A")
            from registrar_tpu.records import host_record, payload_bytes

            degraded = asyncio.Event()
            cache.on("degraded", lambda _r: degraded.set())
            proxy.drop_connections()  # sever every proxied connection
            await asyncio.wait_for(degraded.wait(), timeout=10)
            # a write lands while the cache is dark
            await writer.set_data(
                f"{PATH}/netemhost",
                payload_bytes(host_record("load_balancer", "10.1.1.9")),
            )
            deadline = asyncio.get_running_loop().time() + 10
            while True:
                if cache.authoritative:
                    res = await binderview.resolve(cache, DOMAIN, "A")
                    if [a.data for a in res.answers] == ["10.1.1.9"]:
                        break
                assert asyncio.get_running_loop().time() < deadline, (
                    "cache never recovered coherently after the cut"
                )
                await asyncio.sleep(0.05)
        finally:
            cache.close()
            await writer.close()
            await _shutdown(server, proxy, client)


class TestHandoffThroughWireFaults:
    """ISSUE 5 rider: the cross-process session resume must land through
    a faulty wire — the exact moment a deploy restarts the daemon is
    also the moment ops least want a flaky network to demote the
    zero-downtime path to a re-registration blip."""

    async def test_seeded_resume_lands_through_resets(self):
        from registrar_tpu.retry import call_with_backoff

        server, proxy, client = await _proxied_pair(timeout_ms=10000)
        successor = None
        try:
            await client.create("/ho-netem", b"x", CreateFlag.EPHEMERAL)
            sid, passwd = client.session_id, client.session_passwd
            timeout_ms = client.negotiated_timeout_ms
            zxid = client.last_zxid
            await client.detach()

            # the wire RSTs every connection while the successor starts:
            # its seeded connect must keep retrying (the session seed is
            # NOT consumed by failed attempts) and, once the fault
            # clears, still reattach the SAME session inside its timeout
            toxic = proxy.add(ResetAfter(n=0), direction=UP)
            successor = ZKClient(
                [proxy.address], timeout_ms=10000,
                connect_timeout_ms=500, reconnect_policy=FAST,
            )
            successor.seed_session(
                sid, passwd, negotiated_timeout_ms=timeout_ms,
                last_zxid=zxid,
            )
            connect = asyncio.create_task(
                call_with_backoff(
                    successor.connect, FAST,
                    retryable=lambda _e: not successor.closed,
                )
            )
            await asyncio.sleep(0.5)  # several attempts die on the RST
            assert not connect.done()
            proxy.remove(toxic)
            await asyncio.wait_for(connect, timeout=8)
            assert successor.session_id == sid
            st = await successor.stat("/ho-netem")
            assert st.ephemeral_owner == sid
            proxy.remove(toxic)
            assert _orphan_ephemerals(server) == []
        finally:
            await _shutdown(server, proxy,
                            *( [successor] if successor else [] ))
