"""Release artifact smoke test (SURVEY.md §2.9: Makefile release tarball).

The reference's `make release` ships a tarball rooted at
/opt/smartdc/registrar containing everything the daemon needs; ours
roots at opt/registrar.  Building the tarball is CI's job — this test
goes further and proves the *extracted artifact runs*: config
validation and a real registration driven solely from the unpacked
tree, without the repo on the path.
"""

import asyncio
import json
import os
import shutil
import subprocess
import sys
import tarfile

import pytest

from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    shutil.which("make") is None, reason="make not available"
)


class TestReleaseArtifact:
    async def test_tarball_contents_run_standalone(self, tmp_path):
        tarball = os.path.join(REPO, "registrar-release.tar.gz")
        build = await asyncio.to_thread(
            subprocess.run,
            # PREFIX pinned: an exported PREFIX in the environment would
            # otherwise change the layout under test (Makefile uses ?=).
            ["make", "release", "PREFIX=/opt/registrar"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert build.returncode == 0, build.stderr
        assert os.path.exists(tarball)

        with tarfile.open(tarball) as tf:
            names = tf.getnames()
            try:
                tf.extractall(tmp_path, filter="data")
            except TypeError:  # Python < 3.12: no filter kwarg
                tf.extractall(tmp_path)
        root = tmp_path / "opt" / "registrar"
        assert (root / "registrar_tpu" / "main.py").exists()
        assert (root / "etc" / "config.coal.json").exists()
        assert any("systemd" in n for n in names)

        # The MPL-2.0 license text ships in the tarball like the
        # reference's LICENSE does (reference LICENSE, Makefile release).
        license_text = (root / "LICENSE").read_text()
        assert "Mozilla Public License Version 2.0" in license_text

        # Both console scripts are declared for pip installs: the daemon
        # and the operator CLI (the zkCli.sh workflow, reference
        # README.md:785-807) — and each target is importable/callable.
        pyproject = (root / "pyproject.toml").read_text()
        assert 'registrar = "registrar_tpu.main:main"' in pyproject
        assert (
            'registrar-zkcli = "registrar_tpu.tools.zkcli:main"' in pyproject
        )
        from registrar_tpu.tools.zkcli import main as zkcli_main

        assert callable(zkcli_main)

        # The shipped SMF manifest is generated from the .xml.in template
        # (reference Makefile:19): valid XML, fully substituted, and its
        # paths point into the install prefix.
        manifest = root / "smf" / "manifests" / "registrar.xml"
        assert manifest.exists()
        assert not any(n.endswith(".xml.in") for n in names)
        text = manifest.read_text()
        assert "@@" not in text
        assert "/opt/registrar/etc/config.json" in text
        import xml.etree.ElementTree as ET

        ET.fromstring(text)  # svccfg-importable at least as far as XML

        # Environment pointing ONLY at the extracted tree.
        env = {
            k: v for k, v in os.environ.items() if k != "PYTHONPATH"
        }
        env["PYTHONPATH"] = str(root)

        server = await ZKServer().start()
        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps({
            "registration": {"domain": "rel.test.us", "type": "host",
                             "heartbeatInterval": 200},
            "adminIp": "10.11.11.11",
            "zookeeper": {"servers": [{"host": server.host,
                                       "port": server.port}],
                          "timeout": 5000},
        }))
        try:
            # 1. Config pre-flight from the artifact.
            out = await asyncio.to_thread(
                subprocess.run,
                [sys.executable, "-m", "registrar_tpu",
                 "-f", str(cfg_path), "-n"],
                cwd=tmp_path, env=env, capture_output=True, text=True,
                timeout=30,
            )
            assert out.returncode == 0, out.stdout + out.stderr
            assert "configuration OK" in out.stdout

            # 2. The daemon from the artifact registers for real.
            proc = subprocess.Popen(
                [sys.executable, "-m", "registrar_tpu", "-f", str(cfg_path)],
                cwd=tmp_path, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
            )
            try:
                probe = await ZKClient([server.address]).connect()
                deadline = asyncio.get_running_loop().time() + 20
                while await probe.exists("/us/test/rel") is None:
                    assert asyncio.get_running_loop().time() < deadline
                    await asyncio.sleep(0.1)
                await probe.close()
            finally:
                proc.terminate()
                await asyncio.to_thread(proc.wait, 15)
        finally:
            await server.stop()
            if os.path.exists(tarball):
                os.unlink(tarball)
