"""Metrics endpoint tests: registry rendering, HTTP server, event wiring.

The reference has no metrics surface (SURVEY.md §5: bunyan logs only;
contemporaries used node-artedi).  The rebuild's opt-in `metrics` config
block exposes Prometheus text format 0.0.4 — these tests pin the format,
the HTTP behavior, and that the counters actually track the
register_plus event surface end to end.
"""

import asyncio

import pytest

from registrar_tpu.agent import register_plus
from registrar_tpu.metrics import (
    Counter,
    Gauge,
    MetricsRegistry,
    MetricsServer,
    instrument,
)
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk.client import ZKClient


async def _http_get(host, port, path):
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(
        f"GET {path} HTTP/1.0\r\nHost: {host}\r\n\r\n".encode()
    )
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(), timeout=5)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, head.decode(), body.decode()


class TestRegistry:
    def test_counter_rendering_and_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("x_total", "things that happened")
        c.inc()
        c.inc(2, labels={"status": "ok"})
        c.inc(labels={"status": "fail"})
        text = reg.render()
        assert "# HELP x_total things that happened" in text
        assert "# TYPE x_total counter" in text
        assert "\nx_total 1" in text
        assert 'x_total{status="fail"} 1' in text
        assert 'x_total{status="ok"} 2' in text

    def test_counter_never_decrements(self):
        c = Counter("c_total", "h")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set_and_function(self):
        g = Gauge("g", "h")
        g.set(2.5)
        assert "g 2.5" in "\n".join(g.render())
        g.set_function(lambda: 7)
        assert "g 7" in "\n".join(g.render())

    def test_unsampled_metric_renders_zero(self):
        reg = MetricsRegistry()
        reg.counter("quiet_total", "never incremented")
        assert "quiet_total 0" in reg.render()

    def test_duplicate_name_rejected(self):
        reg = MetricsRegistry()
        reg.counter("a", "h")
        with pytest.raises(ValueError):
            reg.gauge("a", "h")

    def test_label_escaping(self):
        c = Counter("e_total", "h")
        c.inc(labels={"cmd": 'say "hi"\nplease'})
        out = "\n".join(c.render())
        assert '{cmd="say \\"hi\\"\\nplease"}' in out

    def test_counter_set_total_is_monotonic_per_labelset(self):
        # The shard router's rollup path (ISSUE 12): polled cumulative
        # totals install directly, but a stale LOWER value (a poll that
        # raced a respawn's banked counter) is ignored — a counter can
        # never be seen going backwards.
        c = Counter("polled_total", "h")
        c.set_total(5, labels={"shard": "0"})
        c.set_total(9, labels={"shard": "0"})
        c.set_total(7, labels={"shard": "0"})  # stale: ignored
        c.set_total(3, labels={"shard": "1"})  # independent label set
        assert c.value({"shard": "0"}) == 9
        assert c.value({"shard": "1"}) == 3


class TestHttp:
    async def test_metrics_endpoint_and_404(self):
        reg = MetricsRegistry()
        reg.counter("t_total", "h").inc(3)
        server = await MetricsServer(reg).start()
        try:
            status, head, body = await _http_get(
                server.host, server.port, "/metrics"
            )
            assert status == 200
            assert "text/plain; version=0.0.4" in head
            assert "t_total 3" in body

            status, _, _ = await _http_get(server.host, server.port, "/else")
            assert status == 404
        finally:
            await server.stop()

    async def test_oversized_request_line_dropped_cleanly(self):
        # A request line beyond the StreamReader limit raises ValueError
        # inside readline; the handler must drop the connection without an
        # unhandled-task exception and keep serving.
        reg = MetricsRegistry()
        reg.counter("t_total", "h").inc(1)
        server = await MetricsServer(reg).start()
        try:
            reader, writer = await asyncio.open_connection(
                server.host, server.port
            )
            writer.write(b"GET /" + b"A" * (128 * 1024))  # no newline
            await writer.drain()
            try:
                raw = await asyncio.wait_for(reader.read(), timeout=5)
            except ConnectionResetError:
                raw = b""  # server closed with unread bytes pending -> RST
            assert raw == b""  # dropped, no response owed
            writer.close()

            status, _, body = await _http_get(
                server.host, server.port, "/metrics"
            )
            assert status == 200 and "t_total 1" in body  # still alive
        finally:
            await server.stop()


class TestInstrumentation:
    async def test_counters_track_agent_events(self):
        zk_server = await ZKServer().start()
        client = await ZKClient([zk_server.address]).connect()
        try:
            ee = register_plus(
                client,
                {"domain": "metrics.test.us", "type": "host"},
                admin_ip="10.0.0.1",
                hostname="mbox",
                heartbeat_interval=0.03,
                settle_delay=0.01,
            )
            reg = instrument(ee, client)
            await ee.wait_for("register", timeout=10)
            await ee.wait_for("heartbeat", timeout=10)
            text = reg.render()
            assert "registrar_registrations_total 1" in text
            assert 'registrar_heartbeats_total{status="ok"}' in text
            # Documented label sets exist from the first scrape, so
            # rate()/absent() alerts work before the first failure.
            assert 'registrar_heartbeats_total{status="failure"} 0' in text
            assert 'registrar_health_transitions_total{to="down"} 0' in text
            assert "registrar_znodes_owned 1" in text
            assert "registrar_zk_connected 1" in text
            assert "registrar_health_down 0" in text
            ee.stop()
        finally:
            await client.close()
            await zk_server.stop()

    async def test_rebirth_and_drift_metrics(self):
        # ISSUE 3: session rebirths, drift detected/repaired by reason,
        # and the reconcile sweep counters ride the same event surface.
        from registrar_tpu.retry import RetryPolicy

        zk_server = await ZKServer().start()
        client = await ZKClient(
            [zk_server.address],
            survive_session_expiry=True,
            reconnect_policy=RetryPolicy(
                max_attempts=float("inf"), initial_delay=0.02, max_delay=0.1
            ),
        ).connect()
        try:
            ee = register_plus(
                client,
                {"domain": "metrics.test.us", "type": "host"},
                admin_ip="10.0.0.1",
                hostname="mbox",
                heartbeat_interval=60,
                settle_delay=0.01,
                reconcile={"interval_seconds": 0.05, "repair": True},
            )
            reg = instrument(ee, client)
            (znodes,) = await ee.wait_for("register", timeout=10)

            # Pre-seeded zero series exist before anything drifts.
            text = reg.render()
            assert 'registrar_drift_total{reason="owner"} 0' in text
            assert 'registrar_drift_repaired_total{reason="payload"} 0' in text
            assert "registrar_session_rebirths_total 0" in text
            assert "registrar_rebirth_breaker_trips_total 0" in text

            # Mint one missing-node drift and let the reconciler repair it.
            await client.unlink(znodes[0])
            await ee.wait_for("driftRepaired", timeout=10)

            # Force an expiry -> in-process rebirth -> re-registration.
            rereg = asyncio.ensure_future(ee.wait_for("register", timeout=10))
            await zk_server.expire_session(client.session_id)
            await rereg

            await ee.wait_for("reconcile", timeout=10)
            assert reg.get("registrar_drift_total").value(
                {"reason": "missing"}
            ) >= 1
            assert reg.get("registrar_drift_repaired_total").value(
                {"reason": "missing"}
            ) >= 1
            assert reg.get("registrar_session_rebirths_total").value() == 1
            assert reg.get("registrar_rebirth_breaker_trips_total").value() == 0
            assert reg.get("registrar_reconcile_sweeps_total").value() >= 1
            rendered = reg.render()
            assert "registrar_reconcile_sweep_seconds" in rendered
            ee.stop()
        finally:
            await client.close()
            await zk_server.stop()

    async def test_busy_metrics_port_does_not_block_registration(self):
        """A busy port logs an error; registration must proceed anyway."""
        from registrar_tpu.config import parse_config
        from registrar_tpu.main import run

        # Occupy a port for the duration.
        blocker = await asyncio.start_server(
            lambda r, w: None, "127.0.0.1", 0
        )
        port = blocker.sockets[0].getsockname()[1]
        zk_server = await ZKServer().start()
        cfg = parse_config(
            {
                "registration": {"domain": "busy.metrics.us", "type": "host"},
                "adminIp": "10.1.1.2",
                "zookeeper": {
                    "servers": [
                        {"host": zk_server.host, "port": zk_server.port}
                    ],
                    "timeout": 5000,
                },
                "metrics": {"port": port},
            }
        )
        task = asyncio.create_task(run(cfg, _exit=lambda code: None))
        probe = None
        try:
            probe = await ZKClient([zk_server.address]).connect()
            deadline = asyncio.get_running_loop().time() + 20
            while await probe.exists("/us/metrics/busy") is None:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.1)
        finally:
            if probe is not None:
                await probe.close()
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            blocker.close()
            await blocker.wait_closed()
            await zk_server.stop()

    async def test_daemon_serves_metrics(self):
        """End to end through main.run(): config block -> live /metrics."""
        import socket

        from registrar_tpu.config import parse_config
        from registrar_tpu.main import run

        # Grab a free port for the metrics listener (bind(0), read, close).
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        zk_server = await ZKServer().start()
        cfg = parse_config(
            {
                "registration": {
                    "domain": "daemon.metrics.us",
                    "type": "host",
                    "heartbeatInterval": 50,
                },
                "adminIp": "10.1.1.1",
                "zookeeper": {
                    "servers": [
                        {"host": zk_server.host, "port": zk_server.port}
                    ],
                    "timeout": 5000,
                },
                "metrics": {"port": port},
            }
        )
        task = asyncio.create_task(run(cfg, _exit=lambda code: None))
        try:
            # The pipeline includes the reference's fixed 1 s settle delay;
            # poll until registration lands, then scrape.
            deadline = asyncio.get_running_loop().time() + 20
            text = None
            while asyncio.get_running_loop().time() < deadline:
                try:
                    status, _, text = await _http_get(
                        "127.0.0.1", port, "/metrics"
                    )
                    if (
                        status == 200
                        and "registrar_registrations_total 1" in text
                    ):
                        break
                except OSError:
                    pass
                await asyncio.sleep(0.1)
            assert text is not None
            assert "registrar_registrations_total 1" in text
            assert "registrar_zk_connected 1" in text
            assert "registrar_znodes_owned 1" in text
            assert "registrar_uptime_seconds" in text
            # /status: uptime_s + last_transition stamps (ISSUE 9
            # satellite) — MTTR is computable from a live daemon, so
            # the registration transition must carry a wall stamp.
            status, _, body = await _http_get(
                "127.0.0.1", port, "/status"
            )
            assert status == 200
            import json as json_mod
            import time as time_mod

            snapshot = json_mod.loads(body)
            assert snapshot["uptime_s"] >= 0
            reg_transition = snapshot["last_transition"]["registration"]
            assert reg_transition["state"] == "registered"
            assert abs(time_mod.time() - reg_transition["at"]) < 60
        finally:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            await zk_server.stop()


def test_metric_value_defaults_to_zero_for_unsampled_labels():
    from registrar_tpu.metrics import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter("zero_test_total", "help")
    assert c.value({"never": "sampled"}) == 0.0
    assert reg.get("zero_test_total") is c
    assert reg.get("no_such_metric") is None


class TestCacheInstrumentation:
    """ISSUE 4: instrument_cache exposes the resolve cache's stats at
    scrape time, pre-seeded (every series present before any traffic)."""

    def _cache_like(self):
        # duck-typed stand-in: instrument_cache only touches stats,
        # .entries, and .authoritative
        class FakeCache:
            stats = {
                "hits": 0, "misses": 0, "invalidations": 0,
                "bypasses": 0, "degraded_total": 0, "evictions": 0,
                "stale_serves": 0, "stale_refusals": 0,
                "coherence_lag_ms_last": 0.0,
                "coherence_lag_ms_total": 0.0, "coherence_lag_count": 0,
            }
            entries = 0
            authoritative = True

        return FakeCache()

    def test_pre_seeded_series_render_at_zero(self):
        from registrar_tpu.metrics import MetricsRegistry, instrument_cache

        cache = self._cache_like()
        reg = instrument_cache(cache, MetricsRegistry())
        text = reg.render()
        for series in (
            "registrar_cache_hits_total 0",
            "registrar_cache_misses_total 0",
            "registrar_cache_invalidations_total 0",
            "registrar_cache_bypasses_total 0",
            "registrar_cache_degraded_total 0",
            "registrar_cache_stale_serves_total 0",
            "registrar_cache_stale_refusals_total 0",
            "registrar_cache_evictions_total 0",
            "registrar_cache_coherence_lag_seconds_total 0",
            "registrar_cache_coherence_lag_count 0",
            "registrar_cache_entries 0",
            "registrar_cache_authoritative 1",
            "registrar_cache_coherence_lag_seconds 0",
        ):
            assert f"\n{series}\n" in f"\n{text}", f"missing: {series}"

    def test_scrape_reads_live_stats(self):
        from registrar_tpu.metrics import MetricsRegistry, instrument_cache

        cache = self._cache_like()
        reg = instrument_cache(cache, MetricsRegistry())
        cache.stats["hits"] = 41
        cache.stats["misses"] = 7
        cache.stats["coherence_lag_ms_total"] = 1500.0
        cache.stats["coherence_lag_ms_last"] = 250.0
        cache.entries = 3
        cache.authoritative = False
        text = reg.render()
        assert "registrar_cache_hits_total 41" in text
        assert "registrar_cache_misses_total 7" in text
        assert "registrar_cache_coherence_lag_seconds_total 1.5" in text
        assert "registrar_cache_coherence_lag_seconds 0.25" in text
        assert "registrar_cache_entries 3" in text
        assert "registrar_cache_authoritative 0" in text

    async def test_real_cache_round_trip(self):
        """End to end: a real ZKCache, real resolves, scraped counters."""
        from registrar_tpu import binderview
        from registrar_tpu.metrics import MetricsRegistry, instrument_cache
        from registrar_tpu.registration import register
        from registrar_tpu.testing.server import ZKServer
        from registrar_tpu.zk.client import ZKClient
        from registrar_tpu.zkcache import ZKCache

        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            await register(
                client, {"domain": "m.test.us", "type": "host"},
                admin_ip="10.0.0.1", hostname="h0", settle_delay=0,
            )
            cache = ZKCache(client)
            reg = instrument_cache(cache, MetricsRegistry())
            await binderview.resolve(cache, "h0.m.test.us", "A")
            await binderview.resolve(cache, "h0.m.test.us", "A")
            text = reg.render()
            assert "registrar_cache_hits_total 1" in text
            assert "registrar_cache_misses_total 1" in text
            assert "registrar_cache_authoritative 1" in text
            cache.close()
        finally:
            await client.close()
            await server.stop()


class TestRestartInstrumentation:
    """ISSUE 5: handoff/resume/reload counters, all pre-seeded."""

    async def test_restart_counters_wired_and_pre_seeded(self):
        from registrar_tpu.agent import register_plus
        from registrar_tpu.metrics import instrument
        from registrar_tpu.testing.server import ZKServer
        from registrar_tpu.zk.client import ZKClient

        server = await ZKServer().start()
        client = await ZKClient([server.address]).connect()
        try:
            ee = register_plus(
                client, {"domain": "m.test.us", "type": "host"},
                admin_ip="10.1.1.1", hostname="mbox", settle_delay=0.01,
            )
            reg = instrument(ee, client)
            await ee.wait_for("register", timeout=10)

            # every series exists at zero before any event fires
            text = reg.render()
            for line in (
                'registrar_session_resumes_total{outcome="reattached"} 0',
                'registrar_session_resumes_total{outcome="repaired"} 0',
                'registrar_session_resumes_total{outcome="fresh"} 0',
                'registrar_config_reloads_total{result="applied"} 0',
                'registrar_config_reloads_total{result="noop"} 0',
                'registrar_config_reloads_total{result="failed"} 0',
                "registrar_handoffs_total 0",
                "registrar_drains_total 0",
            ):
                assert line in text, line

            ee.emit("resume", "reattached")
            ee.emit("resume", "fresh")
            ee.emit("configReload", "applied")
            ee.emit("configReload", "failed")
            ee.emit("handoff", "/var/run/state.json")
            ee.emit("drain", ["/m/test"])
            text = reg.render()
            assert 'registrar_session_resumes_total{outcome="reattached"} 1' in text
            assert 'registrar_session_resumes_total{outcome="fresh"} 1' in text
            assert 'registrar_session_resumes_total{outcome="repaired"} 0' in text
            assert 'registrar_config_reloads_total{result="applied"} 1' in text
            assert 'registrar_config_reloads_total{result="failed"} 1' in text
            assert "registrar_handoffs_total 1" in text
            assert "registrar_drains_total 1" in text
            ee.stop()
        finally:
            await client.close()
            await server.stop()


class TestEnsembleInstrumentation:
    """ISSUE 10: write-refusal counter + member-role info gauge."""

    async def test_write_refusals_and_member_role(self):
        from registrar_tpu.agent import RegistrarEvents
        from registrar_tpu.testing.server import ZKEnsemble

        async with ZKEnsemble(3) as ens:
            client = ZKClient(
                ens.addresses, timeout_ms=60_000, can_be_read_only=True,
                reconnect=False,
            )
            await client.connect()
            try:
                reg = instrument(RegistrarEvents(), client)
                text = reg.render()
                # pre-seeded series exist before any refusal
                assert (
                    'registrar_write_refusals_total{reason="read_only"} 0'
                    in text
                )
                assert (
                    'registrar_zk_member_role{role="read_write"} 1' in text
                )
                assert (
                    'registrar_zk_member_role{role="read_only"} 0' in text
                )
                # degrade to a read-only minority and renegotiate
                await ens.kill(1)
                await ens.kill(2)
                survivor = ens.servers[0]
                ro = ZKClient(
                    [(survivor.host, survivor.port)],
                    timeout_ms=60_000, can_be_read_only=True,
                )
                await ro.connect()
                try:
                    reg2 = instrument(RegistrarEvents(), ro)
                    with pytest.raises(Exception):
                        await ro.create("/refused", b"")
                    text = reg2.render()
                    assert (
                        'registrar_write_refusals_total{reason="read_only"} 1'
                        in text
                    )
                    assert (
                        'registrar_zk_member_role{role="read_only"} 1'
                        in text
                    )
                    assert (
                        'registrar_zk_member_role{role="read_write"} 0'
                        in text
                    )
                finally:
                    await ro.close()
                text = reg2.render()
                assert (
                    'registrar_zk_member_role{role="disconnected"} 1' in text
                )
            finally:
                await client.close()
