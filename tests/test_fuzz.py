"""Property-based and fuzz tests for the wire layer and data contract.

Two goals: (1) encode/decode are exact inverses for arbitrary valid
values; (2) arbitrary malformed bytes never produce anything but the
typed JuteError / a dropped connection — no hangs, no stray exceptions,
no server crashes.
"""

import asyncio
import random
import struct

import pytest

# The property suite NEEDS hypothesis, but the tier-1 environment does
# not ship it — skip at collection (one 's' in the report) instead of
# erroring the whole file, which forced every runner to carry
# --continue-on-collection-errors forever (ISSUE 13 satellite).
pytest.importorskip(
    "hypothesis",
    reason="hypothesis not installed (property/fuzz suite is opt-in)",
)

from hypothesis import given, settings, strategies as st  # noqa: E402

from registrar_tpu.records import (
    domain_to_path,
    host_record,
    parse_payload,
    path_to_domain,
    payload_bytes,
)
from registrar_tpu.testing.server import ZKServer
from registrar_tpu.zk import protocol as proto
from registrar_tpu.zk.jute import INT_MAX, INT_MIN, LONG_MAX, LONG_MIN, JuteError, Reader, Writer
from registrar_tpu.zk.client import ZKClient

ints = st.integers(INT_MIN, INT_MAX)
longs = st.integers(LONG_MIN, LONG_MAX)


class TestJuteProperties:
    @given(ints)
    def test_int_roundtrip(self, v):
        assert Reader(Writer().write_int(v).to_bytes()).read_int() == v

    @given(longs)
    def test_long_roundtrip(self, v):
        assert Reader(Writer().write_long(v).to_bytes()).read_long() == v

    @given(st.one_of(st.none(), st.binary(max_size=2048)))
    def test_buffer_roundtrip(self, v):
        assert Reader(Writer().write_buffer(v).to_bytes()).read_buffer() == v

    @given(st.one_of(st.none(), st.text(max_size=256)))
    def test_ustring_roundtrip(self, v):
        assert Reader(Writer().write_ustring(v).to_bytes()).read_ustring() == v

    @given(st.lists(st.text(max_size=32), max_size=32))
    def test_vector_roundtrip(self, v):
        data = Writer().write_vector(v, Writer.write_ustring).to_bytes()
        assert Reader(data).read_vector(Reader.read_ustring) == v

    @given(st.binary(max_size=512))
    @settings(max_examples=300)
    def test_arbitrary_bytes_never_crash_reader(self, data):
        """Malformed input must yield the typed JuteError ONLY — since
        ISSUE 16 even invalid UTF-8 in read_ustring is wrapped, so a
        decode loop needs exactly one except clause."""
        r = Reader(data)
        fixed = struct.Struct(">iq")
        for op in (Reader.read_int, Reader.read_long, Reader.read_bool,
                   Reader.read_buffer, Reader.read_ustring,
                   lambda rr: rr.long_at(0),
                   lambda rr: rr.read_struct(fixed)):
            try:
                op(Reader(data))
            except JuteError:
                pass
        try:
            r.read_vector(Reader.read_ustring)
        except JuteError:
            pass

    @given(st.binary(max_size=256))
    @settings(max_examples=200)
    def test_memoryview_input_parity(self, data):
        # The zero-copy path (the frame layer hands replies over as
        # memoryviews) must accept and reject byte-for-byte like bytes.
        def script(reader):
            out = []
            try:
                out.append(reader.read_int())
                out.append(reader.read_buffer())
                out.append(reader.read_ustring())
            except JuteError as err:
                out.append(("reject", str(err)))
            return out

        assert script(Reader(data)) == script(Reader(memoryview(data)))


class TestRecordProperties:
    @given(longs, longs, ints, st.integers(0, INT_MAX))
    def test_stat_roundtrip(self, a, b, c, d):
        stat = proto.Stat(czxid=a, mzxid=b, version=c, data_length=d)
        w = Writer()
        stat.write(w)
        assert proto.Stat.read(Reader(w.to_bytes())) == stat

    @given(st.binary(max_size=256))
    @settings(max_examples=200)
    def test_arbitrary_payload_never_crashes_record_readers(self, data):
        for record in (proto.ConnectRequest, proto.ConnectResponse,
                       proto.CreateRequest, proto.ReplyHeader,
                       proto.WatcherEvent, proto.SetWatches,
                       proto.AuthPacket, proto.GetACLResponse,
                       proto.SetACLRequest):
            try:
                record.read(Reader(data))
            except (JuteError, UnicodeDecodeError):
                pass

    _acls = st.lists(
        st.tuples(
            st.integers(1, 31),
            st.sampled_from(["world", "digest", "ip", "auth"]),
            st.text(max_size=32),
        ).map(lambda t: proto.ACL(perms=t[0], scheme=t[1], id=t[2])),
        min_size=1,
        max_size=8,
    )

    @given(_acls, ints)
    def test_set_acl_request_roundtrip(self, acls, version):
        req = proto.SetACLRequest(path="/p", acls=acls, version=version)
        w = Writer()
        req.write(w)
        assert proto.SetACLRequest.read(Reader(w.to_bytes())) == req

    @given(_acls)
    def test_get_acl_response_roundtrip(self, acls):
        resp = proto.GetACLResponse(acls=acls, stat=proto.Stat())
        w = Writer()
        resp.write(w)
        assert proto.GetACLResponse.read(Reader(w.to_bytes())) == resp

    @given(
        st.sampled_from(["digest", "ip", "x"]),
        st.one_of(st.none(), st.binary(max_size=64)),
    )
    def test_auth_packet_roundtrip(self, scheme, auth):
        pkt = proto.AuthPacket(type=0, scheme=scheme, auth=auth)
        w = Writer()
        pkt.write(w)
        assert proto.AuthPacket.read(Reader(w.to_bytes())) == pkt

    _paths = st.text(
        alphabet=st.characters(min_codepoint=ord("a"), max_codepoint=ord("z")),
        min_size=1, max_size=12,
    ).map(lambda s: "/" + s)
    _multi_ops = st.lists(
        st.one_of(
            st.tuples(_paths, st.binary(max_size=64), st.integers(0, 3)).map(
                lambda t: (
                    proto.OpCode.CREATE,
                    proto.CreateRequest(path=t[0], data=t[1], flags=t[2]),
                )
            ),
            st.tuples(_paths, ints).map(
                lambda t: (
                    proto.OpCode.DELETE,
                    proto.DeleteRequest(path=t[0], version=t[1]),
                )
            ),
            st.tuples(_paths, st.binary(max_size=64), ints).map(
                lambda t: (
                    proto.OpCode.SET_DATA,
                    proto.SetDataRequest(path=t[0], data=t[1], version=t[2]),
                )
            ),
            st.tuples(_paths, ints).map(
                lambda t: (
                    proto.OpCode.CHECK,
                    proto.CheckVersionRequest(path=t[0], version=t[1]),
                )
            ),
        ),
        max_size=16,
    )

    @given(_multi_ops)
    def test_multi_request_roundtrip(self, ops):
        w = Writer()
        proto.MultiRequest(ops=ops).write(w)
        assert proto.MultiRequest.read(Reader(w.to_bytes())).ops == ops

    @given(
        st.lists(
            st.one_of(
                _paths.map(lambda p: proto.CreateResponse(path=p)),
                ints.map(lambda e: proto.ErrorResult(err=e)),
                st.just(proto.DeleteResult()),
                st.just(proto.CheckResult()),
                ints.map(
                    lambda v: proto.SetDataResponse(stat=proto.Stat(version=v))
                ),
            ),
            max_size=16,
        )
    )
    def test_multi_response_roundtrip(self, results):
        w = Writer()
        proto.MultiResponse(results=results).write(w)
        assert proto.MultiResponse.read(Reader(w.to_bytes())).results == results

    @given(st.binary(max_size=256))
    @settings(max_examples=200)
    def test_arbitrary_bytes_never_crash_multi_readers(self, data):
        for record in (proto.MultiRequest, proto.MultiResponse,
                       proto.MultiHeader, proto.CheckVersionRequest):
            try:
                record.read(Reader(data))
            except (JuteError, UnicodeDecodeError, ValueError):
                pass

    @given(
        st.text(
            alphabet=st.characters(
                whitelist_categories=("Ll", "Nd"), max_codepoint=0x7F
            ),
            min_size=1, max_size=20,
        )
    )
    def test_domain_path_roundtrip(self, label):
        domain = f"{label}.example.com"
        assert path_to_domain(domain_to_path(domain)) == domain

    @given(
        st.sampled_from(["host", "load_balancer", "redis_host"]),
        st.one_of(st.none(), st.integers(0, 86400)),
        st.one_of(st.none(), st.lists(st.integers(1, 65535), max_size=8)),
    )
    def test_host_record_payload_roundtrip(self, rtype, ttl, ports):
        rec = host_record(rtype, "10.0.0.1", ttl=ttl, ports=ports)
        parsed = parse_payload(payload_bytes(rec))
        assert parsed == rec
        assert list(parsed) == list(rec)  # key order preserved


class TestServerFuzz:
    async def test_random_garbage_connections_dont_kill_server(self):
        rng = random.Random(0xC0FFEE)
        server = await ZKServer().start()
        try:
            for _ in range(30):
                try:
                    r, w = await asyncio.open_connection(*server.address)
                    n = rng.randrange(1, 64)
                    w.write(bytes(rng.randrange(256) for _ in range(n)))
                    await w.drain()
                    w.close()
                except (ConnectionError, OSError):
                    pass
            # server still healthy for a real client
            client = await ZKClient([server.address]).connect()
            await client.create("/post-fuzz", b"ok")
            data, _ = await client.get("/post-fuzz")
            assert data == b"ok"
            await client.close()
        finally:
            await server.stop()

    async def test_valid_handshake_then_garbage_frames(self):
        rng = random.Random(0xFACADE)
        server = await ZKServer().start()
        try:
            for _ in range(15):
                client = ZKClient([server.address], reconnect=False)
                await client.connect()
                # inject garbage directly into the socket after handshake
                payload = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 48)))
                client._writer.write(proto.frame(payload))
                try:
                    await client._writer.drain()
                except (ConnectionError, OSError):
                    pass
                await asyncio.sleep(0)
                try:
                    await client.close()
                except Exception:  # noqa: BLE001 - teardown races are fine here
                    pass
            probe = await ZKClient([server.address]).connect()
            await probe.create("/still-alive", b"")
            await probe.close()
        finally:
            await server.stop()


class TestClientFuzz:
    async def test_client_survives_garbage_from_server(self):
        # The inverse of the server fuzz: a server that completes the
        # handshake then spews corrupt framing must produce a clean
        # client teardown (close event), never a hang or a crash.
        garbage_cases = [
            b"\xff" * 64,                      # negative frame length
            (2**31 - 1).to_bytes(4, "big"),    # absurd length, no payload
            bytes(random.Random(0xDEAD).randrange(256) for _ in range(48)),
        ]
        for garbage in garbage_cases:
            async def handler(reader, writer, g=garbage):
                try:
                    hdr = await reader.readexactly(4)
                    await reader.readexactly(int.from_bytes(hdr, "big"))
                    w = Writer()
                    proto.ConnectResponse(
                        timeout_ms=6000, session_id=1, passwd=b"\x00" * 16
                    ).write(w)
                    writer.write(proto.frame(w.to_bytes()))
                    await writer.drain()
                    writer.write(g)
                    await writer.drain()
                    # EOF after the garbage: random bytes can form a
                    # plausible length prefix, and waiting for the rest
                    # of that frame is then the CORRECT client behavior —
                    # the close is what turns it into a dead connection.
                    writer.close()
                except (ConnectionError, OSError, asyncio.IncompleteReadError):
                    pass

            srv = await asyncio.start_server(handler, "127.0.0.1", 0)
            port = srv.sockets[0].getsockname()[1]
            client = ZKClient([("127.0.0.1", port)], reconnect=False)
            closed = asyncio.Event()
            client.on("close", lambda *a: closed.set())  # before connect:
            # the teardown can fire between connect() returning and any
            # later registration, so the listener must already be armed
            await client.connect()
            await asyncio.wait_for(closed.wait(), timeout=5)
            await client.close()
            srv.close()
            await srv.wait_closed()


class TestShardWireFuzz:
    """ISSUE 16: the sharded serve tier's decode boundary — arbitrary
    bytes land in ShardError (the class the relay answers STATUS_ERR)
    or decode cleanly; never MemoryError/IndexError/struct.error."""

    @given(st.binary(max_size=128))
    @settings(max_examples=300)
    def test_resolve_name_contract(self, body):
        from registrar_tpu.shard import ShardError, resolve_name

        try:
            name = resolve_name(body)
        except ShardError:
            return
        assert isinstance(name, str)

    @given(
        st.text(max_size=32),
        st.sampled_from(["A", "AAAA", "SRV", "TXT"]),
        st.booleans(),
    )
    def test_resolve_name_roundtrips_well_formed_bodies(
        self, name, qtype, live
    ):
        from registrar_tpu.shard import pack_resolve, resolve_name

        assert resolve_name(pack_resolve(name, qtype, live)) == name

    @given(st.binary(max_size=64), st.integers(0, 0xFF))
    @settings(max_examples=300)
    def test_split_traced_contract(self, frame, op):
        from registrar_tpu.shard import ShardError, TRACE_FLAG, split_traced

        try:
            out_op, ctx, body = split_traced(frame, op)
        except ShardError:
            return
        assert 0 <= out_op <= 0xFF and not out_op & TRACE_FLAG
        assert ctx is None or len(ctx) == 3
        assert bytes(body) in bytes(frame)

    @given(st.binary(min_size=4, max_size=64))
    @settings(max_examples=200, deadline=None)
    def test_read_frame_contract(self, prefix):
        from registrar_tpu.shard import ShardError, _read_frame

        class _Scripted:
            def __init__(self, data):
                self._data = data

            async def readexactly(self, n):
                if len(self._data) < n:
                    raise asyncio.IncompleteReadError(self._data, n)
                out, self._data = self._data[:n], self._data[n:]
                return out

        try:
            frame = asyncio.run(_read_frame(_Scripted(prefix)))
        except ShardError:
            return
        assert frame is None or len(frame) == int.from_bytes(
            prefix[:4], "big"
        )


class TestFramingFuzz:
    """ISSUE 16: well-formed frames followed by arbitrary trailing
    garbage, split at an arbitrary chunk boundary — every complete
    frame carves in order and the only possible raise is the framing
    contract ConnectionError."""

    @given(
        st.lists(st.binary(max_size=32), max_size=4),
        st.binary(max_size=16),
        st.integers(0, 160),
    )
    @settings(max_examples=300, deadline=None)
    def test_carve_or_reject(self, payloads, garbage, cut):
        from registrar_tpu.zk.framing import FrameReader

        class _Scripted:
            def __init__(self, chunks):
                self._chunks = [c for c in chunks if c]

            async def read(self, _n):
                return self._chunks.pop(0) if self._chunks else b""

        wire = b"".join(
            len(p).to_bytes(4, "big") + p for p in payloads
        ) + garbage
        cut = min(cut, len(wire))
        fr = FrameReader(_Scripted([wire[:cut], wire[cut:]]))

        async def go():
            carved = []
            while await fr.fill():
                carved.extend(fr.carve())
            return carved

        try:
            carved = [bytes(f) for f in asyncio.run(go())]
        except ConnectionError:
            return  # garbage corrupted a length prefix
        assert carved[: len(payloads)] == payloads


class TestChrootMapping:
    """_abs/_rel are exact inverses for any chroot and any client path."""

    _comp = st.text(
        alphabet=st.characters(min_codepoint=ord("a"), max_codepoint=ord("z")),
        min_size=1, max_size=8,
    )
    _client_paths = st.lists(_comp, min_size=0, max_size=4).map(
        lambda parts: "/" + "/".join(parts) if parts else "/"
    )
    _chroots = st.lists(_comp, min_size=1, max_size=3).map(
        lambda parts: "/" + "/".join(parts)
    )

    @given(_chroots, _client_paths)
    def test_abs_rel_roundtrip(self, chroot, path):
        client = ZKClient([("h", 1)], chroot=chroot)
        absolute = client._abs(path)
        assert absolute.startswith(chroot)
        assert client._rel(absolute) == path
        # _abs always yields a valid znode path
        proto.check_path(absolute)

    @given(_client_paths)
    def test_no_chroot_is_identity(self, path):
        client = ZKClient([("h", 1)])
        assert client._abs(path) == path
        assert client._rel(path) == path
