"""Bunyan-format logging tests: downstream log tooling compatibility."""

import io
import json
import logging

from registrar_tpu import jlog


def _setup(level=None):
    buf = io.StringIO()
    log = jlog.setup("registrar", level=level, stream=buf)
    return log, buf


def _records(buf):
    return [json.loads(line) for line in buf.getvalue().splitlines()]


class TestFormat:
    def test_bunyan_required_fields(self):
        log, buf = _setup(level=logging.INFO)
        log.info("hello %s", "world")
        (rec,) = _records(buf)
        # the bunyan record contract: v/level/name/hostname/pid/time/msg
        assert rec["v"] == 0
        assert rec["level"] == 30
        assert rec["name"] == "registrar"
        assert rec["msg"] == "hello world"
        assert isinstance(rec["pid"], int)
        assert rec["time"].endswith("Z")
        assert "T" in rec["time"]

    def test_level_numbers(self):
        log, buf = _setup(level=jlog.TRACE)
        log.log(jlog.TRACE, "t")
        log.debug("d")
        log.info("i")
        log.warning("w")
        log.error("e")
        log.critical("f")
        assert [r["level"] for r in _records(buf)] == [10, 20, 30, 40, 50, 60]

    def test_extra_zdata_fields(self):
        log, buf = _setup(level=logging.INFO)
        log.info("registered", extra={"zdata": {"znodes": ["/a", "/b"]}})
        (rec,) = _records(buf)
        assert rec["znodes"] == ["/a", "/b"]

    def test_err_serializer(self):
        log, buf = _setup(level=logging.INFO)
        try:
            raise RuntimeError("kaboom")
        except RuntimeError:
            log.exception("failed")
        (rec,) = _records(buf)
        assert rec["err"]["name"] == "RuntimeError"
        assert rec["err"]["message"] == "kaboom"
        assert "Traceback" in rec["err"]["stack"]

    def test_exception_value_in_zdata(self):
        log, buf = _setup(level=logging.INFO)
        log.error("e", extra={"zdata": {"err": ValueError("bad")}})
        (rec,) = _records(buf)
        assert rec["err"] == {"message": "bad", "name": "ValueError"}


class TestSrc:
    def test_src_present_at_debug(self):
        log, buf = _setup(level=logging.DEBUG)
        log.debug("x")
        (rec,) = _records(buf)
        assert rec["src"]["file"].endswith("test_jlog.py")
        assert isinstance(rec["src"]["line"], int)

    def test_src_absent_at_info(self):
        log, buf = _setup(level=logging.INFO)
        log.info("x")
        (rec,) = _records(buf)
        assert "src" not in rec


class TestLevels:
    def test_env_level(self, monkeypatch):
        monkeypatch.setenv("LOG_LEVEL", "debug")
        _, buf = _setup()
        assert logging.getLogger().level == logging.DEBUG

    def test_escalate(self):
        _setup(level=logging.INFO)
        jlog.escalate(1)
        assert logging.getLogger().level == logging.DEBUG
        jlog.escalate(1)
        assert logging.getLogger().level == jlog.TRACE
        jlog.escalate(5)  # clamped at TRACE
        assert logging.getLogger().level == jlog.TRACE


def test_levels_below_debug_map_to_bunyan_trace():
    from registrar_tpu.jlog import _bunyan_level

    assert _bunyan_level(5) == 10  # bunyan TRACE
